# Pass 5 -- whole-package static concurrency lint (AIKO6xx).
#
# The framework's worst production bugs are cross-thread races inside
# the actor fleet, not dataflow mistakes: `Pipeline.load()` once
# iterated the live stream dict while gateways routed ("dictionary
# changed size during iteration" under a 1,000-stream creation storm),
# and journal replay raced the forget flush.  Every one of those is a
# statically detectable shape, so this pass scans Python SOURCE (not
# definitions) and reports:
#
#   AIKO601  unsynchronized iteration of a container attribute that
#            another thread role mutates (the Pipeline.load() class --
#            fix: `list()` snapshot before iterating, or a shared lock)
#   AIKO602  check-then-act on a shared attribute across thread roles
#            without a lock (`if self.x: self.x.pop()` while another
#            role rebinds/mutates self.x)
#   AIKO603  blocking host call (actor_lint's _BLOCKING_* tables)
#            while holding a lock
#   AIKO604  lock-order inversion: a cycle in the per-class lock
#            acquire graph (nested `with` blocks, followed through
#            self-method calls)
#   AIKO605  mutable class-level default (class-attr dict/list/set
#            mutated through self and never rebound per-instance)
#
# Thread roles are inferred from the dispatch-registration call sites
# the runtime actually uses -- add_mailbox_handler / add_timer_handler
# / add_queue_handler / add_flatout_handler / add_message_handler /
# post_message("command") register onto the process event loop;
# threading.Thread(target=self.m) starts a dedicated worker thread --
# plus an explicit `# aiko: role=<name>` escape hatch on (or directly
# above) the `def` line.  Public methods are additionally
# "wire"-callable: another service (possibly on another thread, like
# the serving gateway reading `Pipeline.load()` per routing decision)
# may call them at any time.  Roles propagate through self-method
# calls, so a private helper inherits the roles of every caller.
#
# Two roles are POTENTIALLY CONCURRENT when they can run on different
# threads: everything registered on the event engine shares the one
# loop thread (mailbox/timer/pump/message never race each other), a
# worker thread races the loop and other workers, and "wire" races
# everything including itself.
#
# Findings integrate with the shared diagnostics registry, `# aiko:
# allow` statement suppression (any line of a multi-line statement),
# and a committed BASELINE file: pre-existing accepted findings are
# fingerprinted (code + file + class.method + attribute -- no line
# numbers, so unrelated edits don't churn it) and filtered out, while
# every NEW finding fails `aiko lint --code --strict`.  Stale baseline
# entries surface as AIKO600 info notes so they get expired.

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field
from pathlib import Path

from .actor_lint import (
    _BLOCKING_ATTRS, _BLOCKING_CALLS, _BLOCKING_MODULES,
    statement_suppressed)
from .diagnostics import AnalysisReport, Diagnostic

__all__ = [
    "run_code_pass", "role_map", "finding_fingerprint",
    "load_baseline", "apply_baseline", "write_baseline",
]

# dispatch-registration call sites -> role of the registered method.
# Everything here runs on the process event-loop thread; the role
# names stay distinct because they document INTENT (a timer racing a
# mailbox handler is impossible today, but the roles tell a reader
# which dispatch path a method belongs to).
_REGISTRAR_ROLE = {
    "add_mailbox_handler": "mailbox",
    "add_timer_handler": "timer",
    "add_queue_handler": "pump",
    "add_flatout_handler": "pump",
    "add_message_handler": "message",
}
# roles that share the single event-loop thread
_LOOP_AFFINE = frozenset({"mailbox", "timer", "pump", "message"})

_ROLE_COMMENT = re.compile(r"#\s*aiko:\s*role=([A-Za-z_:]+)")
_KNOWN_ROLES = frozenset(
    {"mailbox", "timer", "pump", "message", "worker", "wire", "none"})

# in-place container mutators (dict/list/set/deque vocabulary)
_MUTATORS = frozenset({
    "append", "appendleft", "add", "extend", "insert", "remove",
    "discard", "pop", "popleft", "popitem", "clear", "update",
    "setdefault",
})
# C-level copying calls: `list(self.x)` snapshots atomically (the GIL
# is never yielded mid-copy), so iterating the RESULT is safe
_SNAPSHOT_CALLS = frozenset(
    {"list", "tuple", "set", "frozenset", "dict", "sorted", "len",
     "sum", "min", "max", "any", "all"})
# reading calls that do not extend a check-then-act window
_SAFE_ATTR_CALLS = frozenset({"get", "items", "keys", "values", "copy"})

_BASES_FLEET = ("Actor", "Service", "Element", "Engine", "Gateway",
                "Keeper", "Worker", "Pipeline", "Manager", "Registrar",
                "Telemetry", "AutoPilot", "Autoscaler", "Journal",
                "Monitor", "Scheduler", "Producer", "Consumer",
                "Server", "Client", "Thread")


def _self_dotted(node) -> str | None:
    """Render an attribute chain rooted at `self` ("self.a.b" -> "a.b"),
    None for anything else."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name) and node.id == "self" and parts:
        return ".".join(reversed(parts))
    return None


def _dotted_name(node) -> str | None:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _lock_name(expr) -> str | None:
    """`with self._lock:` -> "_lock" when the attribute smells like a
    lock (name contains lock/mutex/cond/sem)."""
    dotted = _self_dotted(expr)
    if dotted is None:
        return None
    leaf = dotted.rsplit(".", 1)[-1].lower()
    if any(word in leaf for word in ("lock", "mutex", "cond", "sem")):
        return dotted
    return None


def _iterated_attr(expr) -> str | None:
    """The self-attribute a `for`/comprehension iterates LIVE:
    `self.streams`, `self.streams.values()|items()|keys()`.  A
    snapshot (`list(self.streams)`) is a Call to a builtin and
    resolves to None here -- that is the sanctioned discipline."""
    dotted = _self_dotted(expr)
    if dotted is not None:
        return dotted
    if (isinstance(expr, ast.Call) and not expr.args
            and not expr.keywords
            and isinstance(expr.func, ast.Attribute)
            and expr.func.attr in ("items", "values", "keys")):
        return _self_dotted(expr.func.value)
    return None


@dataclass
class _Access:
    kind: str                 # iterate | mutate | rebind | check
    attr: str
    method: str
    lineno: int
    locks: frozenset
    node: object = None
    detail: str = ""          # mutator name, check shape, ...


@dataclass
class _MethodFacts:
    name: str
    node: object
    roles: set = field(default_factory=set)
    accesses: list = field(default_factory=list)
    blocking: list = field(default_factory=list)   # (msg, node, locks)
    acquires: list = field(default_factory=list)   # (lock, held, node)
    self_calls: list = field(default_factory=list)  # (callee, held, node)


class _MethodWalker(ast.NodeVisitor):
    """One pass over a method body: attribute access map (read /
    write / iterate / delete with the lock set held at each site),
    blocking-under-lock sites, lock-acquire nesting, self-calls."""

    def __init__(self, facts: _MethodFacts):
        self.facts = facts
        self._held: list[str] = []

    # -- locks ---------------------------------------------------------

    def _locks(self) -> frozenset:
        return frozenset(self._held)

    def visit_With(self, node):
        acquired = []
        for item in node.items:
            self.visit(item.context_expr)
            lock = _lock_name(item.context_expr)
            if lock is not None:
                self.facts.acquires.append(
                    (lock, self._locks(), node))
                self._held.append(lock)
                acquired.append(lock)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self._held.pop()

    visit_AsyncWith = visit_With

    # -- iteration -----------------------------------------------------

    def _note_iterate(self, iter_expr, node):
        attr = _iterated_attr(iter_expr)
        if attr is not None:
            self.facts.accesses.append(_Access(
                "iterate", attr, self.facts.name, node.lineno,
                self._locks(), node))

    def visit_For(self, node):
        self._note_iterate(node.iter, node)
        self.generic_visit(node)

    visit_AsyncFor = visit_For

    def _visit_comprehension(self, node):
        for generator in node.generators:
            self._note_iterate(generator.iter, node)
        self.generic_visit(node)

    visit_ListComp = _visit_comprehension
    visit_SetComp = _visit_comprehension
    visit_DictComp = _visit_comprehension
    visit_GeneratorExp = _visit_comprehension

    # -- stores --------------------------------------------------------

    def _note_store(self, target, node, kind="mutate"):
        if isinstance(target, (ast.Tuple, ast.List)):
            for element in target.elts:
                self._note_store(element, node, kind)
            return
        if isinstance(target, ast.Starred):
            self._note_store(target.value, node, kind)
            return
        if isinstance(target, ast.Subscript):
            attr = _self_dotted(target.value)
            if attr is not None:
                self.facts.accesses.append(_Access(
                    "mutate", attr, self.facts.name, node.lineno,
                    self._locks(), node, detail="subscript"))
            return
        if isinstance(target, ast.Attribute):
            attr = _self_dotted(target)
            if attr is not None:
                self.facts.accesses.append(_Access(
                    "rebind", attr, self.facts.name, node.lineno,
                    self._locks(), node))

    def visit_Assign(self, node):
        for target in node.targets:
            self._note_store(target, node)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._note_store(node.target, node)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._note_store(node.target, node)
        self.generic_visit(node)

    def visit_Delete(self, node):
        for target in node.targets:
            if isinstance(target, ast.Subscript):
                attr = _self_dotted(target.value)
                if attr is not None:
                    self.facts.accesses.append(_Access(
                        "mutate", attr, self.facts.name, node.lineno,
                        self._locks(), node, detail="del"))
            elif isinstance(target, ast.Attribute):
                attr = _self_dotted(target)
                if attr is not None:
                    self.facts.accesses.append(_Access(
                        "rebind", attr, self.facts.name, node.lineno,
                        self._locks(), node, detail="del"))
        self.generic_visit(node)

    # -- calls ---------------------------------------------------------

    def visit_Call(self, node):
        func = node.func
        if isinstance(func, ast.Attribute):
            owner = _self_dotted(func.value)
            if owner is not None and func.attr in _MUTATORS:
                self.facts.accesses.append(_Access(
                    "mutate", owner, self.facts.name, node.lineno,
                    self._locks(), node, detail=func.attr))
            if (isinstance(func.value, ast.Name)
                    and func.value.id == "self"):
                self.facts.self_calls.append(
                    (func.attr, self._locks(), node))
        # blocking-call vocabulary shared with the AIKO301 actor pass.
        # Recorded with the LEXICAL lock set; the class-level rule adds
        # locks inherited from call sites (`_locked`-style helpers)
        # before deciding AIKO603.
        dotted = _dotted_name(func)
        message = None
        if dotted is not None:
            if dotted in _BLOCKING_CALLS:
                message = _BLOCKING_CALLS[dotted]
            else:
                root = dotted.split(".", 1)[0]
                if root in _BLOCKING_MODULES:
                    message = _BLOCKING_MODULES[root]
        if (message is None and isinstance(func, ast.Attribute)
                and func.attr in _BLOCKING_ATTRS):
            message = _BLOCKING_ATTRS[func.attr]
        if message is not None:
            self.facts.blocking.append(
                (message, node, self._locks()))
        self.generic_visit(node)

    # -- check-then-act ------------------------------------------------

    def visit_If(self, node):
        checked = {
            attr for attr in (
                _self_dotted(sub) for sub in ast.walk(node.test)
                if isinstance(sub, ast.Attribute))
            if attr is not None}
        if checked:
            used = self._dependent_uses(node.body, checked)
            for attr in sorted(used):
                self.facts.accesses.append(_Access(
                    "check", attr, self.facts.name, node.lineno,
                    self._locks(), node))
        self.generic_visit(node)

    def _dependent_uses(self, body, checked: set) -> set:
        """Attributes from `checked` that the if-body USES in a way
        that assumes the check still holds: subscript access, an
        in-place mutator, or a method call on the checked object."""
        used = set()
        for stmt in body:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Subscript):
                    attr = _self_dotted(sub.value)
                    if attr in checked:
                        used.add(attr)
                elif isinstance(sub, ast.Call):
                    func = sub.func
                    if (isinstance(func, ast.Attribute)
                            and func.attr not in _SAFE_ATTR_CALLS):
                        attr = _self_dotted(func.value)
                        if attr in checked:
                            used.add(attr)
        return used


class _ClassFacts:
    def __init__(self, node: ast.ClassDef, source_lines, path: str):
        self.node = node
        self.name = node.name
        self.path = path
        self.source_lines = source_lines
        self.methods: dict[str, _MethodFacts] = {}
        self.class_level_mutables: dict[str, ast.stmt] = {}
        self.bases = [
            (_dotted_name(base) or "") for base in node.bases]

        for stmt in node.body:
            if isinstance(stmt,
                          (ast.FunctionDef, ast.AsyncFunctionDef)):
                facts = _MethodFacts(stmt.name, stmt)
                _MethodWalker(facts).visit(stmt)
                self.methods[stmt.name] = facts
            elif isinstance(stmt, ast.Assign):
                for target in stmt.targets:
                    if (isinstance(target, ast.Name)
                            and _is_mutable_literal(stmt.value)):
                        self.class_level_mutables[target.id] = stmt

    # -- role inference ------------------------------------------------

    def infer_roles(self) -> None:
        explicit: dict[str, set] = {}
        for name, facts in self.methods.items():
            roles = self._explicit_roles(facts.node)
            if roles is not None:
                explicit[name] = roles
                facts.roles |= (roles - {"none"})

        # registration call sites, scanned across EVERY method body
        for facts in self.methods.values():
            for sub in ast.walk(facts.node):
                if not isinstance(sub, ast.Call):
                    continue
                self._roles_from_call(sub, explicit)

        # public surface: wire-callable from any thread
        for name, facts in self.methods.items():
            if name in explicit or name.startswith("_"):
                continue
            facts.roles.add("wire")

        # propagate caller roles through self-method calls (a private
        # helper runs on every thread that calls it)
        changed = True
        while changed:
            changed = False
            for facts in self.methods.values():
                if not facts.roles:
                    continue
                for callee, _, _ in facts.self_calls:
                    target = self.methods.get(callee)
                    if (target is None or callee in explicit
                            or callee.startswith("__")):
                        continue
                    merged = target.roles | facts.roles
                    if merged != target.roles:
                        target.roles = merged
                        changed = True

    def _explicit_roles(self, node) -> set | None:
        """`# aiko: role=<name>` on the def line or the line above it
        (comma/colon-separated for multi-role)."""
        for lineno in (node.lineno, node.lineno - 1):
            index = lineno - 1
            if not (0 <= index < len(self.source_lines)):
                continue
            match = _ROLE_COMMENT.search(self.source_lines[index])
            if match is None:
                continue
            names = {part for part in
                     re.split(r"[:+,]", match.group(1).lower())
                     if part}
            return {name for name in names if name in _KNOWN_ROLES} \
                or {"none"}
        return None

    def _roles_from_call(self, call: ast.Call, explicit: dict) -> None:
        func = call.func

        def assign(method_name: str | None, role: str):
            facts = self.methods.get(method_name or "")
            if facts is None or method_name in explicit:
                return
            facts.roles.add(role)

        if isinstance(func, ast.Attribute):
            role = _REGISTRAR_ROLE.get(func.attr)
            if role is not None and call.args:
                handler = call.args[0]
                if isinstance(handler, ast.Attribute):
                    assign(_self_dotted(handler), role)
                return
            if func.attr in ("post_message", "post_message_later"):
                if (call.args and isinstance(call.args[0], ast.Constant)
                        and isinstance(call.args[0].value, str)):
                    assign(call.args[0].value, "mailbox")
                return
        # threading.Thread(target=self.m) -- a dedicated worker thread
        # per target method
        name = _dotted_name(func) or ""
        if name.rsplit(".", 1)[-1] == "Thread":
            for keyword in call.keywords:
                if (keyword.arg == "target"
                        and isinstance(keyword.value, ast.Attribute)):
                    target = _self_dotted(keyword.value)
                    if target is not None and "." not in target:
                        assign(target, f"worker:{target}")

    def is_fleet_class(self) -> bool:
        """Only classes with a cross-thread surface are analyzed: actor
        fleet bases, or any inferred non-default role (a handler
        registration / worker-thread spawn inside the class)."""
        for base in self.bases:
            leaf = base.rsplit(".", 1)[-1]
            if any(leaf.endswith(word) for word in _BASES_FLEET):
                return True
        return any(
            role for facts in self.methods.values()
            for role in facts.roles if role != "wire")


def _is_mutable_literal(value) -> bool:
    if isinstance(value, (ast.Dict, ast.List, ast.Set)):
        return True
    return (isinstance(value, ast.Call)
            and isinstance(value.func, ast.Name)
            and value.func.id in ("dict", "list", "set")
            and not value.args and not value.keywords)


def _affinity(role: str) -> str:
    if role in _LOOP_AFFINE:
        return "loop"
    return role  # wire, worker:<target>


def _concurrent(role_a: str, role_b: str) -> bool:
    """Can these two roles run at the same instant on different
    threads?"""
    if role_a == "wire" or role_b == "wire":
        return True
    return _affinity(role_a) != _affinity(role_b)


def _roles_concurrent(roles_a, roles_b) -> tuple | None:
    for role_a in sorted(roles_a):
        for role_b in sorted(roles_b):
            if _concurrent(role_a, role_b):
                return (role_a, role_b)
    return None


# -- per-class rules ------------------------------------------------------


def _emit(report, code, cls: _ClassFacts, access_node, method: str,
          message: str, port: str) -> None:
    if statement_suppressed(cls.source_lines, access_node):
        return
    report.add(Diagnostic(
        code, message, definition=cls.name, element=method,
        port=port, source=cls.path))


def _inherited_locks(cls: _ClassFacts) -> dict:
    """Locks a method is ALWAYS called under: for a private method,
    the intersection of the lock sets held at every in-class call
    site (transitively).  `loop()` calling `_next_work_locked()` under
    `self._condition` protects the callee's accesses exactly like a
    lexical `with`.  Public methods inherit nothing -- an external
    caller holds no lock."""
    call_sites: dict[str, list] = {}
    for name, facts in cls.methods.items():
        for callee, held, _ in facts.self_calls:
            call_sites.setdefault(callee, []).append((name, held))

    inherited = {name: frozenset() for name in cls.methods}
    changed = True
    while changed:
        changed = False
        for name in cls.methods:
            if not name.startswith("_") or name.startswith("__"):
                continue
            sites = call_sites.get(name)
            if not sites:
                continue
            merged = None
            for caller, held in sites:
                effective = held | inherited.get(caller, frozenset())
                merged = (effective if merged is None
                          else merged & effective)
            if merged and merged != inherited[name]:
                inherited[name] = frozenset(merged)
                changed = True
    return inherited


def _check_class(report: AnalysisReport, cls: _ClassFacts) -> None:
    cls.infer_roles()
    if not cls.is_fleet_class():
        return
    inherited = _inherited_locks(cls)

    by_attr: dict[str, list[_Access]] = {}
    for facts in cls.methods.values():
        if facts.name.startswith("__"):
            continue  # construction/dunder: single-threaded by contract
        for access in facts.accesses:
            by_attr.setdefault(access.attr, []).append(access)

    roles_of = {name: facts.roles
                for name, facts in cls.methods.items()}

    def effective_locks(access: _Access) -> frozenset:
        return access.locks | inherited.get(access.method, frozenset())

    def hazards(access: _Access, kinds) -> list:
        """Sites of OTHER methods whose roles can run concurrently
        with `access` and are not protected by a common lock."""
        found = []
        for other in by_attr.get(access.attr, ()):
            if other.kind not in kinds:
                continue
            if other.method == access.method:
                continue
            pair = _roles_concurrent(
                roles_of.get(access.method, ()),
                roles_of.get(other.method, ()))
            if pair is None:
                continue
            if effective_locks(access) & effective_locks(other):
                continue  # both under one shared lock
            found.append((other, pair))
        return found

    # AIKO601 / AIKO602 ---------------------------------------------------
    for attr, accesses in sorted(by_attr.items()):
        for access in accesses:
            if access.kind == "iterate":
                racing = hazards(access, ("mutate",))
                if racing:
                    other, (role_a, role_b) = racing[0]
                    _emit(
                        report, "AIKO601", cls, access.node,
                        access.method,
                        f"{access.method}() line {access.lineno} "
                        f"[role {role_a}] iterates live `self.{attr}` "
                        f"while {other.method}() line {other.lineno} "
                        f"[role {role_b}] mutates it; snapshot with "
                        f"list(self.{attr.split('.', 1)[0]}...) before "
                        f"iterating, or hold one lock at both sites",
                        port=attr)
            elif access.kind == "check":
                racing = hazards(access, ("mutate", "rebind"))
                if racing:
                    other, (role_a, role_b) = racing[0]
                    _emit(
                        report, "AIKO602", cls, access.node,
                        access.method,
                        f"{access.method}() line {access.lineno} "
                        f"[role {role_a}] checks `self.{attr}` then "
                        f"acts on it, while {other.method}() line "
                        f"{other.lineno} [role {role_b}] "
                        f"{'rebinds' if other.kind == 'rebind' else 'mutates'}"
                        f" it; bind a local snapshot "
                        f"(`x = self.{attr}`) and use that, or hold "
                        f"one lock across check and act",
                        port=attr)

    # AIKO603: blocking call while holding a lock -------------------------
    for facts in cls.methods.values():
        for message, node, locks in facts.blocking:
            held = locks | inherited.get(facts.name, frozenset())
            if not held:
                continue
            _emit(
                report, "AIKO603", cls, node, facts.name,
                f"{facts.name}() line {node.lineno}: {message} -- "
                f"while holding {', '.join(sorted(held))}; move the "
                f"blocking call outside the critical section",
                port=";".join(sorted(held)))

    # AIKO604: lock-order inversion ---------------------------------------
    _check_lock_order(report, cls)

    # AIKO605: mutable class-level defaults -------------------------------
    for attr, stmt in sorted(cls.class_level_mutables.items()):
        mutated = [
            access for facts in cls.methods.values()
            for access in facts.accesses
            if access.attr == attr and access.kind == "mutate"]
        rebound = any(
            access.attr == attr and access.kind == "rebind"
            for facts in cls.methods.values()
            for access in facts.accesses)
        if mutated and not rebound:
            site = mutated[0]
            _emit(
                report, "AIKO605", cls, stmt, "<class>",
                f"class-level default `{attr}` (line {stmt.lineno}) is "
                f"mutated through self in {site.method}() line "
                f"{site.lineno} and never rebound per-instance: every "
                f"instance shares ONE container across threads; assign "
                f"it in __init__ instead",
                port=attr)


def _check_lock_order(report: AnalysisReport, cls: _ClassFacts) -> None:
    # locks each method EVENTUALLY acquires (direct + via self-calls)
    eventual: dict[str, set] = {
        name: {lock for lock, _, _ in facts.acquires}
        for name, facts in cls.methods.items()}
    changed = True
    while changed:
        changed = False
        for name, facts in cls.methods.items():
            for callee, _, _ in facts.self_calls:
                callee_locks = eventual.get(callee)
                if callee_locks and not callee_locks <= eventual[name]:
                    eventual[name] |= callee_locks
                    changed = True

    edges: dict[str, set] = {}
    provenance: dict[tuple, tuple] = {}

    def add_edge(held, lock, method, node):
        for holder in held:
            if holder == lock:
                continue
            edges.setdefault(holder, set()).add(lock)
            provenance.setdefault((holder, lock), (method, node))

    for name, facts in cls.methods.items():
        for lock, held, node in facts.acquires:
            add_edge(held, lock, name, node)
        for callee, held, node in facts.self_calls:
            if held:
                for lock in eventual.get(callee, ()):
                    add_edge(held, lock, name, node)

    # cycle detection over the small per-class lock graph
    seen_cycles = set()
    for start in sorted(edges):
        stack = [(start, [start])]
        while stack:
            node_name, path = stack.pop()
            for successor in sorted(edges.get(node_name, ())):
                if successor == start:
                    cycle = tuple(path)
                    pivot = cycle.index(min(cycle))
                    canonical = cycle[pivot:] + cycle[:pivot]
                    if canonical in seen_cycles:
                        continue
                    seen_cycles.add(canonical)
                    method, site = provenance[
                        (path[-1], start)]
                    _emit(
                        report, "AIKO604", cls, site, method,
                        f"lock-order inversion: "
                        f"{' -> '.join(canonical + (canonical[0],))} "
                        f"(edge closed in {method}() line "
                        f"{site.lineno}); acquire these locks in one "
                        f"global order",
                        port="->".join(canonical))
                elif successor not in path:
                    stack.append((successor, path + [successor]))


# -- module / package driver ----------------------------------------------


def _scan_source(report: AnalysisReport, text: str, path: str) -> None:
    try:
        tree = ast.parse(text)
    except SyntaxError as error:
        report.add(Diagnostic(
            "AIKO600", f"source does not parse: {error}", source=path))
        return
    source_lines = text.splitlines()
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            _check_class(report,
                         _ClassFacts(node, source_lines, path))


def _relative(path: Path, root: Path | None) -> str:
    try:
        if root is not None:
            return path.resolve().relative_to(
                root.resolve()).as_posix()
    except ValueError:
        pass
    return path.as_posix()


def run_code_pass(paths, root=None) -> AnalysisReport:
    """AIKO6xx concurrency lint over Python sources: files or
    directories (searched recursively for *.py, skipping __pycache__).
    Findings are deterministically ordered, so two runs over one tree
    render byte-identical reports."""
    root = Path(root) if root is not None else Path.cwd()
    files: dict[str, Path] = {}
    for entry in paths:
        entry = Path(entry)
        if entry.is_dir():
            candidates = sorted(entry.rglob("*.py"))
        else:
            candidates = [entry]
        for candidate in candidates:
            if "__pycache__" in candidate.parts:
                continue
            files[_relative(candidate, root)] = candidate

    report = AnalysisReport(passes_run=["code"])
    for label in sorted(files):
        path = files[label]
        try:
            text = path.read_text()
        except OSError as error:
            report.add(Diagnostic(
                "AIKO600", f"unreadable source: {error}", source=label))
            continue
        _scan_source(report, text, label)
    report.findings.sort(
        key=lambda d: (d.source, d.code, d.definition, d.element,
                       d.port, d.message))
    return report


def role_map(text: str, path: str = "<source>") -> dict:
    """{class: {method: sorted role list}} for one source text --
    the inference surface, exposed for tests and `aiko lint` users
    verifying an escape-hatch comment took effect."""
    tree = ast.parse(text)
    out: dict[str, dict] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            cls = _ClassFacts(node, text.splitlines(), path)
            cls.infer_roles()
            out[cls.name] = {
                name: sorted(facts.roles)
                for name, facts in cls.methods.items()}
    return out


# -- baseline -------------------------------------------------------------


def finding_fingerprint(diagnostic: Diagnostic) -> str:
    """Stable identity of one accepted finding: code + file +
    Class.method + attribute/lock detail.  Deliberately line-number
    free, so unrelated edits to the file do not churn the baseline."""
    return " ".join((
        diagnostic.code, diagnostic.source,
        f"{diagnostic.definition}.{diagnostic.element}",
        diagnostic.port or "-"))


def load_baseline(path) -> list:
    document = json.loads(Path(path).read_text())
    if not isinstance(document, dict) or "entries" not in document:
        raise ValueError(
            f"{path}: baseline must be an object with an 'entries' "
            f"list")
    return list(document["entries"])


def write_baseline(path, report: AnalysisReport) -> int:
    entries = sorted({
        finding_fingerprint(d) for d in report.findings
        if d.code != "AIKO600"})
    Path(path).write_text(json.dumps(
        {"version": 1, "entries": entries}, indent=2) + "\n")
    return len(entries)


def apply_baseline(report: AnalysisReport, entries) -> int:
    """Filter baselined findings out of `report` IN PLACE.  Matched
    entries are accepted pre-existing findings; every entry that no
    longer matches anything is STALE and surfaces as an AIKO600 info
    note (expire it by re-running with --update-baseline).  Returns
    the number of findings filtered."""
    accepted = set(entries)
    matched: set = set()
    kept = []
    for diagnostic in report.findings:
        fingerprint = finding_fingerprint(diagnostic)
        if diagnostic.code != "AIKO600" and fingerprint in accepted:
            matched.add(fingerprint)
            continue
        kept.append(diagnostic)
    filtered = len(report.findings) - len(kept)
    for stale in sorted(accepted - matched):
        kept.append(Diagnostic(
            "AIKO600",
            f"stale baseline entry (finding no longer produced): "
            f"{stale}; remove it or refresh with --update-baseline"))
    report.findings = kept
    return filtered
