# Cross-request prefix KV reuse: radix-style hash-chained block cache.
#
# Million-user chat traffic is dominated by shared prefixes (system
# prompts, few-shot templates, multi-turn history).  The paged pool
# already makes block ORDER irrelevant -- the block-table indirection
# (paged_decode_step's gather) means any request can point at any
# block -- so the only missing piece is an index from token content to
# block id.  This module provides it, SGLang-RadixAttention style but
# flattened to a hash CHAIN instead of a tree:
#
#   digest[0] = H(block_size | tokens[0:B])
#   digest[i] = H(digest[i-1] | tokens[i*B:(i+1)*B])
#
# A chain digest therefore commits to the ENTIRE prefix up to and
# including its block, so a single dict lookup per block walks the
# radix path: the longest cached prefix of a new prompt is the longest
# run of consecutive digest hits.  Hashing is process-stable blake2b
# (like federation.py's rendezvous md5 -- NEVER Python's salted
# hash()), so digests can cross process boundaries as gateway affinity
# hints and keeper snapshot keys.
#
# Sharing is copy-on-write by construction: cached blocks are only
# ever FULL blocks (every position written), a borrowing request's
# block table points at them read-only, and its own writes land in the
# freshly-allocated tail blocks.  Refcounts make eviction safe:
#
#   refcount > 0   block is referenced by a live slot: unevictable
#   refcount == 0  block sits in an LRU second-chance tier -- still
#                  indexed, reclaimed ONLY when the pool runs dry,
#                  BEFORE admission defers or the preemption ladder
#                  fires (a cache must never cause a preemption)

from __future__ import annotations

import hashlib
from collections import OrderedDict

from ..analyze.grammar import DirectiveGrammar, Field, GrammarError
from .blocks import BlockManager

__all__ = ["PREFIX_GRAMMAR", "PrefixCache", "PrefixPolicy",
           "chain_hashes", "prefix_head"]

# gateway EC shares mirror at most this many chain-head digests: the
# affinity summary is a compact routing hint, not the cache index
PREFIX_HEADS_CAP = 32

PREFIX_GRAMMAR = DirectiveGrammar(
    "prefix-cache policy",
    options={
        "prefix_cache": Field("str", choices=("on", "off")),
        "min_prefix_blocks": Field("int", minimum=1),
        "cache_blocks": Field("int", minimum=1),
        "affinity_weight": Field("float", minimum=0.0),
    })


class PrefixPolicy:
    """Parsed prefix-cache spec (rule code AIKO411).  Two scopes share
    one grammar, mirroring the checkpoint policy's split:

      engine (LMGenerate `prefix_policy` parameter):
        min_prefix_blocks=  smallest cached run worth borrowing (tiny
                            hits pay table-rewrite cost for nothing)
        cache_blocks=       cap on the refcount-0 cached tier (0 /
                            absent = bounded only by the pool)

      gateway (`prefix_policy` parameter):
        affinity_weight=    load-score discount for a replica already
                            holding the stream's prefix

    `prefix_cache=on|off` is legal on both: one switch arms/disarms
    the whole vertical (off = behavior identical to pre-prefix
    deployments, the A/B control arm)."""

    __slots__ = ("enabled", "min_prefix_blocks", "cache_blocks",
                 "affinity_weight", "present", "spec")

    def __init__(self):
        self.enabled = True
        self.min_prefix_blocks = 1
        self.cache_blocks = 0             # 0 = pool-bounded tier
        self.affinity_weight = 1.0
        self.present: set = set()
        self.spec = ""

    @classmethod
    def parse(cls, spec) -> "PrefixPolicy":
        """Parse a spec (directive string, dict of the same keys, or
        None/"" for all defaults)."""
        policy = cls()
        if spec is None or spec == "" or spec is True:
            return policy
        if isinstance(spec, PrefixPolicy):
            return spec
        parsed = PREFIX_GRAMMAR.parse(spec)
        if not isinstance(spec, dict):
            policy.spec = str(spec)
        for key, value in parsed.options.items():
            if key == "prefix_cache":
                policy.enabled = value == "on"
            else:
                setattr(policy, key, value)
            policy.present.add(key)
        return policy

    def validate_gateway(self) -> None:
        """A gateway spec weights routing; the cache-shape knobs
        belong to the replica that owns the pool."""
        engine_side = self.present & {"min_prefix_blocks",
                                      "cache_blocks"}
        if engine_side:
            raise GrammarError(
                f"prefix-cache policy: {sorted(engine_side)} are "
                f"engine-side directives; a gateway spec carries "
                f"prefix_cache/affinity_weight only")

    def validate_engine(self) -> None:
        if "affinity_weight" in self.present:
            raise GrammarError(
                "prefix-cache policy: affinity_weight is a "
                "gateway-side directive (routing score); an engine "
                "spec carries prefix_cache/min_prefix_blocks/"
                "cache_blocks")

    def __repr__(self):
        return (f"PrefixPolicy(enabled={self.enabled}, "
                f"min_prefix_blocks={self.min_prefix_blocks}, "
                f"cache_blocks={self.cache_blocks}, "
                f"affinity_weight={self.affinity_weight})")


def chain_hashes(tokens, block_size: int) -> list:
    """Hex chain digests for every FULL block of `tokens`, in chain
    order.  Deterministic across processes and runs: blake2b over the
    parent digest plus the block's int32 token bytes, seeded with the
    block size (a 16-token block must never collide with two 8-token
    blocks holding the same ids)."""
    import numpy as np

    tokens = np.asarray(tokens, dtype=np.int32).reshape(-1)
    block_size = int(block_size)
    digests = []
    parent = b"aiko-prefix:%d" % block_size
    for start in range(0, tokens.size - block_size + 1, block_size):
        digest = hashlib.blake2b(
            parent + tokens[start:start + block_size].tobytes(),
            digest_size=16)
        parent = digest.digest()
        digests.append(digest.hexdigest())
    return digests


def prefix_head(tokens, block_size: int) -> str | None:
    """The CHAIN HEAD digest (first full block) of a prompt, or None
    when the prompt cannot fill one block.  This is the compact
    affinity hint clients / gateways exchange: two prompts sharing a
    system preamble of >= block_size tokens share a head."""
    import numpy as np

    first = np.asarray(tokens, dtype=np.int32).reshape(-1)[:block_size]
    hashes = chain_hashes(first, block_size)
    return hashes[0] if hashes else None


class PrefixCache:
    """Refcounted content index over a BlockManager's pool.

    The manager keeps owning allocation; this class tracks which
    allocated blocks are REGISTERED (content-addressed by chain
    digest) and how many live slots reference each.  All bookkeeping
    is O(1) per block on the event loop.

    Invariant (tested): `manager.free_count + cached + active`
    reconciles to `manager.capacity`, where cached = refcount-0
    registered blocks and active = every block a slot references
    (shared or private)."""

    def __init__(self, manager: BlockManager, cache_blocks: int = 0):
        self.manager = manager
        self.cache_blocks = int(cache_blocks)
        self._entries: dict = {}          # digest -> block id
        self._digest_of: dict = {}        # block id -> digest
        self._refs: dict = {}             # block id -> live references
        self._depth: dict = {}            # block id -> chain index
        self._lru: OrderedDict = OrderedDict()  # refcount-0 blocks
        self.hits = 0                     # acquisitions with >= 1 block
        self.partial_hits = 0             # hit shorter than the chain
        self.blocks_shared = 0            # total blocks borrowed
        self.evictions = 0                # cached blocks reclaimed

    # -- inventory -----------------------------------------------------

    @property
    def cached_count(self) -> int:
        """Refcount-0 registered blocks (the reclaimable tier)."""
        return len(self._lru)

    @property
    def shared_count(self) -> int:
        """Registered blocks currently referenced by >= 1 slot."""
        return len(self._refs) - len(self._lru)

    def heads(self, cap: int = PREFIX_HEADS_CAP) -> list:
        """Chain-HEAD digests (depth 0) currently resident, newest
        registrations last, capped -- the gateway affinity summary."""
        found = [self._digest_of[block] for block, depth
                 in self._depth.items() if depth == 0]
        return found[-cap:]

    def lookup(self, hashes) -> int:
        """Longest resident prefix of a digest chain, in blocks --
        WITHOUT acquiring (the gateway-side / probe view)."""
        return len(self.resident_blocks(hashes))

    def resident_blocks(self, hashes) -> list:
        """Block ids of the longest resident prefix of a digest chain,
        in chain order, WITHOUT acquiring.  The snapshot-export path:
        the caller must copy the KV out (offer_pool_blocks gathers at
        call time) before yielding back to the event loop, since an
        unreferenced block can be evicted by any later allocation."""
        blocks = []
        for digest in hashes:
            block = self._entries.get(digest)
            if block is None:
                break
            blocks.append(block)
        return blocks

    # -- borrow / return -----------------------------------------------

    def acquire(self, hashes) -> list:
        """Borrow the longest resident prefix of `hashes`: increments
        each matched block's refcount (pulling refcount-0 blocks out
        of the LRU tier) and returns the block ids in chain order.
        The caller owns releasing exactly these blocks."""
        taken = []
        for digest in hashes:
            block = self._entries.get(digest)
            if block is None:
                break
            if self._refs[block] == 0:
                self._lru.pop(block, None)
            self._refs[block] += 1
            taken.append(block)
        if taken:
            self.hits += 1
            self.blocks_shared += len(taken)
            if len(taken) < len(hashes):
                self.partial_hits += 1
        return taken

    def release(self, blocks) -> None:
        """Return a slot's blocks: registered blocks decref (hitting
        zero parks them at the LRU tail -- still indexed, reclaimable);
        unregistered (private tail) blocks go straight back to the
        manager's free list."""
        private = []
        for block in blocks:
            block = int(block)
            if block in self._refs:
                self._refs[block] -= 1
                if self._refs[block] < 0:
                    raise ValueError(
                        f"prefix block {block} released more times "
                        f"than acquired")
                if self._refs[block] == 0:
                    self._lru[block] = True
                    self._lru.move_to_end(block)
            else:
                private.append(block)
        if private:
            self.manager.free(private)
        self._trim()

    # -- registration ---------------------------------------------------

    def register(self, hashes, blocks, depth: int = 0,
                 refcount: int = 1) -> list:
        """Index freshly-written FULL blocks under their chain digests
        with the given starting refcount (1 = the writing slot still
        references them; 0 = parked straight into the cached tier, the
        keeper-import path).  `depth` is the chain index of the FIRST
        digest (a slot that borrowed `n` cached blocks registers its
        own blocks from depth n).  A digest that is ALREADY indexed
        keeps its existing block -- the duplicate block stays private
        to the caller (refcount 1) or is freed (refcount 0), never
        aliased.  Returns the blocks actually indexed."""
        indexed = []
        freed = []
        for offset, (digest, block) in enumerate(zip(hashes, blocks)):
            block = int(block)
            if digest in self._entries or block in self._refs:
                # lost the registration race (or re-registering after
                # preemption): keep the first writer's copy
                if refcount == 0 and block not in self._refs:
                    freed.append(block)
                continue
            self._entries[digest] = block
            self._digest_of[block] = digest
            self._refs[block] = refcount
            self._depth[block] = depth + offset
            if refcount == 0:
                self._lru[block] = True
                self._lru.move_to_end(block)
            indexed.append(block)
        if freed:
            self.manager.free(freed)
        self._trim()
        return indexed

    # -- allocation with second-chance reclaim --------------------------

    def allocate(self, count: int) -> list | None:
        """All-or-nothing allocation that reclaims the LRU cached tier
        before giving up: cache pressure must never cause a deferral
        or preemption the cold system would not have had."""
        granted = self.manager.allocate(count)
        while granted is None and self._lru:
            self._evict_one()
            granted = self.manager.allocate(count)
        return granted

    def _evict_one(self) -> None:
        block, _ = self._lru.popitem(last=False)   # LRU head
        self._forget(block)
        self.manager.free([block])
        self.evictions += 1

    def _forget(self, block: int) -> None:
        digest = self._digest_of.pop(block)
        del self._entries[digest]
        del self._refs[block]
        del self._depth[block]

    def _trim(self) -> None:
        """Enforce the policy's cached-tier cap (cache_blocks > 0)."""
        if self.cache_blocks > 0:
            while len(self._lru) > self.cache_blocks:
                self._evict_one()

    def drop(self) -> int:
        """Reclaim the whole refcount-0 tier (tests / drain); returns
        the number of blocks returned to the manager."""
        dropped = 0
        while self._lru:
            self._evict_one()
            dropped += 1
        return dropped
