# DecodeEngine: slot-based continuous batching over paged KV.
#
# The vLLM-shaped serving core the ROADMAP names (open item #2): a
# fixed-arity array of decode SLOTS share one paged KV pool; new
# requests are admitted at prefill boundaries into free slots,
# finished sequences (EOS / max_new) free their slot and blocks
# immediately, and every engine step runs ONE jit-compiled decode step
# over all slots (models/transformer.py paged_decode_step) with
# inactive slots masked onto the trash block -- so after warmup an
# arbitrary admission/eviction sequence triggers ZERO recompiles, the
# same shape-stability trick as the micro-batch scheduler's
# zero-filler group concat.
#
# Scheduling policy (deliberately boring and deterministic):
#   - admission is FIFO; a request that cannot get its prompt blocks
#     defers (decode.deferred_admissions counts it) -- no head-of-line
#     skipping, so caller-observed ordering is reproducible;
#   - KV blocks are allocated LAZILY one block at a time as a slot's
#     cursor crosses a block boundary (the paged-KV win: admitting on
#     prompt cost instead of reserving prompt+max_new up front);
#   - on pool exhaustion the YOUNGEST active slot is preempted
#     (blocks freed, request requeued at the FRONT for a full
#     re-prefill) so the oldest slot always progresses -- no livelock;
#     greedy decode is deterministic, so a preempted request's
#     regenerated tokens are identical and `emitted_upto` dedupes its
#     token stream.
#
# Everything here runs on the event loop (host bookkeeping is a few
# numpy writes per step); the device work is the one fused step call.

from __future__ import annotations

import time

from collections import deque
from dataclasses import dataclass, field

import numpy as np

from ..models import init_paged_pool, paged_decode_step, paged_prefill
from ..utils import get_logger
from ..utils.padding import bucket_length
from .blocks import TRASH_BLOCK, BlockManager

__all__ = ["DecodeEngine", "Completion", "StepReport"]

_LOGGER = get_logger("decode_engine")


@dataclass
class _Request:
    request_id: object
    prompt: np.ndarray            # (true_len,) int32, exact tokens
    max_new: int
    submitted_at: float
    generated: list = field(default_factory=list)
    emitted_upto: int = 0         # token offsets already surfaced
    admitted_at: float | None = None
    first_token_at: float | None = None
    decode_steps: int = 0
    preemptions: int = 0
    deferred: bool = False        # counted at most once per request


@dataclass
class Completion:
    request_id: object
    tokens: np.ndarray            # (max_new,) int32 (EOS-padded)
    stats: dict


@dataclass
class StepReport:
    completions: list = field(default_factory=list)
    # (request_id, offset, token_id) newly surfaced this step, in
    # decode order -- the element's token-streaming feed
    emitted: list = field(default_factory=list)
    admitted: int = 0
    active: int = 0


class _Slot:
    __slots__ = ("request", "blocks", "seq", "true_len")

    def __init__(self, request: _Request, blocks: list, seq: int,
                 true_len: int):
        self.request = request
        self.blocks = blocks
        self.seq = seq            # admission order; preemption victims
        self.true_len = true_len  # are chosen youngest (max seq) first


def _jit_cache_size() -> int:
    return (paged_prefill._cache_size()
            + paged_decode_step._cache_size())


class DecodeEngine:
    """Continuous-batching greedy decode over one transformer.

    Shapes fixed at construction: `decode_slots` slots, a pool of
    `kv_blocks` blocks of `kv_block_size` positions, and block tables
    wide enough for `max_context` positions per slot.  Outputs are
    bit-identical to the closed-batch generate() path for the same
    prompt tokens (tests/test_decode.py proves it).
    """

    def __init__(self, params, config, *, decode_slots: int = 4,
                 kv_block_size: int = 16, kv_blocks: int | None = None,
                 max_context: int | None = None, eos_id: int | None = None,
                 registry=None):
        if decode_slots < 1:
            raise ValueError(f"decode_slots must be >= 1, "
                             f"got {decode_slots}")
        self.params = params
        self.config = config
        self.slots_n = int(decode_slots)
        self.eos_id = None if eos_id is None else int(eos_id)
        max_context = int(max_context or config.max_seq_len)
        self.max_blocks = -(-max_context // int(kv_block_size))
        self.max_context = self.max_blocks * int(kv_block_size)
        if kv_blocks is None:
            # full reservation: every slot can grow to max_context, so
            # preemption never fires; shrink kv_blocks to oversubscribe
            kv_blocks = self.slots_n * self.max_blocks + 1
        self.blocks = BlockManager(int(kv_blocks), int(kv_block_size))
        self.pool = init_paged_pool(config, self.blocks.num_blocks,
                                    self.blocks.block_size)
        self.tables = np.full((self.slots_n, self.max_blocks),
                              TRASH_BLOCK, np.int32)
        self.positions = np.zeros((self.slots_n,), np.int32)
        self.last_tokens = np.zeros((self.slots_n, 1), np.int32)
        self.slots: list[_Slot | None] = [None] * self.slots_n
        self.waiting: deque[_Request] = deque()
        self._admission_seq = 0
        self._registry = registry
        self.counters = {"admitted": 0, "completed": 0, "preempted": 0,
                         "deferred_admissions": 0, "cancelled": 0,
                         "compiles": 0}
        self._update_gauges()

    # -- submission --------------------------------------------------------

    def _bucket(self, true_len: int) -> int:
        """Prompt prefill bucket: power-of-two padding rounded up to a
        block multiple, so the per-bucket prefill executable count stays
        logarithmic and block scatter is exact.  Clamped to max_context
        (itself a block multiple): a prompt whose pow2 round-up
        overshoots a non-pow2 max_context still fits — prefill works at
        any block-multiple length — and must not be rejected."""
        block = self.blocks.block_size
        padded = bucket_length(true_len, minimum=block)
        return min(-(-padded // block) * block, self.max_context)

    def submit(self, request_id, prompt_tokens, max_new_tokens: int):
        prompt = np.asarray(prompt_tokens, np.int32).reshape(-1)
        max_new = int(max_new_tokens)
        if prompt.size < 1:
            raise ValueError(f"{request_id}: empty prompt")
        if max_new < 1:
            raise ValueError(f"{request_id}: max_new_tokens must be >= 1")
        worst = max(self._bucket(prompt.size), prompt.size + max_new)
        if worst > self.max_context:
            raise ValueError(
                f"{request_id}: prompt {prompt.size} + max_new "
                f"{max_new} exceeds max_context {self.max_context}")
        if self.blocks.blocks_for(worst) > self.blocks.capacity:
            raise ValueError(
                f"{request_id}: needs {self.blocks.blocks_for(worst)} "
                f"KV blocks but the pool only has "
                f"{self.blocks.capacity}; raise kv_blocks")
        self.waiting.append(_Request(
            request_id=request_id, prompt=prompt, max_new=max_new,
            submitted_at=time.perf_counter()))
        self._update_gauges()

    def cancel(self, predicate) -> int:
        """Drop every request whose request_id satisfies `predicate`
        (waiting or mid-decode; a cancelled slot frees immediately).
        Returns the number cancelled."""
        cancelled = 0
        kept = deque()
        for request in self.waiting:
            if predicate(request.request_id):
                cancelled += 1
            else:
                kept.append(request)
        self.waiting = kept
        for index, slot in enumerate(self.slots):
            if slot is not None and predicate(slot.request.request_id):
                self._release_slot(index)
                cancelled += 1
        if cancelled:
            self.counters["cancelled"] += cancelled
            self._bump("decode.cancelled", cancelled)
            self._update_gauges()
        return cancelled

    def has_work(self) -> bool:
        return bool(self.waiting) or any(
            slot is not None for slot in self.slots)

    # -- the engine step ---------------------------------------------------

    def step(self) -> StepReport:
        """One engine tick: admit waiting requests into free slots at
        the prefill boundary, grow/preempt block allocations, then run
        ONE fused decode step over all slots."""
        report = StepReport()
        self._admit(report)
        active = [index for index, slot in enumerate(self.slots)
                  if slot is not None]
        if not active:
            self._update_gauges()
            report.active = 0
            return report
        self._grow_or_preempt()
        active = [index for index, slot in enumerate(self.slots)
                  if slot is not None]
        report.active = len(active)
        if not active:
            self._update_gauges()
            return report
        write_blocks = np.zeros((self.slots_n,), np.int32)
        write_offsets = np.zeros((self.slots_n,), np.int32)
        for index in active:
            position = int(self.positions[index])
            block_index = position // self.blocks.block_size
            write_blocks[index] = self.slots[index].blocks[block_index]
            write_offsets[index] = position % self.blocks.block_size
        before = _jit_cache_size()
        self.pool, next_tokens = paged_decode_step(
            self.params, self.config, self.pool, self.tables,
            self.positions, self.last_tokens, write_blocks,
            write_offsets)
        self._note_compiles(_jit_cache_size() - before)
        next_tokens = np.asarray(next_tokens)
        for index in active:
            slot = self.slots[index]
            request = slot.request
            token = int(next_tokens[index, 0])
            self.positions[index] += 1
            self.last_tokens[index, 0] = token
            request.generated.append(token)
            request.decode_steps += 1
            self._surface(report, request)
            if self._finished(request):
                report.completions.append(self._complete(index))
        self._update_gauges()
        return report

    # -- admission / prefill ----------------------------------------------

    def _admit(self, report: StepReport) -> None:
        while self.waiting:
            free = [index for index, slot in enumerate(self.slots)
                    if slot is None]
            if not free:
                return
            request = self.waiting[0]
            true_len = int(request.prompt.size)
            bucket = self._bucket(true_len)
            needed = self.blocks.blocks_for(bucket)
            granted = self.blocks.allocate(needed)
            if granted is None:
                # pool exhausted: admission DEFERS (FIFO order kept);
                # completions free blocks, so the queue always drains.
                # Counted once per REQUEST, not per blocked tick.
                if not request.deferred:
                    request.deferred = True
                    self.counters["deferred_admissions"] += 1
                    self._bump("decode.deferred_admissions", 1)
                return
            self.waiting.popleft()
            index = free[0]
            slot = _Slot(request, granted, self._admission_seq, true_len)
            self._admission_seq += 1
            self.slots[index] = slot
            self.tables[index, :] = TRASH_BLOCK
            self.tables[index, :needed] = granted
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :true_len] = request.prompt
            # a preempted request's RE-admission keeps first-attempt
            # timestamps: the caller saw its first token back then, so
            # ttft/queue_wait/prefill stats must not absorb the retry
            if request.admitted_at is None:
                request.admitted_at = time.perf_counter()
            before = _jit_cache_size()
            self.pool, first = paged_prefill(
                self.params, self.config, self.pool, padded,
                self.tables[index], np.int32(true_len))
            self._note_compiles(_jit_cache_size() - before)
            first = int(first)
            if request.first_token_at is None:
                request.first_token_at = time.perf_counter()
            request.generated.append(first)
            self.positions[index] = true_len
            self.last_tokens[index, 0] = first
            self.counters["admitted"] += 1
            report.admitted += 1
            self._bump("decode.admitted", 1)
            self._surface(report, request)
            if self._finished(request):
                report.completions.append(self._complete(index))

    # -- block growth / preemption ----------------------------------------

    def _grow_or_preempt(self) -> None:
        """Ensure every active slot owns the block its next write
        position lands in; on exhaustion preempt the youngest slot so
        the oldest always progresses (no livelock)."""
        order = sorted(
            (index for index, slot in enumerate(self.slots)
             if slot is not None),
            key=lambda index: self.slots[index].seq)
        for index in order:
            slot = self.slots[index]
            if slot is None:
                continue  # preempted below while growing an older slot
            needed = (int(self.positions[index])
                      // self.blocks.block_size) + 1
            while len(slot.blocks) < needed:
                granted = self.blocks.allocate(1)
                if granted is not None:
                    slot.blocks.extend(granted)
                    self.tables[index, len(slot.blocks) - 1] = granted[0]
                    continue
                victim = max(
                    (other for other in range(self.slots_n)
                     if self.slots[other] is not None),
                    key=lambda other: self.slots[other].seq)
                self._preempt(victim)
                if victim == index:
                    break  # this slot itself was the youngest

    def _preempt(self, index: int) -> None:
        slot = self.slots[index]
        request = slot.request
        _LOGGER.info("preempting slot %d (%r) after %d tokens: pool "
                     "exhausted", index, request.request_id,
                     len(request.generated))
        request.preemptions += 1
        # full recompute on re-admission: greedy decode regenerates the
        # SAME tokens, and emitted_upto keeps the stream from repeating
        request.generated = []
        request.decode_steps = 0
        self._release_slot(index)
        self.waiting.appendleft(request)
        self.counters["preempted"] += 1
        self._bump("decode.preempted", 1)

    def _release_slot(self, index: int) -> None:
        slot = self.slots[index]
        self.blocks.free(slot.blocks)
        self.slots[index] = None
        self.tables[index, :] = TRASH_BLOCK
        self.positions[index] = 0
        self.last_tokens[index, 0] = 0

    # -- completion --------------------------------------------------------

    def _finished(self, request: _Request) -> bool:
        if len(request.generated) >= request.max_new:
            return True
        return (self.eos_id is not None
                and request.generated[-1] == self.eos_id)

    def _surface(self, report: StepReport, request: _Request) -> None:
        while request.emitted_upto < len(request.generated):
            offset = request.emitted_upto
            report.emitted.append(
                (request.request_id, offset, request.generated[offset]))
            request.emitted_upto = offset + 1

    def _complete(self, index: int) -> Completion:
        slot = self.slots[index]
        request = slot.request
        now = time.perf_counter()
        pad = self.eos_id if self.eos_id is not None else 0
        tokens = np.full((request.max_new,), pad, np.int32)
        tokens[:len(request.generated)] = request.generated
        self._release_slot(index)
        self.counters["completed"] += 1
        self._bump("decode.completed", 1)
        admitted_at = request.admitted_at or now
        first_at = request.first_token_at or now
        stats = {
            "queue_wait_s": admitted_at - request.submitted_at,
            "prefill_s": first_at - admitted_at,
            "ttft_s": first_at - request.submitted_at,
            "decode_steps": request.decode_steps,
            "preemptions": request.preemptions,
            "total_s": now - request.submitted_at,
            "tokens": len(request.generated),
        }
        if self._registry is not None:
            self._registry.histogram("decode.queue_wait_s").record(
                stats["queue_wait_s"])
            self._registry.histogram("decode.prefill_s").record(
                stats["prefill_s"])
            self._registry.histogram("decode.ttft_s").record(
                stats["ttft_s"])
            self._registry.histogram("decode.total_s").record(
                stats["total_s"])
            self._registry.histogram("decode.steps").record(
                stats["decode_steps"])
        return Completion(request.request_id, tokens, stats)

    # -- observability -----------------------------------------------------

    @property
    def compile_count(self) -> int:
        """Jit-cache signatures THIS engine's calls compiled (prefill
        buckets + the one decode step).  The zero-recompile acceptance
        assertion reads deltas of this across an admit/evict storm."""
        return self.counters["compiles"]

    def _note_compiles(self, delta: int) -> None:
        if delta > 0:
            self.counters["compiles"] += delta
            self._bump("decode.compiles", delta)

    def _bump(self, name: str, amount: int) -> None:
        if self._registry is not None:
            self._registry.counter(name).inc(amount)

    def _update_gauges(self) -> None:
        if self._registry is None:
            return
        self._registry.gauge("decode.active_slots").set(
            sum(1 for slot in self.slots if slot is not None))
        self._registry.gauge("decode.free_blocks").set(
            self.blocks.free_count)
        self._registry.gauge("decode.waiting").set(len(self.waiting))

    def stats(self) -> dict:
        return {
            "active_slots": sum(1 for slot in self.slots
                                if slot is not None),
            "free_blocks": self.blocks.free_count,
            "waiting": len(self.waiting),
            "slots": self.slots_n,
            "blocks": self.blocks.capacity,
            "block_size": self.blocks.block_size,
            **self.counters,
        }
