# DecodeEngine: slot-based continuous batching over paged KV.
#
# The vLLM-shaped serving core the ROADMAP names (open item #2): a
# fixed-arity array of decode SLOTS share one paged KV pool; new
# requests are admitted at prefill boundaries into free slots,
# finished sequences (EOS / max_new) free their slot and blocks
# immediately, and every engine step runs ONE jit-compiled decode step
# over all slots (models/transformer.py paged_decode_step) with
# inactive slots masked onto the trash block -- so after warmup an
# arbitrary admission/eviction sequence triggers ZERO recompiles, the
# same shape-stability trick as the micro-batch scheduler's
# zero-filler group concat.
#
# Scheduling policy (deliberately boring and deterministic):
#   - admission is FIFO; a request that cannot get its prompt blocks
#     defers (decode.deferred_admissions counts it) -- no head-of-line
#     skipping, so caller-observed ordering is reproducible;
#   - KV blocks are allocated LAZILY one block at a time as a slot's
#     cursor crosses a block boundary (the paged-KV win: admitting on
#     prompt cost instead of reserving prompt+max_new up front);
#   - on pool exhaustion the YOUNGEST active slot is preempted
#     (blocks freed, request requeued at the FRONT for a full
#     re-prefill) so the oldest slot always progresses -- no livelock;
#     greedy decode is deterministic, so a preempted request's
#     regenerated tokens are identical and `emitted_upto` dedupes its
#     token stream.  A slot preempted MID-CHUNKED-PREFILL discards its
#     partially written blocks back to the free list the same way.
#
# Two kernel-floor lifts ride the same slot machinery (ROADMAP #3):
#   - CHUNKED PREFILL (prefill_chunk_size): instead of one monolithic
#     per-bucket prefill call that convoys every co-scheduled decode
#     slot for the whole prompt, a prefilling slot consumes its prompt
#     `prefill_chunk_size` tokens per engine tick (paged_prefill_chunk
#     attends to the already-written KV blocks of earlier chunks), so
#     decode steps interleave with prefill progress
#     (decode.chunk_interleaves counts ticks where both ran);
#   - GREEDY-EXACT SPECULATIVE DECODING (draft_params/draft_config/
#     spec_k): a small draft proposes k tokens per slot, the target
#     verifies all k+1 window positions in ONE batched forward
#     (paged_verify_step) and accepts the longest greedy-matching
#     prefix -- the weight stream that floors small-batch decode is
#     read once per k+1 positions instead of once per token, while
#     emitted tokens stay bit-identical to plain greedy decode.  The
#     draft keeps its own fully-reserved paged pool with static
#     per-slot block rows, so speculation never touches the target
#     pool's allocation/preemption logic.
#
# Everything here runs on the event loop (host bookkeeping is a few
# numpy writes per step); the device work is the fused step calls.

from __future__ import annotations

import time

from collections import deque
from dataclasses import dataclass, field

import numpy as np

from ..models import (
    init_paged_pool, paged_decode_step, paged_prefill,
    paged_prefill_chunk, paged_verify_step)
from ..utils import get_logger
from ..utils.padding import bucket_length
from .blocks import TRASH_BLOCK, BlockManager
from .prefix import PrefixCache, PrefixPolicy, chain_hashes

__all__ = ["DecodeEngine", "Completion", "StepReport"]

_LOGGER = get_logger("decode_engine")


@dataclass
class _Request:
    request_id: object
    prompt: np.ndarray            # (true_len,) int32, exact tokens
    max_new: int
    submitted_at: float
    generated: list = field(default_factory=list)
    emitted_upto: int = 0         # token offsets already surfaced
    admitted_at: float | None = None
    first_token_at: float | None = None
    decode_steps: int = 0
    preemptions: int = 0
    deferred: bool = False        # counted at most once per request


@dataclass
class Completion:
    request_id: object
    tokens: np.ndarray            # (max_new,) int32 (EOS-padded)
    stats: dict


@dataclass
class StepReport:
    completions: list = field(default_factory=list)
    # (request_id, offset, token_id) newly surfaced this step, in
    # decode order -- the element's token-streaming feed
    emitted: list = field(default_factory=list)
    admitted: int = 0
    active: int = 0


class _Slot:
    __slots__ = ("request", "blocks", "seq", "true_len", "bucket",
                 "padded", "prefill_pos", "draft_pending", "shared",
                 "hashes")

    def __init__(self, request: _Request, blocks: list, seq: int,
                 true_len: int, bucket: int, padded: np.ndarray):
        self.request = request
        self.blocks = blocks
        self.seq = seq            # admission order; preemption victims
        self.true_len = true_len  # are chosen youngest (max seq) first
        self.bucket = bucket
        self.padded = padded      # (bucket,) right-padded prompt
        self.prefill_pos = 0      # prompt tokens already written
        self.draft_pending = []   # emitted tokens the draft hasn't seen
        self.shared = 0           # leading blocks borrowed from the
        self.hashes = None        # prefix cache, + their digest chain

    @property
    def prefilling(self) -> bool:
        return self.prefill_pos < self.true_len


def _jit_cache_size() -> int:
    return (paged_prefill._cache_size()
            + paged_decode_step._cache_size()
            + paged_prefill_chunk._cache_size()
            + paged_verify_step._cache_size())


class DecodeEngine:
    """Continuous-batching greedy decode over one transformer.

    Shapes fixed at construction: `decode_slots` slots, a pool of
    `kv_blocks` blocks of `kv_block_size` positions, and block tables
    wide enough for `max_context` positions per slot.  Outputs are
    bit-identical to the closed-batch generate() path for the same
    prompt tokens (tests/test_decode.py proves it).
    """

    def __init__(self, params, config, *, decode_slots: int = 4,
                 kv_block_size: int = 16, kv_blocks: int | None = None,
                 max_context: int | None = None, eos_id: int | None = None,
                 prefill_chunk_size: int | None = None,
                 draft_params=None, draft_config=None, spec_k: int = 0,
                 prefix_policy=None, registry=None):
        if decode_slots < 1:
            raise ValueError(f"decode_slots must be >= 1, "
                             f"got {decode_slots}")
        self.params = params
        self.config = config
        self.slots_n = int(decode_slots)
        self.eos_id = None if eos_id is None else int(eos_id)
        max_context = int(max_context or config.max_seq_len)
        self.max_blocks = -(-max_context // int(kv_block_size))
        self.max_context = self.max_blocks * int(kv_block_size)
        if kv_blocks is None:
            # full reservation: every slot can grow to max_context, so
            # preemption never fires; shrink kv_blocks to oversubscribe
            kv_blocks = self.slots_n * self.max_blocks + 1
        self.blocks = BlockManager(int(kv_blocks), int(kv_block_size))
        # cross-request prefix KV reuse (decode/prefix.py): with a
        # prefix policy armed, fully-written prompt blocks are indexed
        # by their token hash chain and later admissions borrow the
        # longest cached prefix instead of re-prefilling it.  None =
        # cold path, behavior identical to pre-prefix deployments
        policy = (PrefixPolicy.parse(prefix_policy)
                  if prefix_policy is not None else None)
        if policy is not None and not policy.enabled:
            policy = None
        self.prefix_policy = policy
        self.prefix = (PrefixCache(self.blocks, policy.cache_blocks)
                       if policy is not None else None)
        self.pool = init_paged_pool(config, self.blocks.num_blocks,
                                    self.blocks.block_size)
        self.tables = np.full((self.slots_n, self.max_blocks),
                              TRASH_BLOCK, np.int32)
        self.positions = np.zeros((self.slots_n,), np.int32)
        self.last_tokens = np.zeros((self.slots_n, 1), np.int32)
        self.slots: list[_Slot | None] = [None] * self.slots_n
        self.waiting: deque[_Request] = deque()
        self._admission_seq = 0
        self._registry = registry
        # chunked prefill: coerced to a power-of-two block multiple so
        # the per-chunk executables stay logarithmic; a chunk covering
        # max_context degenerates to the monolithic path
        if prefill_chunk_size is not None:
            chunk = bucket_length(int(prefill_chunk_size),
                                  minimum=self.blocks.block_size)
            self.prefill_chunk = int(min(chunk, self.max_context))
        else:
            self.prefill_chunk = None
        # greedy-exact speculative decoding: draft model + window size
        if (draft_params is None) != (draft_config is None):
            raise ValueError("speculative decoding needs BOTH "
                             "draft_params and draft_config")
        self.spec_k = int(spec_k or 0)
        if self.spec_k and draft_params is None:
            raise ValueError(f"spec_k={self.spec_k} needs a draft model "
                             f"(draft_params/draft_config)")
        if draft_params is not None and self.spec_k < 1:
            self.spec_k = 4
        self.draft_params = draft_params
        self.draft_config = draft_config
        if draft_config is not None:
            if draft_config.vocab_size != config.vocab_size:
                raise ValueError(
                    f"draft vocab_size {draft_config.vocab_size} != "
                    f"target vocab_size {config.vocab_size}: proposals "
                    f"would index a different token space")
            # the draft pool is FULLY reserved with a static block row
            # per slot: the draft is small, so the reservation is cheap
            # and speculation stays out of the target pool's
            # allocation/preemption logic entirely
            self.draft_pool = init_paged_pool(
                draft_config, self.slots_n * self.max_blocks + 1,
                self.blocks.block_size)
            self.draft_tables = np.zeros(
                (self.slots_n, self.max_blocks), np.int32)
            for index in range(self.slots_n):
                self.draft_tables[index] = (
                    1 + index * self.max_blocks
                    + np.arange(self.max_blocks))
            self.draft_positions = np.zeros((self.slots_n,), np.int32)
        self.spec_draft_s = 0.0
        self.spec_verify_s = 0.0
        self.counters = {"admitted": 0, "completed": 0, "preempted": 0,
                         "deferred_admissions": 0, "cancelled": 0,
                         "compiles": 0, "prefill_chunks": 0,
                         "chunk_interleaves": 0, "spec_windows": 0,
                         "spec_drafted": 0, "spec_accepted": 0,
                         "adopted": 0, "adopt_fallbacks": 0,
                         "kv_migrated_bytes": 0, "restores": 0,
                         "restore_fallbacks": 0,
                         "restore_replayed_tokens": 0,
                         "prefix_hits": 0, "prefix_partial_hits": 0,
                         "prefix_blocks_shared": 0,
                         "prefix_evictions": 0}
        self._update_gauges()

    # -- submission --------------------------------------------------------

    def _bucket(self, true_len: int) -> int:
        """Prompt prefill bucket: power-of-two padding rounded up to a
        block multiple, so the per-bucket prefill executable count stays
        logarithmic and block scatter is exact.  Clamped to max_context
        (itself a block multiple): a prompt whose pow2 round-up
        overshoots a non-pow2 max_context still fits — prefill works at
        any block-multiple length — and must not be rejected."""
        block = self.blocks.block_size
        padded = bucket_length(true_len, minimum=block)
        return min(-(-padded // block) * block, self.max_context)

    def submit(self, request_id, prompt_tokens, max_new_tokens: int):
        prompt = np.asarray(prompt_tokens, np.int32).reshape(-1)
        max_new = int(max_new_tokens)
        if prompt.size < 1:
            raise ValueError(f"{request_id}: empty prompt")
        if max_new < 1:
            raise ValueError(f"{request_id}: max_new_tokens must be >= 1")
        worst = max(self._bucket(prompt.size), prompt.size + max_new)
        if worst > self.max_context:
            raise ValueError(
                f"{request_id}: prompt {prompt.size} + max_new "
                f"{max_new} exceeds max_context {self.max_context}")
        if self.blocks.blocks_for(worst) > self.blocks.capacity:
            raise ValueError(
                f"{request_id}: needs {self.blocks.blocks_for(worst)} "
                f"KV blocks but the pool only has "
                f"{self.blocks.capacity}; raise kv_blocks")
        self.waiting.append(_Request(
            request_id=request_id, prompt=prompt, max_new=max_new,
            submitted_at=time.perf_counter()))
        self._update_gauges()

    def _ingest_kv_blocks(self, record: dict, needed: int,
                          timeout, fallback, what: str):
        """The shared CONSUMER half of both KV migrations -- prefill
        handoff adoption and checkpoint restore: allocate `needed`
        blocks, batch-fetch `record`'s raw block descriptors (ONE
        connection per producing peer), and scatter them into the
        pool.  Returns (granted_blocks, migrated_bytes); on ANY
        failure the grant is returned to the free list, `fallback`
        runs with the reason, and (None, 0) comes back."""
        from .disagg import fetch_kv_blocks

        granted = self._allocate(needed)
        if granted is None:
            fallback("pool exhausted")
            return None, 0
        try:
            leaves = fetch_kv_blocks(record, timeout=timeout)
        except (KeyError, ValueError) as error:
            # TransferError subclasses ValueError; expired keys raise
            # KeyError -- either way the prompt re-prefills locally
            self.blocks.free(granted)
            fallback(f"KV fetch failed: {error}")
            return None, 0
        migrated = 0
        indices = np.asarray(granted)
        for name, stacked in leaves.items():
            if name not in self.pool:
                self.blocks.free(granted)
                fallback(f"{what} leaf {name!r} not in pool "
                         f"(kv_dtype mismatch?)")
                return None, 0
            migrated += stacked.nbytes
            try:
                self.pool[name] = self.pool[name].at[:, indices].set(
                    stacked)
            except (TypeError, ValueError) as error:
                # same leaf names + block size but different model
                # geometry (mixed fleet / rolling reconfig): the
                # scatter is where the mismatch surfaces, and it must
                # degrade like every other path -- never leak the grant
                self.blocks.free(granted)
                fallback(f"{what} leaf {name!r} does not fit this "
                         f"pool: {error}")
                return None, 0
        return granted, migrated

    def adopt_request(self, request_id, handoff: dict,
                      timeout: float | None = None) -> StepReport:
        """Adopt a remotely prefilled request MID-FLIGHT: fetch the
        handoff's KV blocks over the transfer plane (one batched
        connection per peer), rewrite a free slot's block table to the
        granted blocks, and continue greedy decode from the prompt end
        -- no re-prefill, int8 KV carried through unchanged, tokens
        bit-identical to a co-located prefill+decode (the transferred
        K/V are exact copies, and the writes-before-gather invariant
        covers the last block's padding tail exactly as it covers
        local prefill's).

        NEVER loses the request: a fetch failure/timeout, a block-size
        mismatch, a full slot array, or an exhausted pool all FALL
        BACK to a plain submit() -- a local re-prefill through the
        ordinary admission path (decode.adopt_fallbacks counts it).
        Returns a StepReport carrying the first token's emission (and
        the completion, when max_new == 1)."""
        report = StepReport()
        prompt = np.asarray(handoff["prompt"], np.int32).reshape(-1)
        max_new = int(handoff["max_new"])
        true_len = int(handoff.get("true_len", prompt.size))

        def fallback(reason: str) -> StepReport:
            _LOGGER.info("adopt %r fell back to local re-prefill: %s",
                         request_id, reason)
            self.counters["adopt_fallbacks"] += 1
            self._bump("decode.adopt_fallbacks", 1)
            self.submit(request_id, prompt, max_new)
            return report

        if int(handoff.get("block_size", 0)) != self.blocks.block_size:
            return fallback(
                f"block_size {handoff.get('block_size')} != pool's "
                f"{self.blocks.block_size}")
        free = [index for index, slot in enumerate(self.slots)
                if slot is None]
        if not free:
            return fallback("no free slot")
        worst = max(self._bucket(true_len), true_len + max_new)
        if worst > self.max_context:
            raise ValueError(
                f"{request_id}: prompt {true_len} + max_new {max_new} "
                f"exceeds max_context {self.max_context}")
        needed = self.blocks.blocks_for(true_len)
        if len(handoff.get("kv_blocks") or []) != needed:
            return fallback(
                f"handoff carries {len(handoff.get('kv_blocks') or [])}"
                f" blocks, prompt needs {needed}")
        adopt_start = time.perf_counter()
        granted, migrated = self._ingest_kv_blocks(
            handoff, needed, timeout, fallback, "handoff")
        if granted is None:
            return report
        # slot bookkeeping identical to a local prefill's end state
        request = _Request(
            request_id=request_id, prompt=prompt, max_new=max_new,
            submitted_at=(adopt_start
                          - float(handoff.get("queue_wait_s", 0.0))
                          - float(handoff.get("prefill_s", 0.0))))
        request.admitted_at = adopt_start
        bucket = self._bucket(true_len)
        padded = np.zeros((bucket,), np.int32)
        padded[:true_len] = prompt
        index = free[0]
        slot = _Slot(request, granted, self._admission_seq, true_len,
                     bucket, padded)
        self._admission_seq += 1
        slot.prefill_pos = true_len
        self.slots[index] = slot
        self.tables[index, :] = TRASH_BLOCK
        self.tables[index, :needed] = granted
        self._finish_prefill(index, report,
                             int(handoff["first_token"]))
        adopt_ms = (time.perf_counter() - adopt_start) * 1000.0
        self.counters["adopted"] += 1
        self.counters["kv_migrated_bytes"] += migrated
        self.counters["admitted"] += 1
        report.admitted += 1
        self._bump("decode.adopted", 1)
        self._bump("decode.admitted", 1)
        self._bump("decode.kv_migrated_bytes", migrated)
        if self._registry is not None:
            self._registry.histogram("decode.adopt_ms").record(adopt_ms)
        self._update_gauges()
        return report

    def restore_request(self, request_id, record,
                        prompt_tokens=None, max_new_tokens=None,
                        timeout: float | None = None,
                        resume_from: int = 0) -> StepReport:
        """Resume a request from a CHECKPOINT after its decode replica
        died (decode/checkpoint.py): fetch the keeper's merged KV
        blocks over the transfer plane, scatter them into a free slot,
        restore the cursor + generated-token list, and continue greedy
        decode from the snapshot position -- re-decoding only the (at
        most max_checkpoint_lag) tokens generated after the snapshot,
        which greedy determinism regenerates bit-identically, instead
        of re-prefilling the whole prompt.

        `resume_from` is the highest token offset already DELIVERED
        downstream (a replaying client's hint): tokens below it
        re-decode silently -- counted as
        decode.restore_replayed_tokens -- and emission resumes
        gaplessly at that offset.  Without a hint every restored token
        DELIBERATELY re-emits with its original offset -- the
        snapshot's own emitted floor is NOT trusted, because the dead
        element may have buffered (never published) chunks the engine
        already counted as surfaced -- so an offset-keyed consumer
        assembles an exactly-once, gapless stream either way.

        NEVER loses the request: a missing/stale/mismatched record, a
        failed fetch, a full slot array, or an exhausted pool all FALL
        BACK to a plain submit() -- the existing replay re-prefill --
        with decode.restore_fallbacks counting the degradation."""
        report = StepReport()
        if record is not None:
            prompt = np.asarray(record.get("prompt", ()),
                                np.int32).reshape(-1)
            max_new = int(record.get("max_new", max_new_tokens or 0))
        else:
            prompt = np.asarray(
                () if prompt_tokens is None else prompt_tokens,
                np.int32).reshape(-1)
            max_new = int(max_new_tokens or 0)
        if prompt.size < 1 or max_new < 1:
            raise ValueError(
                f"{request_id}: restore needs a prompt and "
                f"max_new_tokens (from the record or the caller)")

        def fallback(reason: str) -> StepReport:
            _LOGGER.info("restore %r fell back to local re-prefill: "
                         "%s", request_id, reason)
            self.counters["restore_fallbacks"] += 1
            self._bump("decode.restore_fallbacks", 1)
            self.submit(request_id, prompt, max_new)
            return report

        if record is None:
            return fallback("no checkpoint record")
        generated = [int(token) for token in
                     (record.get("generated") or ())]
        if not generated:
            return fallback("snapshot precedes the first token")
        if int(record.get("block_size", 0)) != self.blocks.block_size:
            return fallback(
                f"block_size {record.get('block_size')} != pool's "
                f"{self.blocks.block_size}")
        true_len = int(record.get("true_len", prompt.size))
        position = int(record.get("position", 0))
        if position != true_len + len(generated) - 1:
            return fallback(
                f"inconsistent snapshot: position {position} != "
                f"true_len {true_len} + {len(generated)} - 1")
        free = [index for index, slot in enumerate(self.slots)
                if slot is None]
        if not free:
            return fallback("no free slot")
        worst = max(self._bucket(true_len), true_len + max_new)
        if worst > self.max_context:
            raise ValueError(
                f"{request_id}: prompt {true_len} + max_new {max_new} "
                f"exceeds max_context {self.max_context}")
        needed = self.blocks.blocks_for(position)
        if len(record.get("kv_blocks") or []) != needed:
            return fallback(
                f"snapshot carries "
                f"{len(record.get('kv_blocks') or [])} blocks, "
                f"position {position} needs {needed}")
        restore_start = time.perf_counter()
        granted, migrated = self._ingest_kv_blocks(
            record, needed, timeout, fallback, "snapshot")
        if granted is None:
            return report
        now = time.perf_counter()
        request = _Request(
            request_id=request_id, prompt=prompt, max_new=max_new,
            submitted_at=now)
        request.admitted_at = now
        request.first_token_at = now
        request.generated = generated
        # the emission floor: tokens the downstream already holds are
        # re-decoded (their K/V feeds later positions) but re-emission
        # resumes at the floor, so streamed offsets stay gapless.  With
        # a floor PAST the snapshot the gap is exactly the post-snapshot
        # tokens the dead replica emitted -- the re-decode burden
        # max_checkpoint_lag bounds
        resume = max(int(resume_from or 0), 0)
        replayed = max(resume - len(generated), 0)
        request.emitted_upto = min(resume, max_new)
        bucket = self._bucket(true_len)
        padded = np.zeros((bucket,), np.int32)
        padded[:true_len] = prompt
        index = free[0]
        slot = _Slot(request, granted, self._admission_seq, true_len,
                     bucket, padded)
        self._admission_seq += 1
        slot.prefill_pos = true_len
        self.slots[index] = slot
        self.tables[index, :] = TRASH_BLOCK
        self.tables[index, :needed] = granted
        self.positions[index] = position
        self.last_tokens[index, 0] = generated[-1]
        if self.draft_params is not None:
            # the draft's cache cannot restore from the target's
            # snapshot: rebuild it from the prompt and let the pending
            # window re-ingest the restored tail lazily -- proposals
            # are only ever proposals, so correctness is unaffected
            self._draft_prefill(index)
            catchup = generated[max(len(generated) - 2, 0):]
            slot.draft_pending = list(catchup)
            self.draft_positions[index] = (
                position + 1 - len(slot.draft_pending))
        restore_ms = (time.perf_counter() - restore_start) * 1000.0
        self.counters["restores"] += 1
        self.counters["kv_migrated_bytes"] += migrated
        self.counters["admitted"] += 1
        self.counters["restore_replayed_tokens"] += replayed
        report.admitted += 1
        self._bump("decode.restores", 1)
        self._bump("decode.admitted", 1)
        self._bump("decode.kv_migrated_bytes", migrated)
        if replayed:
            self._bump("decode.restore_replayed_tokens", replayed)
        if self._registry is not None:
            self._registry.histogram("decode.restore_ms").record(
                restore_ms)
        self._surface(report, request)
        if self._finished(request):
            report.completions.append(self._complete(index))
        self._update_gauges()
        return report

    def cancel(self, predicate) -> int:
        """Drop every request whose request_id satisfies `predicate`
        (waiting or mid-decode; a cancelled slot frees immediately).
        Returns the number cancelled."""
        cancelled = 0
        kept = deque()
        for request in self.waiting:
            if predicate(request.request_id):
                cancelled += 1
            else:
                kept.append(request)
        self.waiting = kept
        for index, slot in enumerate(self.slots):
            if slot is not None and predicate(slot.request.request_id):
                self._release_slot(index)
                cancelled += 1
        if cancelled:
            self.counters["cancelled"] += cancelled
            self._bump("decode.cancelled", cancelled)
            self._update_gauges()
        return cancelled

    def has_work(self) -> bool:
        return bool(self.waiting) or any(
            slot is not None for slot in self.slots)

    # -- the engine step ---------------------------------------------------

    def step(self) -> StepReport:
        """One engine tick: admit waiting requests into free slots,
        advance every mid-prefill slot by one chunk, grow/preempt block
        allocations, then run ONE fused decode (or speculative verify)
        step over the decoding slots.  Chunked prefill progress and
        decode progress share the tick -- that interleaving is what
        stops a long prompt from convoying every co-scheduled slot."""
        report = StepReport()
        self._admit(report)
        ran_chunk = self._advance_prefills(report)
        active = [index for index, slot in enumerate(self.slots)
                  if slot is not None]
        if not active:
            self._update_gauges()
            report.active = 0
            return report
        self._grow_or_preempt()
        active = [index for index, slot in enumerate(self.slots)
                  if slot is not None]
        report.active = len(active)
        decoding = [index for index in active
                    if not self.slots[index].prefilling]
        if not decoding:
            self._update_gauges()
            return report
        if self.draft_params is not None:
            self._spec_round(decoding, report)
        else:
            self._plain_step(decoding, report)
        if ran_chunk:
            # a prefill chunk and decode progress shared this tick:
            # the convoy the chunking exists to break
            self.counters["chunk_interleaves"] += 1
            self._bump("decode.chunk_interleaves", 1)
        self._update_gauges()
        return report

    def _plain_step(self, decoding: list, report: StepReport) -> None:
        """One paged_decode_step over all slots; mid-prefill and free
        slots write to the trash block and their rows are ignored."""
        write_blocks = np.zeros((self.slots_n,), np.int32)
        write_offsets = np.zeros((self.slots_n,), np.int32)
        for index in decoding:
            position = int(self.positions[index])
            block_index = position // self.blocks.block_size
            write_blocks[index] = self.slots[index].blocks[block_index]
            write_offsets[index] = position % self.blocks.block_size
        before = _jit_cache_size()
        self.pool, next_tokens = paged_decode_step(
            self.params, self.config, self.pool, self.tables,
            self.positions, self.last_tokens, write_blocks,
            write_offsets)
        self._note_compiles(_jit_cache_size() - before)
        next_tokens = np.asarray(next_tokens)
        for index in decoding:
            slot = self.slots[index]
            request = slot.request
            token = int(next_tokens[index, 0])
            self.positions[index] += 1
            self.last_tokens[index, 0] = token
            request.generated.append(token)
            request.decode_steps += 1
            self._surface(report, request)
            if self._finished(request):
                report.completions.append(self._complete(index))

    # -- admission / prefill ----------------------------------------------

    def _admit(self, report: StepReport) -> None:
        while self.waiting:
            free = [index for index, slot in enumerate(self.slots)
                    if slot is None]
            if not free:
                return
            request = self.waiting[0]
            true_len = int(request.prompt.size)
            bucket = self._bucket(true_len)
            needed = self.blocks.blocks_for(bucket)
            # prefix-cache hit path: borrow the longest cached run of
            # this prompt's hash chain, capped so the LAST prompt token
            # always tail-prefills (its logits produce the first
            # generated token), and only allocate the uncached rest
            matched, hashes = [], None
            if self.prefix is not None:
                hashes = chain_hashes(request.prompt,
                                      self.blocks.block_size)
                usable = (true_len - 1) // self.blocks.block_size
                matched = self.prefix.acquire(hashes[:usable])
                if matched and (len(matched)
                                < self.prefix_policy.min_prefix_blocks):
                    # a tiny hit pays table-rewrite cost for nothing
                    self.prefix.release(matched)
                    matched = []
            granted = self._allocate(needed - len(matched))
            if granted is None:
                # pool exhausted (cached tier already reclaimed):
                # admission DEFERS (FIFO order kept); completions free
                # blocks, so the queue always drains.  Counted once
                # per REQUEST, not per blocked tick.
                if matched:
                    self.prefix.release(matched)
                if not request.deferred:
                    request.deferred = True
                    self.counters["deferred_admissions"] += 1
                    self._bump("decode.deferred_admissions", 1)
                return
            blocks = list(matched) + granted
            self.waiting.popleft()
            index = free[0]
            padded = np.zeros((bucket,), np.int32)
            padded[:true_len] = request.prompt
            slot = _Slot(request, blocks, self._admission_seq, true_len,
                         bucket, padded)
            slot.shared = len(matched)
            slot.hashes = hashes
            slot.prefill_pos = len(matched) * self.blocks.block_size
            self._admission_seq += 1
            self.slots[index] = slot
            self.tables[index, :] = TRASH_BLOCK
            self.tables[index, :needed] = blocks
            if matched:
                self.counters["prefix_hits"] += 1
                self.counters["prefix_blocks_shared"] += len(matched)
                self._bump("decode.prefix_hits", 1)
                self._bump("decode.prefix_blocks_shared", len(matched))
                if len(matched) < usable:
                    self.counters["prefix_partial_hits"] += 1
                    self._bump("decode.prefix_partial_hits", 1)
            # a preempted request's RE-admission keeps first-attempt
            # timestamps: the caller saw its first token back then, so
            # ttft/queue_wait/prefill stats must not absorb the retry
            if request.admitted_at is None:
                request.admitted_at = time.perf_counter()
            self.counters["admitted"] += 1
            report.admitted += 1
            self._bump("decode.admitted", 1)
            if (self.prefill_chunk is not None
                    and self.prefill_chunk < bucket):
                # chunked: no device work at admission -- the slot's
                # prompt is consumed one chunk per tick by
                # _advance_prefills, interleaved with decode steps
                # (a prefix hit just starts the chunk cursor past the
                # borrowed blocks)
                continue
            if slot.shared:
                # prefix hit on the monolithic path: ONE chunk call
                # covers the uncached tail -- the whole point of the
                # cache is skipping the quadratic prefix compute
                self._tail_prefill(index, report)
                continue
            before = _jit_cache_size()
            self.pool, first = paged_prefill(
                self.params, self.config, self.pool, padded[None],
                self.tables[index], np.int32(true_len))
            self._note_compiles(_jit_cache_size() - before)
            slot.prefill_pos = bucket
            self._finish_prefill(index, report, int(first))

    def _tail_prefill(self, index: int, report: StepReport) -> None:
        """Prefill ONLY the uncached tail of a prefix-cache hit in one
        chunk call: paged_prefill_chunk attends to the borrowed
        blocks' resident KV exactly as it attends to earlier chunks'
        writes, so the produced logits -- and the first generated
        token -- are bit-identical to a cold prefill over the whole
        prompt (f32 and int8 KV alike; int8 per-block scales travel
        with the shared blocks)."""
        slot = self.slots[index]
        block_size = self.blocks.block_size
        start = slot.prefill_pos
        remaining = slot.true_len - start
        size = bucket_length(remaining, minimum=block_size)
        chunk = np.zeros((1, size), np.int32)
        chunk[0, :remaining] = slot.padded[start:start + remaining]
        write_blocks = np.full((size,), TRASH_BLOCK, np.int32)
        write_offsets = np.zeros((size,), np.int32)
        for offset in range(size):
            position = start + offset
            if position < slot.true_len:
                write_blocks[offset] = slot.blocks[
                    position // block_size]
            write_offsets[offset] = position % block_size
        before = _jit_cache_size()
        self.pool, greedy = paged_prefill_chunk(
            self.params, self.config, self.pool, chunk,
            self.tables[index], np.int32(start), write_blocks,
            write_offsets)
        self._note_compiles(_jit_cache_size() - before)
        first = int(np.asarray(greedy)[slot.true_len - 1 - start])
        self._finish_prefill(index, report, first)

    def _finish_prefill(self, index: int, report: StepReport,
                        first: int, draft_ready: bool = False) -> None:
        """Shared tail of monolithic and chunked prefill: record the
        first generated token, arm the decode cursor, register the
        slot's freshly written prompt blocks with the prefix cache,
        and bring the speculative draft up to date with the prompt
        (chunked prefill already fed the draft chunk-by-chunk:
        draft_ready=True)."""
        slot = self.slots[index]
        request = slot.request
        slot.prefill_pos = max(slot.prefill_pos, slot.true_len)
        if self.prefix is not None:
            self._register_slot_prefix(slot)
        if request.first_token_at is None:
            request.first_token_at = time.perf_counter()
        request.generated.append(first)
        self.positions[index] = slot.true_len
        self.last_tokens[index, 0] = first
        if self.draft_params is not None:
            if not draft_ready:
                self._draft_prefill(index)
            slot.draft_pending = [first]
        self._surface(report, request)
        if self._finished(request):
            report.completions.append(self._complete(index))

    def _draft_prefill(self, index: int) -> None:
        """Bring the draft's cache up to date with a freshly prefilled
        prompt.  The draft's own first-token opinion is DISCARDED --
        the target's prefill output is the authoritative greedy token;
        the draft only ever proposes."""
        slot = self.slots[index]
        before = _jit_cache_size()
        self.draft_pool, _ = paged_prefill(
            self.draft_params, self.draft_config, self.draft_pool,
            slot.padded[None], self.draft_tables[index],
            np.int32(slot.true_len))
        self._note_compiles(_jit_cache_size() - before)
        self.draft_positions[index] = slot.true_len

    def _advance_prefills(self, report: StepReport) -> bool:
        """Advance the OLDEST mid-prefill slot by ONE chunk.  One chunk
        per tick is the SARATHI-style budget: the decode-stall bound
        stays one chunk regardless of how many prefills were admitted
        together (advancing every prefilling slot would multiply the
        stall by the admission burst).  The chunk attends to the
        already-written KV blocks of earlier chunks via the slot's
        block table; the final chunk yields the request's first
        generated token, bit-identical to monolithic prefill's.  With
        a draft model, the SAME chunk range is fed through the draft's
        pool too (a quarter-depth draft adds ~25% to the chunk cost),
        so finishing a prompt never degenerates into one monolithic
        draft prefill.  Returns True when a chunk ran."""
        if self.prefill_chunk is None:
            return False
        block_size = self.blocks.block_size
        order = sorted(
            (index for index, slot in enumerate(self.slots)
             if slot is not None and slot.prefilling),
            key=lambda index: self.slots[index].seq)
        if not order:
            return False
        index = order[0]
        slot = self.slots[index]
        start = slot.prefill_pos
        remaining = slot.true_len - start
        # the last chunk shrinks to its power-of-two bucket, so the
        # executable count stays logarithmic in prefill_chunk
        size = min(self.prefill_chunk,
                   bucket_length(remaining, minimum=block_size))
        take = min(size, remaining)
        chunk = np.zeros((1, size), np.int32)
        chunk[0, :take] = slot.padded[start:start + take]
        write_blocks = np.full((size,), TRASH_BLOCK, np.int32)
        draft_blocks = np.full((size,), TRASH_BLOCK, np.int32)
        write_offsets = np.zeros((size,), np.int32)
        # a prefix-hit slot's draft cache is missing the borrowed
        # blocks' positions entirely, so chunk-feeding the draft would
        # build on garbage: skip it and let _finish_prefill rebuild
        # the draft monolithically (proposals are only proposals, but
        # they should not be noise)
        feed_draft = self.draft_params is not None and not slot.shared
        for offset in range(size):
            position = start + offset
            if position < slot.true_len:
                block_index = position // block_size
                write_blocks[offset] = slot.blocks[block_index]
                if feed_draft:
                    draft_blocks[offset] = self.draft_tables[
                        index, block_index]
            write_offsets[offset] = position % block_size
        before = _jit_cache_size()
        self.pool, greedy = paged_prefill_chunk(
            self.params, self.config, self.pool, chunk,
            self.tables[index], np.int32(start), write_blocks,
            write_offsets)
        if feed_draft:
            self.draft_pool, _ = paged_prefill_chunk(
                self.draft_params, self.draft_config, self.draft_pool,
                chunk, self.draft_tables[index], np.int32(start),
                draft_blocks, write_offsets)
        self._note_compiles(_jit_cache_size() - before)
        self.counters["prefill_chunks"] += 1
        self._bump("decode.prefill_chunks", 1)
        slot.prefill_pos = start + take
        if not slot.prefilling:
            first = int(np.asarray(greedy)[slot.true_len - 1 - start])
            if feed_draft:
                self.draft_positions[index] = slot.true_len
            self._finish_prefill(index, report, first,
                                 draft_ready=not slot.shared)
        return True

    # -- speculative decoding ----------------------------------------------

    def _spec_round(self, decoding: list, report: StepReport) -> None:
        """One speculative round over all decoding slots: the draft
        ingests the <= 2 emitted tokens it hasn't consumed and proposes
        its first token in the same window call, extends the proposal
        run with k-1 single steps, then the target verifies the whole
        [last_token, p_1..p_k] window in ONE batched forward and the
        longest greedy-matching prefix is accepted.  Greedy-exact:
        emitted tokens are bit-identical to plain greedy decode."""
        k = self.spec_k
        block_size = self.blocks.block_size
        # 1) draft ingest + first proposal.  Pending is [new last
        # token] after a partial acceptance (the draft's own accepted
        # proposals already live in its cache) or [p_k, bonus] after a
        # full acceptance (p_k's K/V was never written) -- never more.
        ingest = np.zeros((self.slots_n, 2), np.int32)
        ingest_blocks = np.full((self.slots_n, 2), TRASH_BLOCK, np.int32)
        ingest_offsets = np.zeros((self.slots_n, 2), np.int32)
        pending_len = {}
        for index in decoding:
            pending = self.slots[index].draft_pending
            pending_len[index] = len(pending)
            for j, token in enumerate(pending):
                position = int(self.draft_positions[index]) + j
                ingest[index, j] = token
                if position < self.max_context:
                    ingest_blocks[index, j] = self.draft_tables[
                        index, position // block_size]
                    ingest_offsets[index, j] = position % block_size
        draft_start = time.perf_counter()
        before = _jit_cache_size()
        self.draft_pool, draft_greedy = paged_verify_step(
            self.draft_params, self.draft_config, self.draft_pool,
            self.draft_tables, self.draft_positions, ingest,
            ingest_blocks, ingest_offsets)
        draft_greedy = np.asarray(draft_greedy)
        proposals = np.zeros((self.slots_n, k), np.int32)
        for index in decoding:
            proposals[index, 0] = draft_greedy[
                index, pending_len[index] - 1]
            self.draft_positions[index] += pending_len[index]
        # 2) k-1 single draft steps extend the proposal run, writing
        # each proposal's K/V at its own position
        current = proposals[:, 0:1].copy()
        for run in range(1, k):
            step_blocks = np.full((self.slots_n,), TRASH_BLOCK, np.int32)
            step_offsets = np.zeros((self.slots_n,), np.int32)
            for index in decoding:
                position = int(self.draft_positions[index])
                if position < self.max_context:
                    step_blocks[index] = self.draft_tables[
                        index, position // block_size]
                    step_offsets[index] = position % block_size
            self.draft_pool, current = paged_decode_step(
                self.draft_params, self.draft_config, self.draft_pool,
                self.draft_tables, self.draft_positions, current,
                step_blocks, step_offsets)
            current = np.asarray(current)
            for index in decoding:
                proposals[index, run] = current[index, 0]
                self.draft_positions[index] += 1
        self.spec_draft_s += time.perf_counter() - draft_start
        # 3) target verification: [last_token, p_1..p_k] in one window
        window = np.zeros((self.slots_n, k + 1), np.int32)
        verify_blocks = np.full((self.slots_n, k + 1), TRASH_BLOCK,
                                np.int32)
        verify_offsets = np.zeros((self.slots_n, k + 1), np.int32)
        for index in decoding:
            slot = self.slots[index]
            window[index, 0] = self.last_tokens[index, 0]
            window[index, 1:] = proposals[index]
            for j in range(k + 1):
                position = int(self.positions[index]) + j
                if position // block_size < len(slot.blocks):
                    verify_blocks[index, j] = slot.blocks[
                        position // block_size]
                    verify_offsets[index, j] = position % block_size
        verify_start = time.perf_counter()
        self.pool, verified = paged_verify_step(
            self.params, self.config, self.pool, self.tables,
            self.positions, window, verify_blocks, verify_offsets)
        verified = np.asarray(verified)
        self.spec_verify_s += time.perf_counter() - verify_start
        self._note_compiles(_jit_cache_size() - before)
        # 4) greedy-exact acceptance: verified[j] is the target's
        # greedy token after window position j, so draft_j is accepted
        # iff it EQUALS verified[j-1]; the first mismatch wins a bonus
        # token (the target's own correction) and stops the run
        for index in decoding:
            slot = self.slots[index]
            request = slot.request
            accepted = [int(verified[index, 0])]
            for j in range(1, k + 1):
                if int(window[index, j]) != int(verified[index, j - 1]):
                    break
                accepted.append(int(verified[index, j]))
            remaining = request.max_new - len(request.generated)
            accepted = accepted[:remaining]
            if self.eos_id is not None:
                for j, token in enumerate(accepted):
                    if token == self.eos_id:
                        accepted = accepted[:j + 1]
                        break
            self.counters["spec_windows"] += 1
            self.counters["spec_drafted"] += k
            self.counters["spec_accepted"] += len(accepted)
            self._bump("decode.spec_drafted", k)
            self._bump("decode.spec_accepted", len(accepted))
            if self._registry is not None:
                self._registry.histogram("decode.accepted_len").record(
                    len(accepted))
            # rejected window positions hold stale K/V past the new
            # cursor: masked until the cursor reaches them, then
            # overwritten before the gather -- the same invariant that
            # covers prompt-bucket padding
            previous = int(self.positions[index])
            request.generated.extend(accepted)
            request.decode_steps += 1
            self.positions[index] = previous + len(accepted)
            self.last_tokens[index, 0] = accepted[-1]
            # draft bookkeeping: after a FULL acceptance the draft is
            # missing p_k's K/V as well as the bonus token, so pending
            # is two tokens and its cursor stays put; otherwise it
            # rewinds over its rejected run to the new last token
            if len(accepted) == k + 1:
                slot.draft_pending = accepted[-2:]
            else:
                slot.draft_pending = accepted[-1:]
            self.draft_positions[index] = (
                previous + len(accepted) + 1 - len(slot.draft_pending))
            self._surface(report, request)
            if self._finished(request):
                report.completions.append(self._complete(index))

    # -- block growth / preemption ----------------------------------------

    def _grow_or_preempt(self) -> None:
        """Ensure every active slot owns the block its next write
        position lands in; on exhaustion preempt the youngest slot so
        the oldest always progresses (no livelock)."""
        order = sorted(
            (index for index, slot in enumerate(self.slots)
             if slot is not None),
            key=lambda index: self.slots[index].seq)
        horizon = self.spec_k if self.draft_params is not None else 0
        for index in order:
            slot = self.slots[index]
            if slot is None:
                continue  # preempted below while growing an older slot
            if slot.prefilling:
                continue  # prompt blocks were fully granted at admission
            # speculative rounds write a k+1 window per step, so growth
            # covers the whole window -- but never past what the
            # request can still EMIT (a near-complete slot must not
            # preempt a victim for lookahead blocks no accepted token
            # can land in) nor past max_context; overflow window
            # positions write to the trash block instead
            remaining = (slot.request.max_new
                         - len(slot.request.generated))
            slot_horizon = min(horizon, max(remaining - 1, 0))
            target = min(int(self.positions[index]) + slot_horizon,
                         self.max_context - 1)
            needed = (target // self.blocks.block_size) + 1
            while len(slot.blocks) < needed:
                # cache-aware: the refcount-0 cached tier is reclaimed
                # (LRU-first) BEFORE any preemption fires -- the cache
                # must never cost a live request its slot
                granted = self._allocate(1)
                if granted is not None:
                    slot.blocks.extend(granted)
                    self.tables[index, len(slot.blocks) - 1] = granted[0]
                    continue
                victim = max(
                    (other for other in range(self.slots_n)
                     if self.slots[other] is not None),
                    key=lambda other: self.slots[other].seq)
                self._preempt(victim)
                if victim == index:
                    break  # this slot itself was the youngest

    def _preempt(self, index: int) -> None:
        slot = self.slots[index]
        request = slot.request
        _LOGGER.info("preempting slot %d (%r) after %d tokens%s: pool "
                     "exhausted", index, request.request_id,
                     len(request.generated),
                     (f" (mid-prefill at {slot.prefill_pos}/"
                      f"{slot.true_len})" if slot.prefilling else ""))
        request.preemptions += 1
        # full recompute on re-admission: greedy decode regenerates the
        # SAME tokens, and emitted_upto keeps the stream from repeating.
        # A slot caught MID-CHUNKED-PREFILL takes the same path: its
        # partially written KV blocks go back to the free list via
        # _release_slot and re-admission restarts the prompt at chunk 0
        request.generated = []
        request.decode_steps = 0
        self._release_slot(index)
        self.waiting.appendleft(request)
        self.counters["preempted"] += 1
        self._bump("decode.preempted", 1)

    def _release_slot(self, index: int) -> None:
        slot = self.slots[index]
        if self.prefix is not None:
            # registered blocks decref (a block another slot still
            # shares is NEVER freed here -- preempting one holder must
            # not corrupt its sibling); refcount-0 blocks park in the
            # cached tier, private tail blocks free immediately
            self.prefix.release(slot.blocks)
        else:
            self.blocks.free(slot.blocks)
        self.slots[index] = None
        self.tables[index, :] = TRASH_BLOCK
        self.positions[index] = 0
        self.last_tokens[index, 0] = 0

    # -- prefix cache ------------------------------------------------------

    def _allocate(self, count: int):
        """Pool allocation through the prefix cache's second-chance
        reclaim when the cache is armed: refcount-0 cached blocks are
        evicted LRU-first BEFORE an allocation fails, so admission
        deferral and the preemption ladder only ever fire for demand
        the cold system could not have satisfied either."""
        if self.prefix is not None:
            return self.prefix.allocate(count)
        return self.blocks.allocate(count)

    def _register_slot_prefix(self, slot: _Slot) -> None:
        """Index a slot's fully-written PROMPT blocks by their chain
        digests.  Only blocks entirely below true_len are prompt-pure
        (decode writes start AT true_len, so the block holding it is
        mutable); blocks the slot itself borrowed are already
        registered and are skipped via the depth offset."""
        if slot.hashes is None:
            slot.hashes = chain_hashes(slot.request.prompt,
                                       self.blocks.block_size)
        full = slot.true_len // self.blocks.block_size
        if full > slot.shared:
            self.prefix.register(slot.hashes[slot.shared:full],
                                 slot.blocks[slot.shared:full],
                                 depth=slot.shared)

    def prefix_heads(self) -> list:
        """Resident chain-head digests -- the compact summary a
        replica mirrors into its EC share for gateway prefix-affinity
        routing.  Empty when the cache is disarmed."""
        if self.prefix is None:
            return []
        return self.prefix.heads()

    def export_prefix_snapshot(self, tokens) -> dict | None:
        """Package the resident cached prefix of `tokens` as a
        checkpoint-keeper snapshot (decode/checkpoint.py schema), so
        the keeper doubles as a second-chance CROSS-REPLICA prefix
        store: another replica's adopt_prefix() pulls the blocks over
        the transfer plane instead of re-prefilling.  Returns None
        when the cache is disarmed or holds no block of this chain.

        Keyed ("prefix", head-digest) -- digests are process-stable,
        so any replica that computes the same chain finds it.  seq=0
        every time: a prefix snapshot is always a full (non-delta)
        incarnation."""
        if self.prefix is None:
            return None
        from .checkpoint import CHECKPOINT_SCHEMA
        from .disagg import offer_pool_blocks

        hashes = chain_hashes(tokens, self.blocks.block_size)
        blocks = self.prefix.resident_blocks(hashes)
        if not blocks:
            return None
        kv_blocks, _total = offer_pool_blocks(self.pool, blocks)
        count = len(blocks)
        size = self.blocks.block_size
        prefix_tokens = np.asarray(tokens, np.int32).reshape(-1)
        return {
            "schema": CHECKPOINT_SCHEMA,
            "request_id": ["prefix", hashes[0]],
            "prompt": [int(token) for token
                       in prefix_tokens[:count * size]],
            "generated": [],
            "emitted_upto": 0,
            "max_new": 0,
            "true_len": count * size,
            "position": count * size,
            "block_size": size,
            "kv_dtype": self.config.kv_dtype or "",
            "blocks_total": count,
            "delta_from": 0,
            "seq": 0,
            "kv_blocks": kv_blocks,
        }

    def adopt_prefix(self, record: dict,
                     timeout: float | None = None) -> int:
        """Ingest a keeper prefix record into the LOCAL cache: fetch
        the KV blocks over the transfer plane (the same consumer half
        prefill handoff and checkpoint restore use) and register them
        at refcount 0 -- straight into the reclaimable cached tier, so
        an imported prefix can never pin pool capacity a live request
        needs.  Returns the number of blocks registered (0 on any
        failure or when the chain is already resident: pre-warming is
        best-effort by design)."""
        if self.prefix is None:
            return 0
        if int(record.get("block_size", 0)) != self.blocks.block_size:
            return 0
        prompt = np.asarray(record.get("prompt", ()),
                            np.int32).reshape(-1)
        hashes = chain_hashes(prompt, self.blocks.block_size)
        needed = len(record.get("kv_blocks") or [])
        if not hashes or needed != len(hashes):
            return 0
        if self.prefix.lookup(hashes) == len(hashes):
            return 0                  # already fully resident

        def fallback(reason: str) -> None:
            _LOGGER.info("prefix adopt skipped: %s", reason)

        granted, migrated = self._ingest_kv_blocks(
            record, needed, timeout, fallback, "prefix")
        if granted is None:
            return 0
        indexed = self.prefix.register(hashes, granted, depth=0,
                                       refcount=0)
        self.counters["kv_migrated_bytes"] += migrated
        self._bump("decode.kv_migrated_bytes", migrated)
        self._update_gauges()
        return len(indexed)

    # -- completion --------------------------------------------------------

    def _finished(self, request: _Request) -> bool:
        if len(request.generated) >= request.max_new:
            return True
        return (self.eos_id is not None
                and request.generated[-1] == self.eos_id)

    def _surface(self, report: StepReport, request: _Request) -> None:
        while request.emitted_upto < len(request.generated):
            offset = request.emitted_upto
            report.emitted.append(
                (request.request_id, offset, request.generated[offset]))
            request.emitted_upto = offset + 1

    def _complete(self, index: int) -> Completion:
        slot = self.slots[index]
        request = slot.request
        now = time.perf_counter()
        pad = self.eos_id if self.eos_id is not None else 0
        tokens = np.full((request.max_new,), pad, np.int32)
        tokens[:len(request.generated)] = request.generated
        self._release_slot(index)
        self.counters["completed"] += 1
        self._bump("decode.completed", 1)
        admitted_at = request.admitted_at or now
        first_at = request.first_token_at or now
        stats = {
            "queue_wait_s": admitted_at - request.submitted_at,
            "prefill_s": first_at - admitted_at,
            "ttft_s": first_at - request.submitted_at,
            "decode_steps": request.decode_steps,
            "preemptions": request.preemptions,
            "total_s": now - request.submitted_at,
            "tokens": len(request.generated),
        }
        if self.prefix is not None:
            # rides the completion row into the engine trace span
            # (observe/telemetry.py) so `aiko tune` can tell a
            # cache-bound prefill floor from a compute-bound one
            stats["prefix_blocks"] = slot.shared
        if self._registry is not None:
            self._registry.histogram("decode.queue_wait_s").record(
                stats["queue_wait_s"])
            self._registry.histogram("decode.prefill_s").record(
                stats["prefill_s"])
            self._registry.histogram("decode.ttft_s").record(
                stats["ttft_s"])
            self._registry.histogram("decode.total_s").record(
                stats["total_s"])
            self._registry.histogram("decode.steps").record(
                stats["decode_steps"])
        return Completion(request.request_id, tokens, stats)

    # -- observability -----------------------------------------------------

    @property
    def compile_count(self) -> int:
        """Jit-cache signatures THIS engine's calls compiled (prefill
        buckets + the one decode step).  The zero-recompile acceptance
        assertion reads deltas of this across an admit/evict storm."""
        return self.counters["compiles"]

    def _note_compiles(self, delta: int) -> None:
        if delta > 0:
            self.counters["compiles"] += delta
            self._bump("decode.compiles", delta)

    def _bump(self, name: str, amount: int) -> None:
        if self._registry is not None:
            self._registry.counter(name).inc(amount)

    def _update_gauges(self) -> None:
        if self.prefix is not None:
            # the cache owns the eviction count (reclaims happen inside
            # PrefixCache.allocate/_trim); sync the engine counter here
            # so stats()/telemetry see one authoritative number
            delta = (self.prefix.evictions
                     - self.counters["prefix_evictions"])
            if delta > 0:
                self.counters["prefix_evictions"] += delta
                self._bump("decode.prefix_evictions", delta)
        if self._registry is None:
            return
        self._registry.gauge("decode.active_slots").set(
            sum(1 for slot in self.slots if slot is not None))
        self._registry.gauge("decode.free_blocks").set(
            self.blocks.free_count)
        self._registry.gauge("decode.waiting").set(len(self.waiting))
        if self.prefix is not None:
            self._registry.gauge("decode.prefix_cached_blocks").set(
                self.prefix.cached_count)

    def stats(self) -> dict:
        stats = {
            "active_slots": sum(1 for slot in self.slots
                                if slot is not None),
            "free_blocks": self.blocks.free_count,
            "waiting": len(self.waiting),
            "slots": self.slots_n,
            "blocks": self.blocks.capacity,
            "block_size": self.blocks.block_size,
            **self.counters,
        }
        if self.prefill_chunk is not None:
            stats["prefill_chunk_size"] = self.prefill_chunk
        if self.prefix is not None:
            stats["prefix_cached_blocks"] = self.prefix.cached_count
            stats["prefix_shared_blocks"] = self.prefix.shared_count
        if self.draft_params is not None:
            windows = max(self.counters["spec_windows"], 1)
            spec_total = self.spec_draft_s + self.spec_verify_s
            stats["spec_k"] = self.spec_k
            # mean emitted tokens per verify window (ceiling: k + 1)
            stats["accepted_len_mean"] = round(
                self.counters["spec_accepted"] / windows, 3)
            # share of speculative wall time spent in the draft
            # (ingest + proposal run) vs target verification
            stats["draft_overhead_frac"] = round(
                self.spec_draft_s / max(spec_total, 1e-9), 3)
        return stats
