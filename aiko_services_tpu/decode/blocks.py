# Paged-KV block manager: host-side bookkeeping for the preallocated
# block pool (models/transformer.py init_paged_pool).
#
# The pool's device arrays never change shape; this class only decides
# WHICH fixed-size block each slot's next token lands in.  Allocation
# and free are O(1) list operations on the event loop -- the device
# never sees fragmentation because the block table indirection
# (paged_decode_step's gather) makes any block order equivalent.
#
# Block 0 is reserved as the TRASH block: inactive decode slots write
# their masked garbage there, which is what keeps the engine step
# shape-stable (zero recompiles) across admissions and evictions.

from __future__ import annotations

__all__ = ["BlockManager", "TRASH_BLOCK"]

TRASH_BLOCK = 0


class BlockManager:
    """Fixed pool of `num_blocks` KV blocks of `block_size` positions.

    `num_blocks` INCLUDES the reserved trash block, so the allocatable
    capacity is num_blocks - 1.  Allocation is all-or-nothing: a
    request that cannot get every block it asked for gets none (the
    scheduler defers or preempts instead of holding partial grants
    that could deadlock two half-admitted requests)."""

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is the reserved "
                f"trash block), got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # LIFO free list: recently-freed blocks are re-used first (their
        # pool pages are the warmest).  The parallel set exists only for
        # O(1) double-free detection -- under prefix-cache churn a
        # release wave frees hundreds of blocks, and the old
        # `block in self._free` linear scan made each wave O(n^2)
        self._free = list(range(self.num_blocks - 1, TRASH_BLOCK, -1))
        self._free_set = set(self._free)

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def capacity(self) -> int:
        return self.num_blocks - 1

    def blocks_for(self, positions: int) -> int:
        """Blocks needed to hold `positions` token positions."""
        return -(-int(positions) // self.block_size)

    def allocate(self, count: int) -> list | None:
        """`count` blocks, all-or-nothing; None when the pool cannot
        satisfy the request (caller defers admission or preempts)."""
        count = int(count)
        if count < 0:
            raise ValueError(f"cannot allocate {count} blocks")
        if count > len(self._free):
            return None
        taken = self._free[-count:] if count else []
        del self._free[len(self._free) - count:]
        self._free_set.difference_update(taken)
        return taken

    def free(self, blocks) -> None:
        for block in blocks:
            block = int(block)
            if block == TRASH_BLOCK:
                raise ValueError("the trash block is never allocated")
            if block in self._free_set \
                    or not (0 < block < self.num_blocks):
                raise ValueError(f"double free / bad block {block}")
            self._free.append(block)
            self._free_set.add(block)
