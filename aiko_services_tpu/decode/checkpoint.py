# Warm KV failover: incremental decode-state checkpointing.
#
# The chaos harness (round 13) proves zero-loss failover, but recovery
# of a DECODE replica is cold: the gateway replays every migrated
# stream's frames and the survivor re-prefills every in-flight prompt.
# The round-14 roofline prices one 16k prefill at ~1.9 s of
# compute-bound kernel time, so a crash under a continuous-batching
# storm becomes a re-prefill convoy that stalls every co-scheduled
# decode slot.  Round 16 built the missing primitive -- adopt_request
# ingests KV blocks shipped over the transfer plane bit-identically --
# and this module turns it from a prefill->decode hop into a
# crash-recovery path:
#
#   DecodeCheckpointer  rides the engine pump: every `checkpoint_every`
#                       ticks (or sooner, when a slot has generated
#                       `max_checkpoint_lag` tokens since its last
#                       snapshot) it ships ONLY the KV blocks written
#                       since the previous snapshot -- KV is
#                       append-only, so the delta is the partial last
#                       block plus anything after it -- together with
#                       the slot's cursor, generated tokens,
#                       emitted_upto, and admission config, as the same
#                       JSON-safe raw-descriptor trees PrefillEngine
#                       exports
#   CheckpointKeeper    the standby holding the snapshots: ingests each
#                       delta OFF the engine's event loop (a worker
#                       thread pulls the bytes through fetch_many's
#                       one-connection-per-peer path) and serves
#                       restore() by re-offering the merged blocks on
#                       its own transfer server -- so the checkpoint
#                       survives the replica that wrote it
#   CheckpointPolicy    the AIKO409 grammar (checkpoint_every / keeper /
#                       recovery_rate / max_checkpoint_lag) through the
#                       shared directive core, so `aiko lint` and
#                       construction are the same check
#
# DecodeEngine.restore_request (engine.py) consumes a keeper's restore
# record: the snapshot's blocks scatter into a free slot, the cursor
# and token list resume, and greedy determinism re-decodes the (at
# most `max_checkpoint_lag`) tokens generated after the snapshot
# bit-identically -- no re-prefill.  EVERY degraded path -- dead
# keeper, expired snapshot, block-size mismatch, exhausted pool --
# falls back to the existing replay re-prefill, never losing a frame.

from __future__ import annotations

import queue
import threading
import time

import numpy as np

from ..analyze.grammar import DirectiveGrammar, Field, GrammarError
from ..pipeline.transfer import fetch_many, get_transfer_server
from ..utils import get_logger

__all__ = [
    "CHECKPOINT_GRAMMAR", "CHECKPOINT_SCHEMA", "CheckpointKeeper",
    "CheckpointPolicy", "DecodeCheckpointer", "get_keeper",
    "register_keeper", "reset_keepers",
]

_LOGGER = get_logger("decode_checkpoint")

CHECKPOINT_SCHEMA = "aiko.decode_ckpt/1"

DEFAULT_CHECKPOINT_EVERY = 8     # engine ticks between snapshots
DEFAULT_MAX_CHECKPOINT_LAG = 32  # tokens a crash may force re-decoding
DEFAULT_KEEPER_MAX_AGE_S = 120.0

CHECKPOINT_GRAMMAR = DirectiveGrammar(
    "checkpoint policy",
    options={
        "checkpoint_every": Field("int", minimum=1),
        "keeper": Field("str"),
        "recovery_rate": Field("float", minimum=0.0),
        "max_checkpoint_lag": Field("int", minimum=1),
    })


class CheckpointPolicy:
    """Parsed checkpoint spec (rule code AIKO409).  Two scopes share
    one grammar, mirroring the disagg policy's role= split:

      engine side   (LMGenerate parameter `checkpoint`)
                    checkpoint_every / max_checkpoint_lag / keeper --
                    the snapshot cadence and where deltas ship
      gateway side  (Gateway parameter `checkpoint`, definition
                    parameter `checkpoint_policy`)
                    recovery_rate / keeper -- failover pacing and the
                    keeper name the restore hints (and the journal)
                    carry

    `keeper` is legal on both: the fleet keeper address is one name.
    """

    __slots__ = ("checkpoint_every", "keeper", "recovery_rate",
                 "max_checkpoint_lag", "present", "spec")

    def __init__(self):
        self.checkpoint_every = DEFAULT_CHECKPOINT_EVERY
        self.keeper = ""
        self.recovery_rate = 0.0          # 0 = unpaced replay
        self.max_checkpoint_lag = DEFAULT_MAX_CHECKPOINT_LAG
        self.present: set = set()
        self.spec = ""

    @classmethod
    def parse(cls, spec) -> "CheckpointPolicy":
        """Parse a spec (directive string, dict of the same keys, or
        None/"" for all defaults)."""
        policy = cls()
        if spec is None or spec == "" or spec is True:
            return policy
        if isinstance(spec, CheckpointPolicy):
            return spec
        parsed = CHECKPOINT_GRAMMAR.parse(spec)
        if not isinstance(spec, dict):
            policy.spec = str(spec)
        for key, value in parsed.options.items():
            setattr(policy, key, value)
            policy.present.add(key)
        return policy

    def validate_gateway(self) -> None:
        """A gateway spec paces recovery and names the keeper; the
        snapshot cadence belongs to the replica that decodes."""
        engine_side = self.present & {"checkpoint_every",
                                      "max_checkpoint_lag"}
        if engine_side:
            raise GrammarError(
                f"checkpoint policy: {sorted(engine_side)} are "
                f"engine-side directives; a gateway spec carries "
                f"recovery_rate/keeper only")

    def validate_engine(self) -> None:
        if "recovery_rate" in self.present:
            raise GrammarError(
                "checkpoint policy: recovery_rate is a gateway-side "
                "directive (failover pacing); an engine spec carries "
                "checkpoint_every/max_checkpoint_lag/keeper")

    def __repr__(self):
        return (f"CheckpointPolicy(every={self.checkpoint_every}, "
                f"keeper={self.keeper!r}, "
                f"recovery_rate={self.recovery_rate}, "
                f"max_lag={self.max_checkpoint_lag})")


# -- keeper registry ---------------------------------------------------------
#
# Keepers are addressed by NAME: the engine-side `keeper=` directive,
# the gateway's restore hints, and the journal all carry the name, and
# the adopting element resolves it here.  The registry is per
# interpreter -- exactly the scope the loopback chaos harness and the
# in-process replica fleet share; a wire-addressable keeper actor can
# layer on top without changing the engine-side contract.

_KEEPERS: dict[str, "CheckpointKeeper"] = {}
_KEEPERS_LOCK = threading.Lock()


def register_keeper(name: str, keeper: "CheckpointKeeper") -> None:
    with _KEEPERS_LOCK:
        _KEEPERS[str(name)] = keeper


def get_keeper(name: str) -> "CheckpointKeeper | None":
    with _KEEPERS_LOCK:
        return _KEEPERS.get(str(name))


def reset_keepers() -> None:
    with _KEEPERS_LOCK:
        keepers = list(_KEEPERS.values())
        _KEEPERS.clear()
    for keeper in keepers:
        keeper.stop()


def _request_key(request_id):
    """Snapshot keys must survive a JSON hop: the element keys requests
    by (stream_id, frame_id, row) tuples, which the codec renders as
    lists."""
    if isinstance(request_id, (list, tuple)):
        return tuple(request_id)
    return request_id


class _Kept:
    """One request's merged checkpoint state on the keeper."""

    __slots__ = ("meta", "blocks", "seq", "stored_at")

    def __init__(self):
        self.meta: dict = {}
        self.blocks: list = []      # block index -> {leaf: ndarray}
        self.seq = -1
        self.stored_at = 0.0


class CheckpointKeeper:
    """Holds decode-state snapshots OFF the replica that wrote them.

    store() only enqueues: a worker thread pulls each delta's bytes
    through fetch_many (one connection per producing peer) and merges
    it into the per-request block list, so the engine's event loop
    never waits on the keeper's network.  restore() re-offers the
    merged blocks on THIS process's transfer server and returns a
    JSON-safe record shaped like a prefill handoff (plus the resume
    state), which DecodeEngine.restore_request consumes.  Snapshots
    older than `max_age_s` are stale -- restore raises KeyError and
    the caller falls back to a re-prefill."""

    def __init__(self, name: str = "", max_age_s: float | None = None,
                 register: bool = True):
        self.name = str(name)
        self.max_age_s = float(max_age_s if max_age_s is not None
                               else DEFAULT_KEEPER_MAX_AGE_S)
        self._kept: dict = {}
        self._lock = threading.Lock()
        self._queue: queue.Queue = queue.Queue()
        self._closed = False
        self.counters = {"stored": 0, "store_errors": 0, "dropped": 0,
                         "restored": 0, "bytes": 0, "expired": 0}
        self._stores_since_sweep = 0
        self._worker = threading.Thread(
            target=self._drain, name=f"ckpt_keeper:{self.name}",
            daemon=True)
        self._worker.start()
        if register and self.name:
            register_keeper(self.name, self)

    # -- ingest (async, off the engine loop) ---------------------------

    def store(self, snapshot: dict) -> None:
        """Enqueue one snapshot delta for ingestion.  Never blocks on
        the network: the caller is the engine pump."""
        if not self._closed:
            self._queue.put(("store", snapshot))

    def drop(self, request_id) -> None:
        if not self._closed:
            self._queue.put(("drop", _request_key(request_id)))

    def _drain(self) -> None:
        while True:
            kind, payload = self._queue.get()
            try:
                if kind == "stop":
                    return
                if kind == "drop":
                    with self._lock:
                        if self._kept.pop(payload, None) is not None:
                            self.counters["dropped"] += 1
                elif kind == "store":
                    self._ingest(payload)
                    # fenced/cancelled streams never send a clean drop:
                    # the periodic sweep bounds keeper memory to one
                    # max_age window of live traffic
                    self._stores_since_sweep += 1
                    if self._stores_since_sweep >= 64:
                        self._stores_since_sweep = 0
                        self.sweep()
            except Exception as error:
                # a failed delta (dead producer, expired keys) keeps
                # the PREVIOUS snapshot intact: restore degrades to a
                # longer re-decode, never to corruption
                self.counters["store_errors"] += 1
                _LOGGER.info("keeper %s: snapshot ingest failed "
                             "(previous snapshot kept): %s", self.name,
                             error)
            finally:
                self._queue.task_done()

    def _ingest(self, snapshot: dict) -> None:
        key = _request_key(snapshot["request_id"])
        blocks = snapshot.get("kv_blocks") or []
        delta_from = int(snapshot.get("delta_from", 0))
        blocks_total = int(snapshot.get("blocks_total",
                                        delta_from + len(blocks)))
        names = sorted(blocks[0]) if blocks else []
        descriptors = [block[name] for block in blocks
                       for name in names]
        arrays = fetch_many(descriptors) if descriptors else []
        fetched = []
        for index in range(len(blocks)):
            fetched.append({
                name: arrays[index * len(names) + offset]
                for offset, name in enumerate(names)})
        with self._lock:
            kept = self._kept.get(key)
            seq = int(snapshot.get("seq", 0))
            if kept is None or seq <= kept.seq and seq == 0:
                # seq 0 = a fresh request (or a preempted one restarting
                # from scratch): discard any previous incarnation
                kept = self._kept[key] = _Kept()
            elif seq <= kept.seq:
                return  # stale duplicate delivery
            elif seq != kept.seq + 1:
                # a delta between kept.seq and this one FAILED to
                # ingest: the block holding the last kept position was
                # due a re-ship that never landed, so everything from
                # it up to this delta's start is STALE.  Null the gap
                # -- restore's completeness check then degrades the
                # request to a re-prefill instead of silently serving
                # corrupt KV (the bit-identity guarantee)
                block_size = max(int(kept.meta.get("block_size", 1)), 1)
                stale_from = int(kept.meta.get("position", 0)) \
                    // block_size
                for index in range(stale_from,
                                   min(delta_from, len(kept.blocks))):
                    kept.blocks[index] = None
            kept.seq = seq
            kept.stored_at = time.monotonic()
            kept.meta = {k: v for k, v in snapshot.items()
                         if k != "kv_blocks"}
            if len(kept.blocks) < blocks_total:
                kept.blocks.extend(
                    [None] * (blocks_total - len(kept.blocks)))
            del kept.blocks[blocks_total:]
            for offset, block in enumerate(fetched):
                kept.blocks[delta_from + offset] = block
            self.counters["stored"] += 1
            self.counters["bytes"] += sum(
                array.nbytes for block in fetched
                for array in block.values())

    def flush(self, timeout: float = 5.0) -> bool:
        """Wait (bounded) for every queued delta to be ingested --
        restore calls this so a just-shipped snapshot is visible, and
        deterministic tests pin ingestion down with it."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._queue.unfinished_tasks == 0:
                return True
            time.sleep(0.002)
        return self._queue.unfinished_tasks == 0

    # -- restore (the failover path) -----------------------------------

    def restore(self, request_id) -> dict:
        """Build the restore record for one request: merged blocks
        re-offered on this process's transfer server + the resume
        state.  Raises KeyError when the keeper holds no (complete,
        fresh) snapshot -- the caller's re-prefill fallback."""
        self.flush(timeout=2.0)
        key = _request_key(request_id)
        with self._lock:
            kept = self._kept.get(key)
            if kept is None:
                raise KeyError(f"no checkpoint for {request_id!r}")
            if (self.max_age_s > 0
                    and time.monotonic() - kept.stored_at
                    > self.max_age_s):
                del self._kept[key]
                self.counters["expired"] += 1
                raise KeyError(f"checkpoint for {request_id!r} expired")
            if any(block is None for block in kept.blocks):
                raise KeyError(
                    f"checkpoint for {request_id!r} is incomplete "
                    f"(a delta ingest failed)")
            meta = dict(kept.meta)
            blocks = list(kept.blocks)
        server = get_transfer_server()
        kv_blocks = []
        total = 0
        for block in blocks:
            entry = {}
            for name in sorted(block):
                array = block[name]
                total += array.nbytes
                entry[name] = server.offer(array)
            kv_blocks.append(entry)
        self.counters["restored"] += 1
        record = {
            "schema": CHECKPOINT_SCHEMA,
            "request_id": meta.get("request_id"),
            "prompt": meta.get("prompt", []),
            "generated": meta.get("generated", []),
            "emitted_upto": meta.get("emitted_upto", 0),
            "max_new": meta.get("max_new", 0),
            "true_len": meta.get("true_len", 0),
            "position": meta.get("position", 0),
            "block_size": meta.get("block_size", 0),
            "kv_dtype": meta.get("kv_dtype", ""),
            "kv_bytes": int(total),
            "kv_blocks": kv_blocks,
        }
        return record

    # -- bookkeeping ---------------------------------------------------

    def sweep(self) -> int:
        """Drop snapshots older than max_age_s (fenced streams never
        send a clean drop; expiry bounds keeper memory)."""
        if self.max_age_s <= 0:
            return 0
        horizon = time.monotonic() - self.max_age_s
        with self._lock:
            stale = [key for key, kept in self._kept.items()
                     if kept.stored_at < horizon]
            for key in stale:
                del self._kept[key]
            self.counters["expired"] += len(stale)
        return len(stale)

    def kept_count(self) -> int:
        with self._lock:
            return len(self._kept)

    def kept_blocks(self, request_id) -> int:
        with self._lock:
            kept = self._kept.get(_request_key(request_id))
            return 0 if kept is None else len(kept.blocks)

    def stats(self) -> dict:
        with self._lock:
            kept = len(self._kept)
        return {"kept": kept, **self.counters}

    def stop(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._queue.put(("stop", None))


class DecodeCheckpointer:
    """Ships incremental decode-state snapshots from one DecodeEngine
    to a CheckpointKeeper.

    tick() runs after each engine step, ON the engine's event loop, but
    does only host work: a device->host gather of the delta blocks plus
    transfer-plane offers (the keeper pulls the bytes on its own
    thread).  A slot is due when `checkpoint_every` ticks passed since
    its last snapshot OR it has generated `max_checkpoint_lag` tokens
    since -- the forced snapshot is what makes max_checkpoint_lag a
    hard bound on crash-time re-decode, speculation bursts included."""

    def __init__(self, engine, policy: CheckpointPolicy,
                 keeper: "CheckpointKeeper | str | None" = None,
                 registry=None, node: str = "",
                 on_checkpoint=None):
        self.engine = engine
        self.policy = policy
        self._keeper = keeper if keeper is not None else policy.keeper
        self._registry = registry
        self.node = node or "decode"
        # on_checkpoint(node, elapsed_s, bytes): the telemetry seam
        # (PipelineTelemetry.record_checkpoint -- histogram + a global
        # engine span the tune loader classifies checkpoint-bound from)
        self._on_checkpoint = on_checkpoint
        self.ticks = 0
        self._state: dict = {}
        self.counters = {"checkpoints": 0, "checkpoint_bytes": 0,
                         "checkpoint_errors": 0}
        self._warned_keeper = False

    def keeper(self) -> CheckpointKeeper | None:
        if isinstance(self._keeper, CheckpointKeeper):
            return self._keeper
        keeper = get_keeper(str(self._keeper)) if self._keeper else None
        if keeper is None and not self._warned_keeper:
            self._warned_keeper = True
            _LOGGER.warning(
                "checkpoint keeper %r not registered: snapshots are "
                "skipped (failover degrades to re-prefill)",
                self._keeper)
        return keeper

    def _bump(self, name: str, amount) -> None:
        if self._registry is not None:
            self._registry.counter(name).inc(amount)

    def tick(self) -> int:
        """One cadence tick; returns the number of snapshots shipped.
        Never raises: a failed snapshot keeps the keeper's previous
        one, which only lengthens the re-decode on restore."""
        self.ticks += 1
        engine = self.engine
        shipped = 0
        if self.ticks % 64 == 0:
            # prune state for requests no longer anywhere in the
            # engine (cancelled / fenced streams never call forget):
            # entries hold the full _Request, so a long-lived replica
            # must not leak one per dead stream.  Periodic, not
            # per-tick: the live-set rebuild is O(slots + waiting) and
            # the hot loop should not pay it every step.  The keeper
            # side is bounded by its own sweep
            live = {_request_key(slot.request.request_id)
                    for slot in engine.slots if slot is not None}
            live |= {_request_key(request.request_id)
                     for request in engine.waiting}
            for key in [key for key in self._state
                        if key not in live]:
                del self._state[key]
        for index, slot in enumerate(engine.slots):
            if slot is None or slot.prefilling:
                continue
            request = slot.request
            key = _request_key(request.request_id)
            entry = self._state.get(key)
            if (entry is None or entry["request"] is not request
                    or len(request.generated) < entry["gen"]):
                # fresh slot, or a preempted request restarting from
                # scratch: the next snapshot re-ships from block 0
                entry = self._state[key] = {
                    "request": request, "gen": 0, "pos": 0,
                    "tick": self.ticks, "seq": -1}
            lag_tokens = len(request.generated) - entry["gen"]
            lag_ticks = self.ticks - entry["tick"]
            if lag_tokens <= 0:
                continue
            if (lag_ticks < self.policy.checkpoint_every
                    and lag_tokens < self.policy.max_checkpoint_lag):
                continue
            try:
                shipped += self._snapshot(index, slot, entry,
                                          lag_ticks)
            except Exception as error:
                self.counters["checkpoint_errors"] += 1
                self._bump("decode.checkpoint_errors", 1)
                _LOGGER.info("checkpoint of %r failed (previous "
                             "snapshot kept): %s", key, error)
        return shipped

    def _snapshot(self, index: int, slot, entry: dict,
                  lag_ticks: int) -> int:
        keeper = self.keeper()
        if keeper is None:
            return 0
        from .disagg import offer_pool_blocks
        engine = self.engine
        request = slot.request
        started = time.perf_counter()
        position = int(engine.positions[index])
        coverage = engine.blocks.blocks_for(position)
        # KV is append-only: everything below the last snapshot's
        # position is immutable, so the delta is the (possibly
        # partial, hence re-shipped) block holding that position plus
        # every block after it
        delta_from = entry["pos"] // engine.blocks.block_size
        block_ids = slot.blocks[delta_from:coverage]
        kv_blocks, total = offer_pool_blocks(engine.pool, block_ids)
        snapshot = {
            "schema": CHECKPOINT_SCHEMA,
            "request_id": request.request_id,
            "prompt": [int(token) for token in request.prompt],
            "generated": [int(token) for token in request.generated],
            "emitted_upto": int(request.emitted_upto),
            "max_new": int(request.max_new),
            "true_len": int(slot.true_len),
            "position": position,
            "block_size": engine.blocks.block_size,
            "kv_dtype": engine.config.kv_dtype or "",
            "blocks_total": coverage,
            "delta_from": delta_from,
            "seq": entry["seq"] + 1,
        }
        snapshot["kv_blocks"] = kv_blocks
        keeper.store(snapshot)
        entry.update(gen=len(request.generated), pos=position,
                     tick=self.ticks, seq=entry["seq"] + 1)
        self.counters["checkpoints"] += 1
        self.counters["checkpoint_bytes"] += total
        self._bump("decode.checkpoints", 1)
        self._bump("decode.checkpoint_bytes", total)
        if self._registry is not None:
            self._registry.histogram(
                "decode.checkpoint_lag_ticks").record(lag_ticks)
        if self._on_checkpoint is not None:
            self._on_checkpoint(self.node,
                                time.perf_counter() - started, total)
        return 1

    def forget(self, request_id) -> None:
        """A request completed cleanly: drop its snapshots.  Fenced
        streams deliberately do NOT forget -- the keeper's snapshot is
        exactly what the survivor restores from; expiry sweeps the
        strays."""
        key = _request_key(request_id)
        self._state.pop(key, None)
        keeper = self.keeper()
        if keeper is not None:
            keeper.drop(key)

    def stats(self) -> dict:
        return {"ticks": self.ticks, "tracked": len(self._state),
                **self.counters}
