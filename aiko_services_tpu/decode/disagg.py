# Prefill/decode disaggregation: the prefill half of the split fleet.
#
# Production serving splits prefill and decode into separate replica
# pools (DistServe OSDI'24, Splitwise ISCA'24) because a long prompt's
# compute-bound prefill kernel convoys every co-scheduled decode slot
# -- the longcontext roofline on record is 1.94 s of kernel time for a
# 16k prompt.  Chunked prefill (PR 10) bounds the stall but still
# spends decode-replica cycles on prompt compute; disaggregation moves
# the prompt compute onto a PREFILL pool entirely and streams the
# finished prompt's paged KV blocks to a decode replica over the
# transfer plane (pipeline/transfer.py).
#
#   PrefillEngine   runs paged_prefill / paged_prefill_chunk into its
#                   own paged pool, one request at a time (a prefill
#                   replica's whole job is the prompt kernel; there is
#                   no co-scheduled decode to protect), and EXPORTS the
#                   finished prompt's KV blocks as a `__tensorref__`
#                   descriptor tree -- one descriptor per (block, pool
#                   leaf), so int8 KV (codes + scales) carries through
#                   unchanged
#   fetch_kv_blocks the decode-side half: pulls a handoff's whole
#                   descriptor tree through fetch_many (ONE connection
#                   per producing peer, not one TCP handshake per
#                   block) and restacks it into per-leaf arrays shaped
#                   for a pool scatter
#
# DecodeEngine.adopt_request (engine.py) consumes the handoff: blocks
# fetched into a free slot, block table rewritten, greedy decode
# continues from the prompt end BIT-IDENTICALLY to the co-located
# engine -- the transferred K/V are exact copies of what a local
# prefill would have written, and the writes-before-gather invariant
# covers the garbage tail of the last prompt block exactly as it
# covers local prefill's bucket padding.
#
# The handoff record is JSON-safe end to end (prompt token list +
# descriptor dicts), so it rides the ordinary frame codec between
# gateway, prefill replica, and decode replica.

from __future__ import annotations

import time

from collections import deque

import numpy as np

from ..models import (
    init_paged_pool, paged_prefill, paged_prefill_chunk)
from ..pipeline.transfer import fetch_many, get_transfer_server
from ..utils import get_logger
from ..utils.padding import bucket_length
from .blocks import TRASH_BLOCK, BlockManager

__all__ = ["HANDOFF_SCHEMA", "PrefillEngine", "fetch_kv_blocks",
           "offer_pool_blocks"]

_LOGGER = get_logger("prefill_engine")

HANDOFF_SCHEMA = "aiko.kv_handoff/1"


def offer_pool_blocks(pool: dict, block_ids) -> tuple:
    """Offer `block_ids`' slices of every pool leaf on this process's
    transfer server as RAW descriptors (never `{__tensorref__: ...}`
    marker nodes -- see fetch_kv_blocks); returns (kv_blocks, bytes)
    where kv_blocks is one {leaf_name: descriptor} dict per block.
    Shared by PrefillEngine's handoff export and the decode-state
    checkpointer (decode/checkpoint.py): one device->host gather per
    leaf, then per-block host views."""
    server = get_transfer_server()
    block_ids = np.asarray(block_ids)
    host = {name: np.asarray(leaf[:, block_ids])
            for name, leaf in pool.items()}
    kv_blocks = []
    total_bytes = 0
    for index in range(len(block_ids)):
        entry = {}
        for name in sorted(host):
            view = host[name][:, index]
            total_bytes += view.nbytes
            entry[name] = server.offer(view)
        kv_blocks.append(entry)
    return kv_blocks, total_bytes


def fetch_kv_blocks(handoff: dict, timeout: float | None = None) -> dict:
    """Fetch a handoff's KV blocks in ONE batched round trip per peer
    and restack them for the pool scatter: returns {leaf_name: array of
    shape (n_layers, n_blocks, ...)} matching init_paged_pool's leaf
    layout.  Raises KeyError/TransferError exactly like fetch_many --
    the adopting engine turns either into a local re-prefill.

    The handoff carries RAW transfer descriptors (the {host, port,
    key, dtype, shape} dicts fetch() consumes), deliberately NOT
    `{__tensorref__: ...}` marker nodes: the frame codec eagerly
    materializes marker nodes one fetch at a time on the consumer's
    event loop, which would both serialize the migration and strip
    the descriptors before adopt_request ever saw them."""
    blocks = handoff["kv_blocks"]
    if not blocks:
        raise ValueError("handoff carries no KV blocks")
    names = sorted(blocks[0])
    descriptors = [block[name] for block in blocks for name in names]
    arrays = fetch_many(descriptors, timeout=timeout)
    leaves = {}
    for offset, name in enumerate(names):
        per_block = arrays[offset::len(names)]
        # (n_blocks, n_layers, heads, block, depth) -> pool layout
        # (n_layers, n_blocks, heads, block, depth)
        leaves[name] = np.stack(per_block, axis=1)
    return leaves


class _PrefillJob:
    __slots__ = ("request_id", "prompt", "max_new", "true_len",
                 "bucket", "padded", "blocks", "prefill_pos",
                 "submitted_at", "started_at")

    def __init__(self, request_id, prompt, max_new):
        self.request_id = request_id
        self.prompt = prompt
        self.max_new = int(max_new)
        self.true_len = int(prompt.size)
        self.bucket = 0
        self.padded = None
        self.blocks: list = []
        self.prefill_pos = 0
        self.submitted_at = time.perf_counter()
        self.started_at: float | None = None


class PrefillEngine:
    """Single-flight prompt prefill over a private paged pool.

    Shapes fixed at construction like DecodeEngine's (one block table
    row wide enough for max_context), so a warmed prefill replica
    never recompiles.  step() advances the active job by one chunk
    (or the whole prompt when chunking is off) and returns the list of
    handoff records that finished this tick -- each with the prompt's
    KV blocks ALREADY offered on the transfer plane and the job's
    blocks returned to the free list (the transfer server holds host
    copies for the offer ttl; a handoff nobody adopts costs linger
    memory, never pool capacity)."""

    def __init__(self, params, config, *, kv_block_size: int = 16,
                 kv_blocks: int | None = None,
                 max_context: int | None = None,
                 prefill_chunk_size: int | None = None, registry=None):
        self.params = params
        self.config = config
        max_context = int(max_context or config.max_seq_len)
        self.max_blocks = -(-max_context // int(kv_block_size))
        self.max_context = self.max_blocks * int(kv_block_size)
        if kv_blocks is None:
            kv_blocks = self.max_blocks + 1
        self.blocks = BlockManager(int(kv_blocks), int(kv_block_size))
        self.pool = init_paged_pool(config, self.blocks.num_blocks,
                                    self.blocks.block_size)
        self.table = np.full((self.max_blocks,), TRASH_BLOCK, np.int32)
        self.waiting: deque[_PrefillJob] = deque()
        self._active: _PrefillJob | None = None
        self._registry = registry
        if prefill_chunk_size is not None:
            chunk = bucket_length(int(prefill_chunk_size),
                                  minimum=self.blocks.block_size)
            self.prefill_chunk = int(min(chunk, self.max_context))
        else:
            self.prefill_chunk = None
        self.counters = {"submitted": 0, "exported": 0, "chunks": 0,
                         "compiles": 0, "exported_bytes": 0}

    def _jit_cache_size(self) -> int:
        return (paged_prefill._cache_size()
                + paged_prefill_chunk._cache_size())

    @property
    def compile_count(self) -> int:
        return self.counters["compiles"]

    def _note_compiles(self, delta: int) -> None:
        if delta > 0:
            self.counters["compiles"] += delta
            self._bump("prefill.compiles", delta)

    def _bump(self, name: str, amount) -> None:
        if self._registry is not None:
            self._registry.counter(name).inc(amount)

    # -- submission --------------------------------------------------------

    def _bucket(self, true_len: int) -> int:
        block = self.blocks.block_size
        padded = bucket_length(true_len, minimum=block)
        return min(-(-padded // block) * block, self.max_context)

    def submit(self, request_id, prompt_tokens, max_new_tokens: int):
        prompt = np.asarray(prompt_tokens, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError(f"{request_id}: empty prompt")
        if int(max_new_tokens) < 1:
            raise ValueError(
                f"{request_id}: max_new_tokens must be >= 1")
        if prompt.size + int(max_new_tokens) > self.max_context:
            raise ValueError(
                f"{request_id}: prompt {prompt.size} + max_new "
                f"{int(max_new_tokens)} exceeds max_context "
                f"{self.max_context} (the ADOPTING pool's contract)")
        self.waiting.append(
            _PrefillJob(request_id, prompt, max_new_tokens))
        self.counters["submitted"] += 1

    def cancel(self, predicate) -> int:
        """Drop every job whose request_id satisfies `predicate`; a
        cancelled ACTIVE job's blocks return to the free list.
        Returns the number cancelled."""
        cancelled = 0
        kept = deque()
        for job in self.waiting:
            if predicate(job.request_id):
                cancelled += 1
            else:
                kept.append(job)
        self.waiting = kept
        if (self._active is not None
                and predicate(self._active.request_id)):
            self.blocks.free(self._active.blocks)
            self._active = None
            cancelled += 1
        return cancelled

    def has_work(self) -> bool:
        return self._active is not None or bool(self.waiting)

    @property
    def queue_depth(self) -> int:
        """Jobs not yet finished -- the signal the prefill pool's
        autoscaler watches (queue wait, not slot occupancy)."""
        return len(self.waiting) + (1 if self._active else 0)

    # -- the engine step ---------------------------------------------------

    def step(self) -> list:
        """Advance the active prefill by one chunk (or run it whole);
        returns the handoff records that finished this tick."""
        if self._active is None:
            if not self.waiting:
                return []
            job = self.waiting.popleft()
            job.started_at = time.perf_counter()
            job.bucket = self._bucket(job.true_len)
            granted = self.blocks.allocate(
                self.blocks.blocks_for(job.bucket))
            # the pool is sized for max_context and jobs run one at a
            # time, so a grant can never fail here
            job.blocks = granted
            job.padded = np.zeros((job.bucket,), np.int32)
            job.padded[:job.true_len] = job.prompt
            self.table[:] = TRASH_BLOCK
            self.table[:len(granted)] = granted
            self._active = job
        job = self._active
        if (self.prefill_chunk is None
                or self.prefill_chunk >= job.bucket):
            before = self._jit_cache_size()
            self.pool, first = paged_prefill(
                self.params, self.config, self.pool, job.padded[None],
                self.table, np.int32(job.true_len))
            self._note_compiles(self._jit_cache_size() - before)
            job.prefill_pos = job.bucket
            return [self._finish(job, int(first))]
        return self._step_chunk(job)

    def _step_chunk(self, job: _PrefillJob) -> list:
        block_size = self.blocks.block_size
        start = job.prefill_pos
        remaining = job.true_len - start
        size = min(self.prefill_chunk,
                   bucket_length(remaining, minimum=block_size))
        take = min(size, remaining)
        chunk = np.zeros((1, size), np.int32)
        chunk[0, :take] = job.padded[start:start + take]
        write_blocks = np.full((size,), TRASH_BLOCK, np.int32)
        write_offsets = np.zeros((size,), np.int32)
        for offset in range(size):
            position = start + offset
            if position < job.true_len:
                write_blocks[offset] = job.blocks[position // block_size]
            write_offsets[offset] = position % block_size
        before = self._jit_cache_size()
        self.pool, greedy = paged_prefill_chunk(
            self.params, self.config, self.pool, chunk, self.table,
            np.int32(start), write_blocks, write_offsets)
        self._note_compiles(self._jit_cache_size() - before)
        self.counters["chunks"] += 1
        self._bump("prefill.chunks", 1)
        job.prefill_pos = start + take
        if job.prefill_pos < job.true_len:
            return []
        first = int(np.asarray(greedy)[job.true_len - 1 - start])
        return [self._finish(job, first)]

    # -- export ------------------------------------------------------------

    def _finish(self, job: _PrefillJob, first: int) -> dict:
        """Offer the prompt's KV blocks on the transfer plane and build
        the handoff record.  Only blocks holding TRUE prompt positions
        travel: the bucket-padding tail past true_len is garbage the
        adopting engine overwrites before its gather reaches it, and
        whole blocks past the prompt hold nothing at all."""
        used = self.blocks.blocks_for(job.true_len)
        # RAW descriptors, not {TENSOR_REF_KEY: ...} markers: see
        # fetch_kv_blocks -- the frame codec must carry these inert so
        # the ADOPTING engine batch-fetches
        kv_blocks, total_bytes = offer_pool_blocks(
            self.pool, job.blocks[:used])
        self.blocks.free(job.blocks)
        job.blocks = []
        self._active = None
        now = time.perf_counter()
        self.counters["exported"] += 1
        self.counters["exported_bytes"] += total_bytes
        self._bump("prefill.exports", 1)
        self._bump("prefill.exported_bytes", total_bytes)
        if self._registry is not None:
            self._registry.histogram("prefill.queue_wait_s").record(
                (job.started_at or now) - job.submitted_at)
            self._registry.histogram("prefill.prefill_s").record(
                now - (job.started_at or now))
        return {
            "schema": HANDOFF_SCHEMA,
            "request_id": job.request_id,
            "prompt": [int(token) for token in job.prompt],
            "max_new": job.max_new,
            "true_len": job.true_len,
            "first_token": int(first),
            "block_size": self.blocks.block_size,
            "kv_dtype": self.config.kv_dtype or "",
            "kv_bytes": int(total_bytes),
            "queue_wait_s": round(
                (job.started_at or now) - job.submitted_at, 6),
            "prefill_s": round(now - (job.started_at or now), 6),
            "kv_blocks": kv_blocks,
        }

    def stats(self) -> dict:
        stats = {
            "waiting": len(self.waiting),
            "active": 1 if self._active else 0,
            "block_size": self.blocks.block_size,
            "free_blocks": self.blocks.free_count,
            **self.counters,
        }
        if self.prefill_chunk is not None:
            stats["prefill_chunk_size"] = self.prefill_chunk
        return stats
