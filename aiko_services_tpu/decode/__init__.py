# Continuous batching with paged KV: the slot-based decode engine
# under LMGenerate's `continuous: true` mode and the serving gateway.
#
#   blocks.py   BlockManager -- fixed-size KV block pool bookkeeping
#   engine.py   DecodeEngine -- slot scheduler: mid-decode admission /
#               eviction / preemption with zero recompiles, chunked
#               prefill (prefill_chunk_size) interleaved with decode,
#               and greedy-exact speculative decoding (draft model +
#               spec_k verify windows)
#   disagg.py   PrefillEngine -- the prefill half of a disaggregated
#               fleet: prompt kernels into a private paged pool, KV
#               blocks exported as a transfer-plane descriptor tree
#               that DecodeEngine.adopt_request fetches into a free
#               slot over the transfer plane (no re-prefill)
#   checkpoint.py  warm KV failover -- DecodeCheckpointer ships
#               incremental decode-state snapshots to a
#               CheckpointKeeper so a crashed replica's streams
#               restore on a survivor (DecodeEngine.restore_request)
#               instead of re-prefilling; AIKO409 policy grammar
#   prefix.py   cross-request prefix KV reuse -- PrefixCache indexes
#               fully-written prompt blocks by token hash chain so
#               later admissions borrow the shared prefix (COW,
#               refcounted, LRU second-chance eviction) and only
#               tail-prefill the uncached rest; AIKO411 policy grammar
#
# Device kernels live in models/transformer.py (init_paged_pool,
# paged_prefill, paged_prefill_chunk, paged_decode_step,
# paged_verify_step) next to the closed-batch generate() they must
# stay bit-compatible with.

from .blocks import BlockManager, TRASH_BLOCK      # noqa: F401
from .engine import Completion, DecodeEngine, StepReport  # noqa: F401
from .disagg import (                              # noqa: F401
    HANDOFF_SCHEMA, PrefillEngine, fetch_kv_blocks,
    offer_pool_blocks)
from .checkpoint import (                          # noqa: F401
    CHECKPOINT_SCHEMA, CheckpointKeeper, CheckpointPolicy,
    DecodeCheckpointer, get_keeper, register_keeper, reset_keepers)
from .prefix import (                              # noqa: F401
    PREFIX_GRAMMAR, PrefixCache, PrefixPolicy, chain_hashes,
    prefix_head)

__all__ = ["BlockManager", "TRASH_BLOCK", "CHECKPOINT_SCHEMA",
           "CheckpointKeeper", "CheckpointPolicy", "Completion",
           "DecodeCheckpointer", "DecodeEngine", "HANDOFF_SCHEMA",
           "PREFIX_GRAMMAR", "PrefillEngine", "PrefixCache",
           "PrefixPolicy", "StepReport", "chain_hashes",
           "fetch_kv_blocks", "get_keeper", "offer_pool_blocks",
           "prefix_head", "register_keeper", "reset_keepers"]
