# Console entry points.
#
# Capability parity with the reference's console scripts (reference:
# pyproject.toml:60-64 -- aiko_registrar, aiko_pipeline, aiko_dashboard,
# plus storage/recorder mains): one click group, `python -m
# aiko_services_tpu <command>`.

from __future__ import annotations

import click


@click.group()
def main() -> None:
    """aiko_services_tpu: TPU-native distributed ML pipeline framework."""


@main.command()
@click.option("--name", default="registrar")
@click.option("--transport", default=None,
              help="loopback | mqtt | null (default: auto from env)")
def registrar(name: str, transport: str | None) -> None:
    """Run a service-discovery registrar."""
    from .runtime import Process, Registrar
    process = Process(transport_kind=transport)
    Registrar(process, name=name)
    process.run()


@main.command()
@click.argument("definition", type=click.Path(exists=True))
@click.option("--name", default=None)
@click.option("--transport", default=None)
@click.option("--stream-id", default=None,
              help="Create this stream immediately")
@click.option("--stream-parameters", default="{}",
              help="JSON stream parameters")
@click.option("--frame-data", default=None,
              help="JSON frame data posted to the created stream")
@click.option("--grace-time", default=60.0)
def pipeline(definition: str, name: str | None, transport: str | None,
             stream_id: str | None, stream_parameters: str,
             frame_data: str | None, grace_time: float) -> None:
    """Create and run a pipeline from a JSON definition (reference
    `aiko_pipeline create`, pipeline.py:1444-1528)."""
    import json

    from .pipeline import create_pipeline
    from .runtime import Process
    process = Process(transport_kind=transport)
    pipeline_instance = create_pipeline(process, definition, name=name)
    if stream_id is not None:
        pipeline_instance.create_stream(
            stream_id, parameters=json.loads(stream_parameters),
            grace_time=grace_time)
        if frame_data is not None:
            pipeline_instance.process_frame(
                {"stream_id": stream_id}, json.loads(frame_data))
    process.run()


@main.command()
@click.option("--name", default="storage")
@click.option("--database", default="storage.db")
@click.option("--transport", default=None)
def storage(name: str, database: str, transport: str | None) -> None:
    """Run a sqlite storage service."""
    from .runtime import Process, Storage
    process = Process(transport_kind=transport)
    Storage(process, name=name, database_path=database)
    process.run()


@main.command()
@click.option("--name", default="recorder")
@click.option("--topic", default=None, help="Log topic pattern")
@click.option("--transport", default=None)
def recorder(name: str, topic: str | None, transport: str | None) -> None:
    """Run a log-aggregation recorder service."""
    from .runtime import Process, Recorder
    process = Process(transport_kind=transport)
    Recorder(process, name=name, log_topic_pattern=topic)
    process.run()


@main.command()
@click.option("--transport", default=None)
@click.option("--snapshot", is_flag=True,
              help="Print one services-table snapshot and exit")
@click.option("--wait", default=3.0,
              help="Seconds to wait for discovery in snapshot mode")
def dashboard(transport: str | None, snapshot: bool, wait: float) -> None:
    """Service dashboard: curses TUI, or --snapshot for plain text."""
    from .dashboard import run_dashboard
    run_dashboard(transport_kind=transport, snapshot=snapshot, wait=wait)


@main.command()
def bench() -> None:
    """Run the standard benchmark (one JSON line)."""
    import runpy
    from pathlib import Path
    bench_path = Path(__file__).resolve().parent.parent / "bench.py"
    runpy.run_path(str(bench_path), run_name="__main__")


@main.command()
@click.option("--port", default=None, type=int,
              help="UDP port to answer on (default 4149)")
@click.option("--mqtt-host", default=None,
              help="Broker host to advertise (default: resolved from "
                   "AIKO_MQTT_HOST/AIKO_MQTT_HOSTS with a TCP probe)")
@click.option("--mqtt-port", default=None, type=int)
def bootstrap(port: int | None, mqtt_host: str | None,
              mqtt_port: int | None) -> None:
    """MCU bootstrap responder: answers UDP boot datagrams with the
    namespace + broker endpoint (reference configuration.py:168-186)."""
    import signal
    import time

    from .utils import BootstrapResponder
    kwargs = {"mqtt_host": mqtt_host, "mqtt_port": mqtt_port}
    if port is not None:
        kwargs["port"] = port
    responder = BootstrapResponder(**kwargs)
    click.echo(f"bootstrap responder on udp/{responder.port} advertising "
               f"{responder.mqtt_host}:{responder.mqtt_port}")
    stop = []
    signal.signal(signal.SIGTERM, lambda *_: stop.append(True))
    signal.signal(signal.SIGINT, lambda *_: stop.append(True))
    while not stop:
        time.sleep(0.2)
    responder.close()


if __name__ == "__main__":
    main()
