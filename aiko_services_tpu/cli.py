# Console entry points.
#
# Capability parity with the reference's console scripts (reference:
# pyproject.toml:60-64 -- aiko_registrar, aiko_pipeline, aiko_dashboard,
# plus storage/recorder mains): one click group, `python -m
# aiko_services_tpu <command>`.

from __future__ import annotations

import click


@click.group()
def main() -> None:
    """aiko_services_tpu: TPU-native distributed ML pipeline framework."""


@main.command()
@click.option("--name", default="registrar")
@click.option("--transport", default=None,
              help="loopback | mqtt | null (default: auto from env)")
def registrar(name: str, transport: str | None) -> None:
    """Run a service-discovery registrar."""
    from .runtime import Process, Registrar
    process = Process(transport_kind=transport)
    Registrar(process, name=name)
    process.run()


@main.command()
@click.argument("definition", type=click.Path(exists=True))
@click.option("--name", default=None)
@click.option("--transport", default=None)
@click.option("--stream-id", default=None,
              help="Create this stream immediately")
@click.option("--stream-parameters", default="{}",
              help="JSON stream parameters")
@click.option("--frame-data", default=None,
              help="JSON frame data posted to the created stream")
@click.option("--grace-time", default=60.0)
def pipeline(definition: str, name: str | None, transport: str | None,
             stream_id: str | None, stream_parameters: str,
             frame_data: str | None, grace_time: float) -> None:
    """Create and run a pipeline from a JSON definition (reference
    `aiko_pipeline create`, pipeline.py:1444-1528).

    Elastic-fleet children honor two env knobs set by the replica
    factory (serve/autoscale.py): AIKO_COMPILE_CACHE points JAX's
    persistent compilation cache at the fleet's shared directory, and
    AIKO_WARM_WEIGHTS names a descriptor file whose tensors are
    fetched from a live sibling over the transfer plane instead of
    re-running setup()."""
    import json
    import os

    from .pipeline import create_pipeline
    from .runtime import Process, enable_compile_cache
    enable_compile_cache()  # no-op unless AIKO_COMPILE_CACHE is set
    process = Process(transport_kind=transport)
    pipeline_instance = create_pipeline(process, definition, name=name)
    warm_weights = os.environ.get("AIKO_WARM_WEIGHTS")
    if warm_weights:
        # a failed hand-off (expired transfer keys, drained sibling)
        # downgrades to a COLD start -- setup() runs lazily as usual;
        # dying here would turn a scale-up into a failed spawn
        try:
            with open(warm_weights) as handoff:
                installed = pipeline_instance.import_weights(
                    json.load(handoff))
            click.echo(f"warm start: imported weights for {installed}")
        except Exception as error:
            click.echo(f"warm start failed ({error}); starting cold",
                       err=True)
        finally:
            try:  # one-shot descriptor file from the replica factory
                os.unlink(warm_weights)
            except OSError:
                pass
    if stream_id is not None:
        pipeline_instance.create_stream(
            stream_id, parameters=json.loads(stream_parameters),
            grace_time=grace_time)
        if frame_data is not None:
            pipeline_instance.process_frame(
                {"stream_id": stream_id}, json.loads(frame_data))
    process.run()


@main.command()
@click.option("--name", default="storage")
@click.option("--database", default="storage.db")
@click.option("--transport", default=None)
def storage(name: str, database: str, transport: str | None) -> None:
    """Run a sqlite storage service."""
    from .runtime import Process, Storage
    process = Process(transport_kind=transport)
    Storage(process, name=name, database_path=database)
    process.run()


@main.command()
@click.option("--name", default="recorder")
@click.option("--topic", default=None, help="Log topic pattern")
@click.option("--transport", default=None)
def recorder(name: str, topic: str | None, transport: str | None) -> None:
    """Run a log-aggregation recorder service."""
    from .runtime import Process, Recorder
    process = Process(transport_kind=transport)
    Recorder(process, name=name, log_topic_pattern=topic)
    process.run()


@main.command()
@click.option("--transport", default=None)
@click.option("--snapshot", is_flag=True,
              help="Print one services-table snapshot and exit")
@click.option("--wait", default=3.0,
              help="Seconds to wait for discovery in snapshot mode")
def dashboard(transport: str | None, snapshot: bool, wait: float) -> None:
    """Service dashboard: curses TUI, or --snapshot for plain text."""
    from .dashboard import run_dashboard
    run_dashboard(transport_kind=transport, snapshot=snapshot, wait=wait)


@main.command()
@click.argument("sources", nargs=-1, type=click.Path())
@click.option("--strict", is_flag=True,
              help="Fail on warnings too (errors always fail)")
@click.option("--format", "fmt",
              type=click.Choice(["text", "json"]), default="text")
@click.option("--output", default=None, type=click.Path(),
              help="Also write the report to this file")
@click.option("--passes", "passes_option", default=None,
              help="Comma-separated pass list "
                   "(graph,policy,actor,eval); default: all")
@click.option("--bench", "bench_configs", is_flag=True,
              help="Also lint every pipeline definition bench.py "
                   "constructs")
@click.option("--golden", default=None,
              type=click.Path(exists=True, file_okay=False),
              help="Verify a corpus of deliberately-broken definitions:"
                   " each <code>_*.json (or <code>_*.py for the AIKO6xx"
                   " concurrency pass) must produce that rule code")
@click.option("--code", "code_mode", is_flag=True,
              help="Concurrency lint (AIKO6xx) over Python SOURCE "
                   "files/trees instead of pipeline definitions")
@click.option("--baseline", default=None, type=click.Path(),
              help="(--code) accepted-findings baseline JSON: matches "
                   "are filtered, stale entries surface as AIKO600")
@click.option("--update-baseline", "update_baseline", is_flag=True,
              help="(--code) rewrite --baseline from the current "
                   "findings and exit 0")
def lint(sources, strict, fmt, output, passes_option, bench_configs,
         golden, code_mode, baseline, update_baseline) -> None:
    """Statically verify pipeline definitions (analyze/ subsystem).

    SOURCES are definition JSON files or directories (searched
    recursively for *.json).  Four passes: graph/port dataflow
    (AIKO1xx), tensor-spec shape/dtype flow (AIKO2xx, including a
    jax.eval_shape dry-run of element device programs), element/actor
    safety (AIKO3xx), and policy grammars (AIKO4xx).  With --code,
    SOURCES are Python files/trees and the AIKO6xx static concurrency
    pass runs instead (thread-role inference over the actor fleet;
    see README "Concurrency model").  Exit status: 0 clean, 1 findings
    (with --strict, warnings count), 2 usage error.
    """
    import sys
    from pathlib import Path

    from .analyze import ALL_PASSES, AnalysisReport, analyze_definition

    if code_mode:
        sys.exit(_lint_code(sources, strict, fmt, output, baseline,
                            update_baseline))
    if baseline or update_baseline:
        click.echo("--baseline/--update-baseline need --code", err=True)
        sys.exit(2)

    passes = (tuple(part.strip() for part in passes_option.split(",")
                    if part.strip())
              if passes_option else ALL_PASSES)
    unknown = [name for name in passes if name not in ALL_PASSES]
    if unknown:
        click.echo(f"unknown passes: {unknown} (valid: {ALL_PASSES})",
                   err=True)
        sys.exit(2)

    if golden is not None:
        sys.exit(_lint_golden(Path(golden), passes))

    targets: list = []
    for source in sources:
        path = Path(source)
        if path.is_dir():
            targets.extend(sorted(path.rglob("*.json")))
        else:
            targets.append(path)
    if bench_configs:
        import runpy
        bench_path = Path(__file__).resolve().parent.parent / "bench.py"
        if not bench_path.is_file():
            click.echo(f"--bench needs a source checkout: {bench_path} "
                       f"not found", err=True)
            sys.exit(2)
        bench_module = runpy.run_path(str(bench_path))
        for name, definition in sorted(
                bench_module["collect_definitions"]().items()):
            targets.append((f"bench.py::{name}", definition))
    if not targets:
        click.echo("nothing to lint (give files, directories, or "
                   "--bench)", err=True)
        sys.exit(2)

    report = AnalysisReport()
    for target in targets:
        if isinstance(target, tuple):
            label, source = target
        else:
            label, source = str(target), target
        report.extend(analyze_definition(source, passes=passes,
                                         source_path=label))
    rendered = (report.to_json() if fmt == "json"
                else report.render())
    click.echo(rendered)
    if output:
        Path(output).write_text(
            rendered if rendered.endswith("\n") else rendered + "\n")
    sys.exit(1 if report.failures(strict=strict) else 0)


def _lint_code(sources, strict, fmt, output, baseline,
               update_baseline) -> int:
    """`aiko lint --code`: the AIKO6xx static concurrency pass over
    Python source trees, optionally diffed against a committed
    baseline of accepted findings.  Returns the exit status."""
    import sys
    from pathlib import Path

    from .analyze import (
        apply_baseline, load_baseline, run_code_pass, write_baseline)

    if not sources:
        click.echo("nothing to lint (give Python files or directories)",
                   err=True)
        return 2
    missing = [source for source in sources
               if not Path(source).exists()]
    if missing:
        click.echo(f"no such path(s): {missing}", err=True)
        return 2
    report = run_code_pass([Path(source) for source in sources])
    if update_baseline:
        if not baseline:
            click.echo("--update-baseline needs --baseline PATH",
                       err=True)
            return 2
        count = write_baseline(baseline, report)
        click.echo(f"baseline written: {count} accepted finding(s) -> "
                   f"{baseline}")
        return 0
    if baseline:
        try:
            entries = load_baseline(baseline)
        except (OSError, ValueError) as error:
            click.echo(f"cannot read baseline: {error}", err=True)
            return 2
        filtered = apply_baseline(report, entries)
        click.echo(f"baseline: {filtered} accepted finding(s) "
                   f"filtered", err=True)
    rendered = (report.to_json() if fmt == "json" else report.render())
    click.echo(rendered)
    if output:
        Path(output).write_text(
            rendered if rendered.endswith("\n") else rendered + "\n")
    return 1 if report.failures(strict=strict) else 0


def _lint_golden(corpus: "Path", passes) -> int:
    """Golden-corpus mode: every `<code>_*.json` in the corpus must
    yield a finding with that code -- the proof each lint rule still
    fires.  `<code>_*.py` fixtures run through the AIKO6xx concurrency
    pass the same way.  Returns the exit status."""
    from .analyze import RULES, analyze_definition, run_code_pass

    failures = 0
    checked = 0
    for path in sorted(corpus.glob("*.json")) + sorted(
            corpus.glob("*.py")):
        expected = path.stem.split("_", 1)[0].upper()
        if expected not in RULES:
            click.echo(f"SKIP {path.name}: no rule code prefix")
            continue
        checked += 1
        if path.suffix == ".py":
            report = run_code_pass([path], root=corpus)
        else:
            report = analyze_definition(path, passes=passes,
                                        source_path=str(path))
        codes = {diagnostic.code for diagnostic in report.findings}
        if expected in codes:
            click.echo(f"ok   {path.name}: {expected} fired")
        else:
            failures += 1
            click.echo(f"FAIL {path.name}: expected {expected}, got "
                       f"{sorted(codes) or 'no findings'}")
    click.echo(f"{checked} golden definition(s), {failures} failure(s)")
    return 1 if failures or not checked else 0


@main.group()
def deadletter() -> None:
    """Inspect and drain dead-lettered frames after a recovered
    outage: `ls` lists the Recorder's dead-letter ring, `replay`
    re-submits a selected frame through the serving gateway (frames
    small enough to embed their encoded inputs replay exactly; larger
    ones are descriptor-only evidence)."""


def fetch_dead_letters(process, wait: float = 3.0) -> list:
    """Drain the first discovered Recorder's dead-letter ring: decoded
    {"index", "topic", "meta", "descriptor"} records, oldest first.
    Shared by `aiko deadletter ls|replay` and tests."""
    import json
    import threading

    from .runtime import ServiceFilter
    from .runtime.recorder import SERVICE_PROTOCOL_RECORDER
    from .runtime.storage import do_request

    done = threading.Event()
    collected: list = []

    def on_items(items):
        collected.extend(items)
        done.set()

    do_request(process, ServiceFilter(protocol=SERVICE_PROTOCOL_RECORDER),
               lambda proxy, response_topic:
               proxy.deadletters(response_topic),
               on_items)
    done.wait(wait)
    records = []
    for item in collected:
        try:
            records.append(json.loads(item))
        except (TypeError, ValueError):
            continue
    return records


def replay_dead_letter(process, record: dict, gateway_topic: str,
                       create: bool = True, grace_time: float = 60.0,
                       topic_response: str = "") -> bool:
    """Re-submit one dead-lettered frame through a gateway: optionally
    (re)create the stream (a duplicate create gets a harmless typed
    reject), then publish the EXACT embedded frame data under its
    original stream/frame identity -- the gateway's exactly-once dedupe
    makes replay idempotent.  `topic_response` routes the outcome back
    to the caller.  Returns False when the record carries no embedded
    data (it exceeded AIKO_DEAD_LETTER_DATA_MAX)."""
    import json

    from .utils import generate

    meta = record.get("meta") or {}
    data = meta.get("data")
    if not data:
        return False
    stream_id = str(meta.get("stream_id", ""))
    frame_id = meta.get("frame_id", 0)
    if create:
        process.publish(
            f"{gateway_topic}/in",
            generate("create_stream", [
                stream_id, json.dumps({}).encode("ascii"), grace_time,
                topic_response]))
    process.publish(
        f"{gateway_topic}/in",
        generate("process_frame", [
            {"stream_id": stream_id, "frame_id": frame_id},
            str(data).encode("ascii")]))
    return True


def _discover_gateway_topic(process, wait: float) -> str | None:
    import threading

    from .runtime import ServiceFilter
    from .runtime.storage import do_command
    from .serve import SERVICE_PROTOCOL_GATEWAY

    found = threading.Event()
    topics: list = []

    def on_proxy(proxy):
        # RemoteProxy exposes its /in topic; the service root is its
        # parent (any non-underscore attribute would proxy a call)
        topics.append(proxy._topic_in.rsplit("/in", 1)[0])
        found.set()

    do_command(process, ServiceFilter(protocol=SERVICE_PROTOCOL_GATEWAY),
               on_proxy)
    found.wait(wait)
    return topics[0] if topics else None


@deadletter.command("ls")
@click.option("--transport", default=None)
@click.option("--wait", default=3.0, help="Discovery/response wait (s)")
def deadletter_ls(transport: str | None, wait: float) -> None:
    """List the fleet's dead-lettered frames (newest last)."""
    from .runtime import Process
    process = Process(transport_kind=transport)
    process.run(in_thread=True)
    try:
        records = fetch_dead_letters(process, wait=wait)
        if not records:
            click.echo("no dead letters (or no recorder discovered)")
            return
        for record in records:
            meta = record.get("meta") or {}
            click.echo(
                f"[{record.get('index')}] {meta.get('stream_id')}"
                f"/{meta.get('frame_id')} node={meta.get('node')} "
                f"reason={meta.get('reason')} "
                f"data={'yes' if meta.get('data') else 'no'} "
                f"diag={str(meta.get('diagnostic', ''))[:60]}")
    finally:
        process.terminate()


@deadletter.command("replay")
@click.argument("index", type=int)
@click.option("--gateway", default=None,
              help="Gateway topic path (default: discover one)")
@click.option("--transport", default=None)
@click.option("--wait", default=3.0)
@click.option("--create/--no-create", "create_stream", default=True,
              help="Re-create the stream first (idempotent)")
def deadletter_replay(index: int, gateway: str | None,
                      transport: str | None, wait: float,
                      create_stream: bool) -> None:
    """Re-submit dead letter INDEX through the gateway."""
    from .runtime import Process
    process = Process(transport_kind=transport)
    process.run(in_thread=True)
    try:
        records = {record.get("index"): record
                   for record in fetch_dead_letters(process, wait=wait)}
        record = records.get(index)
        if record is None:
            raise click.ClickException(
                f"no dead letter at index {index} "
                f"(have {sorted(records)})")
        topic = gateway or _discover_gateway_topic(process, wait)
        if not topic:
            raise click.ClickException(
                "no gateway given and none discovered")
        import threading

        from .utils import parse
        outcome = {}
        done = threading.Event()
        response_topic = (f"{process.topic_path_process}/0/"
                          f"deadletter_replay")

        def on_response(_topic, payload):
            try:
                command, parameters = parse(payload)
            except ValueError:
                return
            if command == "process_frame_response" and parameters:
                reply = parameters[0] if isinstance(parameters[0],
                                                    dict) else {}
                outcome["status"] = reply.get("event") or "ok"
                done.set()
            elif command == "overloaded":
                outcome["status"] = "overloaded"
                done.set()

        process.add_message_handler(on_response, response_topic)
        if not replay_dead_letter(process, record, topic,
                                  create=create_stream,
                                  topic_response=response_topic):
            raise click.ClickException(
                "record has no embedded frame data (frame exceeded "
                "AIKO_DEAD_LETTER_DATA_MAX when it was dead-lettered)")
        done.wait(wait)
        click.echo(f"replayed {record['meta'].get('stream_id')}"
                   f"/{record['meta'].get('frame_id')} via {topic}: "
                   f"{outcome.get('status', 'no response within wait')}")
    finally:
        process.terminate()


@main.command()
@click.argument("trace", type=click.Path(exists=True), required=False)
@click.option("--live", default=None, metavar="TOPIC",
              help="Tune from a LIVE wire harvest instead of a trace "
                   "artifact: publish_trace the service at TOPIC "
                   "(a gateway or pipeline topic path), or pass "
                   "'discover' to harvest every discovered "
                   "gateway/pipeline -- the same harvest+merge path "
                   "the gateway autopilot runs each tick")
@click.option("--transport", default=None,
              help="Transport for --live (default: AIKO_TRANSPORT)")
@click.option("--wait", default=3.0,
              help="Discovery/response wait for --live (s)")
@click.option("--slo", default="throughput",
              help="SLO directive: 'throughput', 'latency', or a "
                   "spec like 'slo=throughput;p99_ms=250' "
                   "(AIKO501 grammar)")
@click.option("--json", "as_json", is_flag=True,
              help="Machine-readable report (byte-deterministic: the "
                   "same trace + spec always renders identically)")
@click.option("--output", default=None, type=click.Path(),
              help="Also write the report to this file")
@click.option("--definition", "definition_path", default=None,
              type=click.Path(exists=True),
              help="Side-channel definition for metadata-absent "
                   "traces (self-describing traces embed their own)")
@click.option("--run", "run_name", default=None,
              help="Pick one run out of a combined multi-pipeline "
                   "trace artifact")
@click.option("--apply", "apply_path", default=None,
              type=click.Path(),
              help="Write the tuned definition document here (the "
                   "recommendations applied, then re-linted; lint "
                   "errors fail the command)")
@click.option("--what-if", "what_if", default=None,
              help="Re-score the trace under explicit settings "
                   "instead of recommending: "
                   "'asr.micro_batch=4;frame_window=8;replicas=2'")
@click.option("--no-flops", "no_flops", is_flag=True,
              help="Skip the static FLOP/byte estimation (no element "
                   "instantiation -- faster; achieved-utilization "
                   "evidence is omitted)")
def tune(trace, live, transport, wait, slo, as_json, output,
         definition_path, run_name, apply_path, what_if,
         no_flops) -> None:
    """Profile-guided pipeline optimizer: classify each element's
    dominant floor (dispatch / compute / queue / compile-bound) from a
    recorded trace joined against the static graph, recommend concrete
    settings for the stated SLO, and what-if replay them -- no
    hardware needed (tune/ subsystem, README "Performance tuning").

    TRACE is a Perfetto artifact from `bench.py --trace` or
    PipelineTelemetry.export_trace; `--live TOPIC` harvests one over
    the wire instead.  Exit status: 0 report produced, 1 --apply
    produced a definition that fails lint, 2 the trace cannot be
    joined (no metadata and no --definition) or not harvested.
    """
    import sys
    from pathlib import Path

    from .analyze.grammar import GrammarError
    from .tune import (
        SloSpec, TraceLoadError, render_report, report_json, run_tune)

    if (trace is None) == (live is None):
        click.echo("give exactly one trace source: a TRACE artifact "
                   "path or --live TOPIC", err=True)
        sys.exit(2)
    if live is not None and what_if is not None:
        # what-if replays a SPECIFIC recorded trace under explicit
        # settings; a live harvest is point-in-time and unrepeatable,
        # so the comparison would be against a moving target
        click.echo("--what-if needs a trace artifact (record one with "
                   "bench.py --trace), not --live", err=True)
        sys.exit(2)
    if what_if is not None and apply_path is not None:
        # --what-if scores EXPLICIT settings (no recommender), so
        # there is nothing to apply -- silently ignoring --apply
        # would hand a success exit code and no output file
        click.echo("--what-if and --apply are mutually exclusive: "
                   "what-if scores explicit settings without "
                   "producing recommendations to apply", err=True)
        sys.exit(2)
    try:
        slo_spec = SloSpec.parse(slo)
    except GrammarError as error:
        click.echo(f"bad --slo spec: {error}", err=True)
        sys.exit(2)
    static_costs = {} if no_flops else None
    loaded = None
    try:
        if live is not None:
            # the gateway autopilot's exact harvest+merge+tune path
            # (serve/autopilot.py), run once from the shell: wire-
            # harvest, merge, tune -- no artifact file ever written
            from .runtime import Process
            from .serve.autopilot import harvest_documents, \
                tune_documents
            process = Process(transport_kind=transport)
            process.run(in_thread=True)
            try:
                targets = None if live == "discover" else [live]
                named = harvest_documents(process, wait=wait,
                                          targets=targets)
            finally:
                process.terminate()
            if not named:
                click.echo(
                    f"no traces harvested: nothing answered "
                    f"publish_trace within {wait:g}s "
                    f"({'discovery' if live == 'discover' else live})",
                    err=True)
                sys.exit(2)
            if apply_path is not None:
                # one parse serves both the report and the apply
                from .observe import merge_trace_documents
                from .tune import load_trace
                loaded = load_trace(
                    "live", definition=definition_path, run=run_name,
                    document=merge_trace_documents(list(named)))
                report = run_tune("live", slo_spec=slo_spec,
                                  loaded=loaded,
                                  static_costs=static_costs)
            else:
                report = tune_documents(
                    named, slo_spec=slo_spec,
                    definition=definition_path, run=run_name,
                    static_costs=static_costs)
        elif what_if is not None:
            report = _tune_what_if(trace, slo_spec, definition_path,
                                   run_name, what_if,
                                   static_costs=static_costs)
        else:
            if apply_path is not None:
                # one parse serves both the report and the apply
                from .tune import load_trace
                loaded = load_trace(trace, definition=definition_path,
                                    run=run_name)
            report = run_tune(trace, slo_spec=slo_spec,
                              definition=definition_path,
                              run=run_name,
                              static_costs=static_costs,
                              loaded=loaded)
    except TraceLoadError as error:
        click.echo(str(error), err=True)
        sys.exit(2)
    if not report.get("pipeline") and what_if is None:
        # nothing joined: the trace carries spans but no definition
        # (metadata absent and no side channel, or an ambiguous
        # combined artifact) -- fail loudly instead of printing floors
        # that cannot be attributed to typed nodes
        for diagnostic in report.get("diagnostics", []):
            click.echo(f"{diagnostic['code']}: "
                       f"{diagnostic['message']}", err=True)
        click.echo("trace not joined to a definition: give "
                   "--definition for a metadata-absent trace (or "
                   "--run for a combined one)", err=True)
        sys.exit(2)
    rendered = (report_json(report) if as_json
                else render_report(report))
    click.echo(rendered)
    if output:
        Path(output).write_text(
            rendered if rendered.endswith("\n") else rendered + "\n")
    if apply_path is not None and what_if is None:
        sys.exit(_tune_apply(loaded, report, apply_path))


_WHAT_IF_ELEMENT_KNOBS = ("micro_batch", "decode_slots",
                          "kv_block_size")
_WHAT_IF_PIPELINE_KNOBS = ("frame_window", "replicas")


def _parse_what_if(spec: str, element_names) -> dict:
    """'element.knob=value;knob=value' -> replay overrides.  Unknown
    elements/knobs are usage errors: a typo'd override would
    otherwise be silently ignored and the what-if replay would print
    baseline numbers as the proposed score."""
    overrides: dict = {"elements": {}}
    for part in str(spec).split(";"):
        part = part.strip()
        if not part:
            continue
        key, _, value = part.partition("=")
        try:
            number = int(value)
        except ValueError:
            raise click.ClickException(
                f"--what-if value {value!r} is not an integer "
                f"(in {part!r})")
        if "." in key:
            element, knob = (token.strip()
                             for token in key.split(".", 1))
            if element not in element_names:
                raise click.ClickException(
                    f"--what-if names unknown element {element!r} "
                    f"(trace has {sorted(element_names)})")
            if knob not in _WHAT_IF_ELEMENT_KNOBS:
                raise click.ClickException(
                    f"--what-if element knob {knob!r} is not one of "
                    f"{_WHAT_IF_ELEMENT_KNOBS}")
            overrides["elements"].setdefault(element, {})[
                knob] = number
        else:
            knob = key.strip()
            if knob not in _WHAT_IF_PIPELINE_KNOBS:
                raise click.ClickException(
                    f"--what-if knob {knob!r} is not one of "
                    f"{_WHAT_IF_PIPELINE_KNOBS} (element knobs are "
                    f"'element.knob=value')")
            overrides[knob] = number
    return overrides


def _tune_what_if(trace, slo_spec, definition_path, run_name, what_if,
                  static_costs=None) -> dict:
    """Score explicit settings against the recorded cost model -- no
    recommender in the loop, so CI can pin pure replay determinism."""
    from .tune import (
        CostModel, build_report, classify_elements,
        element_settings_of, load_trace, predict)
    loaded = load_trace(trace, definition=definition_path,
                        run=run_name)
    if static_costs is None:
        static_costs = {}
        if loaded.definition is not None:
            from .analyze.shape_eval import element_cost_estimates
            try:
                static_costs = element_cost_estimates(
                    loaded.definition)
            except Exception:
                static_costs = {}
    model = CostModel.from_trace(
        loaded, static_costs=static_costs,
        dispatch_floor_s=slo_spec.dispatch_floor_s,
        peak_flops=slo_spec.peak_flops)
    classify_elements(model)
    settings = element_settings_of(loaded.definition_document)
    baseline = predict(model, settings)
    overrides = _parse_what_if(what_if, set(loaded.elements))
    proposed = predict(model, settings, overrides)
    return build_report(loaded, model, slo_spec, [], baseline,
                        proposed)


def _tune_apply(loaded, report, apply_path) -> int:
    """Write the tuned definition (from the ALREADY-loaded trace) and
    re-lint it.  Returns the exit status (0 clean, 1 the applied
    definition fails lint)."""
    import json as json_module
    from pathlib import Path

    from .analyze import analyze_definition
    from .tune import Recommendation, apply_recommendations

    if loaded is None or loaded.definition_document is None:
        click.echo("--apply needs a definition (embedded metadata or "
                   "--definition)", err=True)
        return 2
    recommendations = [
        Recommendation(**{key: record[key] for key in
                          ("target", "knob", "current", "proposed",
                           "reason", "floor", "evidence")})
        for record in report.get("recommendations", [])]
    document, diagnostics = apply_recommendations(
        loaded.definition_document, recommendations)
    for diagnostic in diagnostics:
        click.echo(diagnostic.render(), err=True)
    lint_report = analyze_definition(document,
                                     passes=("graph", "policy"))
    Path(apply_path).write_text(
        json_module.dumps(document, indent=2) + "\n")
    failures = lint_report.failures()
    if failures:
        click.echo(f"applied definition FAILS lint "
                   f"({len(failures)} error(s)):", err=True)
        for diagnostic in failures:
            click.echo(f"  {diagnostic.render()}", err=True)
        return 1
    click.echo(f"applied {len(recommendations)} recommendation(s) -> "
               f"{apply_path} (lint clean)")
    return 0


@main.group("trace")
def trace_group() -> None:
    """Fleet-scope distributed tracing: harvest per-process Perfetto
    artifacts from a live fleet (`collect`) and merge many artifacts
    into ONE clock-aligned timeline (`merge`) -- the input `aiko tune`
    reads for cross-process (admission-bound) floor classification."""


@trace_group.command("merge")
@click.argument("output", type=click.Path())
@click.argument("inputs", type=click.Path(exists=True), nargs=-1,
                required=True)
def trace_merge(output: str, inputs) -> None:
    """Merge trace artifacts into OUTPUT.  Inputs are sorted (basename,
    path) before merging, so the same file set always produces
    byte-identical output -- CI diffs two merges to prove it."""
    import sys

    from .observe import merge_trace_files, trace_summary
    try:
        merged = merge_trace_files(list(inputs), output=output)
    except (OSError, ValueError) as error:
        click.echo(f"merge failed: {error}", err=True)
        sys.exit(2)
    summary = trace_summary(merged)
    click.echo(
        f"merged {len(inputs)} artifact(s) -> {output}: "
        f"{len(merged['traceEvents'])} events, "
        f"{summary['traces']} trace(s), "
        f"{summary['multi_process_traces']} crossing processes "
        f"(max {summary['max_processes_per_trace']} processes/trace), "
        f"{summary['linked_spans']} parent-linked span(s)")
    if summary["dangling_parents"]:
        click.echo(
            f"warning: {len(summary['dangling_parents'])} span(s) name "
            f"a parent outside the merged set (partial harvest?)",
            err=True)


@trace_group.command("collect")
@click.option("--output", "output_dir", type=click.Path(),
              required=True,
              help="Directory for the per-process artifacts")
@click.option("--merge", "merge_path", type=click.Path(), default=None,
              help="Also write the merged artifact here")
@click.option("--transport", default=None)
@click.option("--wait", default=3.0,
              help="Discovery/response wait (s)")
def trace_collect(output_dir: str, merge_path: str | None,
                  transport: str | None, wait: float) -> None:
    """Harvest every live pipeline/gateway's trace document over the
    control plane (each replies to `(publish_trace ...)` with its
    self-describing artifact) into per-process files, optionally
    merged."""
    import json as json_module
    import sys
    from pathlib import Path

    from .observe import collect_traces, merge_trace_documents
    from .runtime import Process
    process = Process(transport_kind=transport)
    process.run(in_thread=True)
    try:
        collected = collect_traces(process, wait=wait)
    finally:
        process.terminate()
    if not collected:
        click.echo("no traces collected (no live pipelines/gateways "
                   "discovered, or telemetry disabled)", err=True)
        sys.exit(2)
    directory = Path(output_dir)
    directory.mkdir(parents=True, exist_ok=True)
    from .observe.collector import unique_source_name
    named = []
    seen: dict = {}
    for source in sorted(collected):
        safe = unique_source_name(
            seen, source.replace("/", "_").strip("_"))
        path = directory / f"{safe}.json"
        path.write_text(json_module.dumps(collected[source],
                                          sort_keys=True))
        named.append((safe, collected[source]))
        click.echo(f"collected {source} -> {path}")
    if merge_path:
        merged = merge_trace_documents(named)
        Path(merge_path).write_text(json_module.dumps(
            merged, sort_keys=True, separators=(",", ":")))
        click.echo(f"merged {len(named)} artifact(s) -> {merge_path}")


@main.command()
def bench() -> None:
    """Run the standard benchmark (one JSON line)."""
    import runpy
    from pathlib import Path
    bench_path = Path(__file__).resolve().parent.parent / "bench.py"
    runpy.run_path(str(bench_path), run_name="__main__")


@main.group()
def system() -> None:
    """One-command bootstrap: start/stop a whole local deployment
    (registrar + dashboard + a named pipeline) as detached OS
    processes tracked in a state file."""


DEFAULT_STATE_FILE = ".aiko_system.json"


def _system_state(state_file: str) -> dict:
    import json
    from pathlib import Path
    path = Path(state_file)
    if not path.is_file():
        return {}
    try:
        return json.loads(path.read_text())
    except (OSError, ValueError):
        return {}


def _pid_alive(pid: int) -> bool:
    import os
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


def _pid_is_ours(pid: int) -> bool:
    """Guard against pid reuse: a state file that outlives its children
    (reboot, crash) must not let `aiko system stop` signal whatever
    unrelated process now owns the pid.  Where /proc is unavailable the
    check passes — liveness alone decides, as before."""
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as handle:
            return b"aiko_services_tpu" in handle.read()
    except OSError:
        return True


@system.command("start")
@click.argument("definition", type=click.Path(exists=True))
@click.option("--name", default=None, help="Pipeline service name")
@click.option("--transport", default=None,
              help="loopback | mqtt | null (default: auto from env)")
@click.option("--dashboard/--no-dashboard", "with_dashboard",
              default=False,
              help="Also spawn the curses dashboard (opt-in: as a "
                   "background child it shares this shell's terminal, "
                   "so prefer `aiko dashboard` in its own terminal)")
@click.option("--state-file", default=DEFAULT_STATE_FILE,
              help="Where the spawned pids are recorded for `aiko "
                   "system stop`")
def system_start(definition: str, name: str | None,
                 transport: str | None, with_dashboard: bool,
                 state_file: str) -> None:
    """Spawn registrar (+ optional dashboard) + the DEFINITION
    pipeline.

    Children are detached `python -m aiko_services_tpu <command>`
    processes (ProcessManager with start_new_session, so they survive
    this shell closing); the command returns immediately and `aiko
    system stop` terminates everything it started."""
    import json
    import subprocess
    import sys
    import time
    from pathlib import Path

    from .runtime import ProcessManager

    state = _system_state(state_file)
    alive = {service: pid for service, pid
             in (state.get("pids") or {}).items()
             if _pid_alive(pid) and _pid_is_ours(pid)}
    if alive:
        click.echo(f"already running ({state_file}): {alive} -- "
                   f"`aiko system stop` first", err=True)
        sys.exit(1)

    transport_args = (["--transport", transport] if transport else [])
    manager = ProcessManager()
    services = {}
    logs = {}

    def spawn(service_id, *arguments, inherit_stdio=False):
        # own log file per child: an inherited stdout/stderr would pin
        # any pipe on this shell open and die with its terminal.  The
        # curses dashboard is the exception -- it NEEDS the tty.
        if inherit_stdio:
            child = manager.spawn(
                service_id, sys.executable,
                ["-m", "aiko_services_tpu", *arguments],
                use_interpreter=False, start_new_session=True)
        else:
            log_path = Path(state_file).with_suffix(
                "." + service_id.replace(":", "_") + ".log")
            with open(log_path, "ab") as log:
                child = manager.spawn(
                    service_id, sys.executable,
                    ["-m", "aiko_services_tpu", *arguments],
                    use_interpreter=False, start_new_session=True,
                    stdout=log, stderr=subprocess.STDOUT)
            logs[service_id] = str(log_path)
        services[service_id] = child.pid
        return child

    spawn("registrar", "registrar", *transport_args)
    pipeline_args = ["pipeline", str(Path(definition).resolve()),
                     *transport_args]
    if name:
        pipeline_args += ["--name", name]
    spawn(f"pipeline:{name or Path(definition).stem}", *pipeline_args)
    if with_dashboard:
        if not sys.stdout.isatty():
            click.echo("--dashboard needs a terminal (curses); "
                       "skipping -- run `aiko dashboard` instead",
                       err=True)
        else:
            spawn("dashboard", "dashboard", *transport_args,
                  inherit_stdio=True)
    Path(state_file).write_text(json.dumps({
        "pids": services,
        "logs": logs,
        "definition": str(Path(definition).resolve()),
        "transport": transport,
        "started": time.time(),
    }, indent=2) + "\n")
    for service_id, pid in services.items():
        log_note = (f" (log {logs[service_id]})"
                    if service_id in logs else "")
        click.echo(f"started {service_id}: pid {pid}{log_note}")
    click.echo(f"state: {state_file} -- stop with `aiko system stop"
               + (f" --state-file {state_file}`"
                  if state_file != DEFAULT_STATE_FILE else "`"))


@system.command("stop")
@click.option("--state-file", default=DEFAULT_STATE_FILE)
@click.option("--timeout", default=10.0,
              help="Seconds to wait after SIGTERM before SIGKILL")
def system_stop(state_file: str, timeout: float) -> None:
    """Terminate every process `aiko system start` recorded: SIGTERM,
    a grace wait, then SIGKILL for stragglers."""
    import os
    import signal
    import sys
    import time
    from pathlib import Path

    state = _system_state(state_file)
    pids = state.get("pids") or {}
    if not pids:
        click.echo(f"nothing recorded in {state_file}", err=True)
        sys.exit(1)
    recycled = set()
    for service_id, pid in pids.items():
        if not _pid_alive(pid):
            click.echo(f"{service_id}: pid {pid} already gone")
        elif not _pid_is_ours(pid):
            recycled.add(service_id)
            click.echo(f"{service_id}: pid {pid} is no longer an "
                       f"aiko_services_tpu process (recycled after a "
                       f"reboot?) -- leaving it alone", err=True)
        else:
            try:
                os.kill(pid, signal.SIGTERM)
                click.echo(f"stopping {service_id}: pid {pid}")
            except OSError as error:
                click.echo(f"stop {service_id} pid {pid}: {error}",
                           err=True)
    deadline = time.monotonic() + timeout
    remaining = {service: pid for service, pid in pids.items()
                 if service not in recycled}
    while remaining and time.monotonic() < deadline:
        remaining = {service: pid for service, pid in remaining.items()
                     if _pid_alive(pid)}
        time.sleep(0.05)
    for service_id, pid in remaining.items():
        try:
            os.kill(pid, signal.SIGKILL)
            click.echo(f"killed {service_id}: pid {pid} (no SIGTERM "
                       f"exit within {timeout}s)")
        except OSError:
            pass
    Path(state_file).unlink(missing_ok=True)
    click.echo("stopped")


def _print_replica_pools(transport: str | None, wait: float) -> int:
    """Discover serving gateways through the registrar and print each
    one's replica pool (replica topic, state, load gauges, warm/cold)
    from its EC share -- rendered by the SAME plugin the dashboard
    uses, so the two views cannot drift.  Returns the number of
    gateways found."""
    import time
    from types import SimpleNamespace

    from .dashboard import _gateway_plugin
    from .runtime import Process
    from .runtime.service import ServiceFilter
    from .runtime.share import ECConsumer, services_cache_create_singleton

    process = Process(transport_kind=transport)
    gateways: dict = {}

    def handler(command, fields):
        if command == "add":
            gateways[fields.topic_path] = fields

    cache = services_cache_create_singleton(process)
    # protocols are full URLs ("github.com/.../protocol/gateway:0"):
    # the pattern must match the whole string, not just the tail word
    cache.add_handler(handler, ServiceFilter(protocol="*/gateway:*"))
    process.run(in_thread=True)
    try:
        deadline = time.monotonic() + wait
        while time.monotonic() < deadline and not gateways:
            time.sleep(0.05)
        if not gateways:
            click.echo("pool: no gateway services discovered "
                       f"(waited {wait}s)")
            return 0
        # snapshot: the discovery handler keeps appending from the
        # message-pump thread, and a gateway arriving after this point
        # simply waits for the next invocation
        found = sorted(gateways.items())
        shares = {topic_path: {} for topic_path, _ in found}
        consumers = [ECConsumer(process, shares[topic_path], topic_path)
                     for topic_path, _ in found]
        # give the share mirrors until the deadline to fill in; the
        # pool detail rides the periodic telemetry summary
        while (time.monotonic() < deadline
               and not all(shares.values())):
            time.sleep(0.05)
        for topic_path, fields in found:
            click.echo(f"gateway {fields.name} ({topic_path})")
            model = SimpleNamespace(selected_share=shares[topic_path])
            for line in _gateway_plugin(model):
                click.echo(f"  {line}")
        for consumer in consumers:
            consumer.terminate()
        return len(found)
    finally:
        process.terminate()


@system.command("status")
@click.option("--state-file", default=DEFAULT_STATE_FILE)
@click.option("--pool/--no-pool", "show_pool", default=False,
              help="Also discover serving gateways via the registrar "
                   "and print each replica pool (state, load gauges, "
                   "warm/cold)")
@click.option("--transport", default=None,
              help="Transport for --pool discovery (default: the "
                   "start-time transport from the state file)")
@click.option("--wait", default=3.0,
              help="Seconds to wait for --pool discovery")
def system_status(state_file: str, show_pool: bool,
                  transport: str | None, wait: float) -> None:
    """Liveness of every recorded process; --pool adds the serving
    tier's replica pools."""
    import sys
    state = _system_state(state_file)
    pids = state.get("pids") or {}
    if not pids and not show_pool:
        click.echo(f"nothing recorded in {state_file}")
        sys.exit(1)
    logs = state.get("logs") or {}
    down = 0
    for service_id, pid in pids.items():
        alive = _pid_alive(pid)
        down += 0 if alive else 1
        suffix = f"  {logs[service_id]}" if service_id in logs else ""
        click.echo(f"{service_id:24} pid {pid:<8} "
                   f"{'up' if alive else 'DOWN'}{suffix}")
    if show_pool:
        _print_replica_pools(transport or state.get("transport"), wait)
    sys.exit(1 if down else 0)


@main.command()
@click.option("--port", default=None, type=int,
              help="UDP port to answer on (default 4149)")
@click.option("--mqtt-host", default=None,
              help="Broker host to advertise (default: resolved from "
                   "AIKO_MQTT_HOST/AIKO_MQTT_HOSTS with a TCP probe)")
@click.option("--mqtt-port", default=None, type=int)
def bootstrap(port: int | None, mqtt_host: str | None,
              mqtt_port: int | None) -> None:
    """MCU bootstrap responder: answers UDP boot datagrams with the
    namespace + broker endpoint (reference configuration.py:168-186)."""
    import signal
    import time

    from .utils import BootstrapResponder
    kwargs = {"mqtt_host": mqtt_host, "mqtt_port": mqtt_port}
    if port is not None:
        kwargs["port"] = port
    responder = BootstrapResponder(**kwargs)
    click.echo(f"bootstrap responder on udp/{responder.port} advertising "
               f"{responder.mqtt_host}:{responder.mqtt_port}")
    stop = []
    signal.signal(signal.SIGTERM, lambda *_: stop.append(True))
    signal.signal(signal.SIGINT, lambda *_: stop.append(True))
    while not stop:
        time.sleep(0.2)
    responder.close()


if __name__ == "__main__":
    main()
