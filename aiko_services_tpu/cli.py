# Console entry points.
#
# Capability parity with the reference's console scripts (reference:
# pyproject.toml:60-64 -- aiko_registrar, aiko_pipeline, aiko_dashboard,
# plus storage/recorder mains): one click group, `python -m
# aiko_services_tpu <command>`.

from __future__ import annotations

import click


@click.group()
def main() -> None:
    """aiko_services_tpu: TPU-native distributed ML pipeline framework."""


@main.command()
@click.option("--name", default="registrar")
@click.option("--transport", default=None,
              help="loopback | mqtt | null (default: auto from env)")
def registrar(name: str, transport: str | None) -> None:
    """Run a service-discovery registrar."""
    from .runtime import Process, Registrar
    process = Process(transport_kind=transport)
    Registrar(process, name=name)
    process.run()


@main.command()
@click.argument("definition", type=click.Path(exists=True))
@click.option("--name", default=None)
@click.option("--transport", default=None)
@click.option("--stream-id", default=None,
              help="Create this stream immediately")
@click.option("--stream-parameters", default="{}",
              help="JSON stream parameters")
@click.option("--frame-data", default=None,
              help="JSON frame data posted to the created stream")
@click.option("--grace-time", default=60.0)
def pipeline(definition: str, name: str | None, transport: str | None,
             stream_id: str | None, stream_parameters: str,
             frame_data: str | None, grace_time: float) -> None:
    """Create and run a pipeline from a JSON definition (reference
    `aiko_pipeline create`, pipeline.py:1444-1528)."""
    import json

    from .pipeline import create_pipeline
    from .runtime import Process
    process = Process(transport_kind=transport)
    pipeline_instance = create_pipeline(process, definition, name=name)
    if stream_id is not None:
        pipeline_instance.create_stream(
            stream_id, parameters=json.loads(stream_parameters),
            grace_time=grace_time)
        if frame_data is not None:
            pipeline_instance.process_frame(
                {"stream_id": stream_id}, json.loads(frame_data))
    process.run()


@main.command()
@click.option("--name", default="storage")
@click.option("--database", default="storage.db")
@click.option("--transport", default=None)
def storage(name: str, database: str, transport: str | None) -> None:
    """Run a sqlite storage service."""
    from .runtime import Process, Storage
    process = Process(transport_kind=transport)
    Storage(process, name=name, database_path=database)
    process.run()


@main.command()
@click.option("--name", default="recorder")
@click.option("--topic", default=None, help="Log topic pattern")
@click.option("--transport", default=None)
def recorder(name: str, topic: str | None, transport: str | None) -> None:
    """Run a log-aggregation recorder service."""
    from .runtime import Process, Recorder
    process = Process(transport_kind=transport)
    Recorder(process, name=name, log_topic_pattern=topic)
    process.run()


@main.command()
@click.option("--transport", default=None)
@click.option("--snapshot", is_flag=True,
              help="Print one services-table snapshot and exit")
@click.option("--wait", default=3.0,
              help="Seconds to wait for discovery in snapshot mode")
def dashboard(transport: str | None, snapshot: bool, wait: float) -> None:
    """Service dashboard: curses TUI, or --snapshot for plain text."""
    from .dashboard import run_dashboard
    run_dashboard(transport_kind=transport, snapshot=snapshot, wait=wait)


@main.command()
@click.argument("sources", nargs=-1, type=click.Path())
@click.option("--strict", is_flag=True,
              help="Fail on warnings too (errors always fail)")
@click.option("--format", "fmt",
              type=click.Choice(["text", "json"]), default="text")
@click.option("--output", default=None, type=click.Path(),
              help="Also write the report to this file")
@click.option("--passes", "passes_option", default=None,
              help="Comma-separated pass list "
                   "(graph,policy,actor,eval); default: all")
@click.option("--bench", "bench_configs", is_flag=True,
              help="Also lint every pipeline definition bench.py "
                   "constructs")
@click.option("--golden", default=None,
              type=click.Path(exists=True, file_okay=False),
              help="Verify a corpus of deliberately-broken definitions:"
                   " each <code>_*.json must produce that rule code")
def lint(sources, strict, fmt, output, passes_option, bench_configs,
         golden) -> None:
    """Statically verify pipeline definitions (analyze/ subsystem).

    SOURCES are definition JSON files or directories (searched
    recursively for *.json).  Four passes: graph/port dataflow
    (AIKO1xx), tensor-spec shape/dtype flow (AIKO2xx, including a
    jax.eval_shape dry-run of element device programs), element/actor
    safety (AIKO3xx), and policy grammars (AIKO4xx).  Exit status: 0
    clean, 1 findings (with --strict, warnings count), 2 usage error.
    """
    import sys
    from pathlib import Path

    from .analyze import ALL_PASSES, AnalysisReport, analyze_definition

    passes = (tuple(part.strip() for part in passes_option.split(",")
                    if part.strip())
              if passes_option else ALL_PASSES)
    unknown = [name for name in passes if name not in ALL_PASSES]
    if unknown:
        click.echo(f"unknown passes: {unknown} (valid: {ALL_PASSES})",
                   err=True)
        sys.exit(2)

    if golden is not None:
        sys.exit(_lint_golden(Path(golden), passes))

    targets: list = []
    for source in sources:
        path = Path(source)
        if path.is_dir():
            targets.extend(sorted(path.rglob("*.json")))
        else:
            targets.append(path)
    if bench_configs:
        import runpy
        bench_path = Path(__file__).resolve().parent.parent / "bench.py"
        if not bench_path.is_file():
            click.echo(f"--bench needs a source checkout: {bench_path} "
                       f"not found", err=True)
            sys.exit(2)
        bench_module = runpy.run_path(str(bench_path))
        for name, definition in sorted(
                bench_module["collect_definitions"]().items()):
            targets.append((f"bench.py::{name}", definition))
    if not targets:
        click.echo("nothing to lint (give files, directories, or "
                   "--bench)", err=True)
        sys.exit(2)

    report = AnalysisReport()
    for target in targets:
        if isinstance(target, tuple):
            label, source = target
        else:
            label, source = str(target), target
        report.extend(analyze_definition(source, passes=passes,
                                         source_path=label))
    rendered = (report.to_json() if fmt == "json"
                else report.render())
    click.echo(rendered)
    if output:
        Path(output).write_text(
            rendered if rendered.endswith("\n") else rendered + "\n")
    sys.exit(1 if report.failures(strict=strict) else 0)


def _lint_golden(corpus: "Path", passes) -> int:
    """Golden-corpus mode: every `<code>_*.json` in the corpus must
    yield a finding with that code -- the proof each lint rule still
    fires.  Returns the exit status."""
    from .analyze import RULES, analyze_definition

    failures = 0
    checked = 0
    for path in sorted(corpus.glob("*.json")):
        expected = path.stem.split("_", 1)[0].upper()
        if expected not in RULES:
            click.echo(f"SKIP {path.name}: no rule code prefix")
            continue
        checked += 1
        report = analyze_definition(path, passes=passes,
                                    source_path=str(path))
        codes = {diagnostic.code for diagnostic in report.findings}
        if expected in codes:
            click.echo(f"ok   {path.name}: {expected} fired")
        else:
            failures += 1
            click.echo(f"FAIL {path.name}: expected {expected}, got "
                       f"{sorted(codes) or 'no findings'}")
    click.echo(f"{checked} golden definition(s), {failures} failure(s)")
    return 1 if failures or not checked else 0


@main.command()
def bench() -> None:
    """Run the standard benchmark (one JSON line)."""
    import runpy
    from pathlib import Path
    bench_path = Path(__file__).resolve().parent.parent / "bench.py"
    runpy.run_path(str(bench_path), run_name="__main__")


@main.command()
@click.option("--port", default=None, type=int,
              help="UDP port to answer on (default 4149)")
@click.option("--mqtt-host", default=None,
              help="Broker host to advertise (default: resolved from "
                   "AIKO_MQTT_HOST/AIKO_MQTT_HOSTS with a TCP probe)")
@click.option("--mqtt-port", default=None, type=int)
def bootstrap(port: int | None, mqtt_host: str | None,
              mqtt_port: int | None) -> None:
    """MCU bootstrap responder: answers UDP boot datagrams with the
    namespace + broker endpoint (reference configuration.py:168-186)."""
    import signal
    import time

    from .utils import BootstrapResponder
    kwargs = {"mqtt_host": mqtt_host, "mqtt_port": mqtt_port}
    if port is not None:
        kwargs["port"] = port
    responder = BootstrapResponder(**kwargs)
    click.echo(f"bootstrap responder on udp/{responder.port} advertising "
               f"{responder.mqtt_host}:{responder.mqtt_port}")
    stop = []
    signal.signal(signal.SIGTERM, lambda *_: stop.append(True))
    signal.signal(signal.SIGINT, lambda *_: stop.append(True))
    while not stop:
        time.sleep(0.2)
    responder.close()


if __name__ == "__main__":
    main()
