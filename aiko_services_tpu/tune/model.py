# Per-element cost model + analytical floor classifier.
#
# The cost model joins the trace's DYNAMIC medians (per-frame compute
# share, scheduler queue wait, coalesced group size, compile events)
# with the STATIC side from analyze/ (jax.eval_shape byte counts and
# XLA flop estimates per element), so every number in a tune report is
# attributable to a typed graph node.
#
# Floor classifier (detector-roofline style -- BENCH_NOTES "Detector
# roofline" measured the per-call dispatch floor this formalizes).
# Exactly one label per element, checked in priority order:
#
#   admission-bound (gateway pseudo-node only, fleet-scope traces) the
#                   median admit-wait -- frame submit -> replica
#                   dispatch, parked-queue wait included -- exceeds
#                   the busiest element's compute+queue share: streams
#                   wait at the GATE, not in any replica's kernel --
#                   raise the replica floor and/or lower the admission
#                   rate; no per-element knob can move this floor
#   compile-bound   compile events keep firing past warmup: the
#                   element re-specializes (shape churn / cohort
#                   splits) and wall time is dominated by compilation
#   migration-bound a disaggregated decode element spends more wall
#                   time adopting migrated KV blocks (transfer-plane
#                   fetch + pool scatter) than computing or queueing:
#                   the prefill pool is too remote/slow, not the
#                   kernel -- fix the transfer path or colocate,
#                   a bigger slot pool will not help
#   checkpoint-bound a warm-failover decode element spends more wall
#                   time shipping decode-state snapshots
#                   (decode/checkpoint.py gathers + offers) than
#                   computing or queueing: the snapshot cadence, not
#                   the kernel, is the floor -- stretch
#                   checkpoint_every / max_checkpoint_lag
#   queue-bound     median scheduler wait exceeds median compute: the
#                   element starves behind coalescing or a saturated
#                   slot pool, not its own kernel
#   cache-bound     a prefix-caching decode element serves most
#                   prefills from shared KV blocks (hit rate past
#                   CACHE_HIT_RATE_BOUND): the observed prefill span
#                   is the uncached TAIL, not the full prompt, so the
#                   prefill floor is set by what the cache misses --
#                   pin prefix_policy before tuning slots/blocks, and
#                   read prefill medians as cache-residual time
#   dispatch-bound  median per-CALL time is at the runtime's dispatch
#                   floor (and, when FLOP estimates exist, achieved
#                   utilization is far below peak): the chip is idle
#                   waiting for calls -- batch more, not faster
#   compute-bound   none of the above: the kernel itself is the floor;
#                   only replicas / a faster kernel move it
#   unobserved      the definition declares the element but the trace
#                   carries no spans for it
#
# Every classification carries the evidence numbers the label was
# computed from; thresholds are explicit constants so reports are
# reproducible and arguable.

from __future__ import annotations

from dataclasses import dataclass, field

__all__ = ["ElementCost", "CostModel", "classify_elements",
           "COMPILE_RATIO_BOUND", "LOW_UTILIZATION_BOUND",
           "CACHE_HIT_RATE_BOUND"]

# compile events per call past which an element is compile-bound: a
# healthy steady state compiles each signature once (a handful of
# events over hundreds of calls); 5% means it keeps re-specializing
COMPILE_RATIO_BOUND = 0.05
# achieved fraction of peak below which a fast call is dispatch- (not
# compute-) bound when a FLOP estimate exists
LOW_UTILIZATION_BOUND = 0.02
# dispatch-floor multiple up to which low utilization still reads as
# dispatch-bound (beyond it the kernel is genuinely running long)
DISPATCH_SPAN_MULTIPLE = 8.0
# prefix-cache hit rate (requests with >= 1 borrowed block / judged
# requests) past which an engine element's prefill floor is the cache
# residual, not the kernel: half the traffic skipping most of its
# prefill means slot/block knobs no longer describe the workload
CACHE_HIT_RATE_BOUND = 0.5


def _median(values: list) -> float:
    if not values:
        return 0.0
    ordered = sorted(values)
    middle = len(ordered) // 2
    if len(ordered) % 2:
        return float(ordered[middle])
    return (ordered[middle - 1] + ordered[middle]) / 2.0


def _quantile(values: list, q: float) -> float:
    if not values:
        return 0.0
    ordered = sorted(values)
    if len(ordered) == 1:
        return float(ordered[0])
    position = q * (len(ordered) - 1)
    low = int(position)
    high = min(low + 1, len(ordered) - 1)
    fraction = position - low
    return ordered[low] + (ordered[high] - ordered[low]) * fraction


@dataclass
class ElementCost:
    """The joined static+dynamic cost record for one graph node."""

    name: str
    calls: int = 0
    compute_median_s: float = 0.0      # per-frame share
    compute_p90_s: float = 0.0
    queue_median_s: float = 0.0
    queue_p90_s: float = 0.0
    group_median: float = 1.0
    per_call_median_s: float = 0.0     # share x group
    paths: dict = field(default_factory=dict)
    compiles: int = 0
    engine: dict | None = None
    # serving-gateway pseudo-node (fleet-scope traces): admit/route
    # medians + replay/shed counts from the gateway's own spans
    gateway: dict | None = None
    # static side (analyze/shape_eval.element_cost_estimates)
    flops_per_row: float | None = None
    bytes_per_row: float | None = None
    param_bytes: float | None = None
    achieved_utilization: float | None = None
    # classification, filled by classify_elements
    floor: str = "unobserved"
    evidence: dict = field(default_factory=dict)


@dataclass
class CostModel:
    elements: dict = field(default_factory=dict)   # name -> ElementCost
    frame_p50_s: float = 0.0
    frame_p99_s: float = 0.0
    frames_per_sec: float = 0.0
    frame_count: int = 0
    wall_s: float = 0.0
    dispatch_floor_s: float = 0.0015
    peak_flops: float | None = None

    @classmethod
    def from_trace(cls, loaded, static_costs: dict | None = None,
                   dispatch_floor_s: float = 0.0015,
                   peak_flops: float | None = None) -> "CostModel":
        """Build the model from a LoadedTrace (+ optional static
        estimates).  `peak_flops` defaults to the peak the embedded
        bench config block recorded, when any."""
        if peak_flops is None:
            assumed = (loaded.config or {}).get("peak_tflops_assumed")
            if isinstance(assumed, (int, float)) and assumed:
                peak_flops = float(assumed) * 1e12
        model = cls(dispatch_floor_s=dispatch_floor_s,
                    peak_flops=peak_flops, wall_s=loaded.wall_s,
                    frame_count=loaded.frame_count)
        durations = loaded.frame_durations_s
        model.frame_p50_s = _median(durations)
        model.frame_p99_s = _quantile(durations, 0.99)
        if loaded.wall_s > 0 and durations:
            model.frames_per_sec = len(durations) / loaded.wall_s
        static_costs = static_costs or {}
        for name, profile in sorted(loaded.elements.items()):
            cost = ElementCost(name=name, calls=profile.calls,
                               paths=dict(profile.paths),
                               compiles=profile.compiles)
            cost.compute_median_s = _median(profile.compute_s)
            cost.compute_p90_s = _quantile(profile.compute_s, 0.9)
            cost.queue_median_s = _median(profile.queue_s)
            cost.queue_p90_s = _quantile(profile.queue_s, 0.9)
            cost.group_median = _median(profile.groups) or 1.0
            cost.per_call_median_s = (cost.compute_median_s
                                      * cost.group_median)
            if profile.is_gateway:
                cost.gateway = {
                    "admit_median_s": _median(profile.gateway_admit_s),
                    "admit_p90_s": _quantile(profile.gateway_admit_s,
                                             0.9),
                    "route_median_s": _median(profile.gateway_route_s),
                    "admits": len(profile.gateway_admit_s),
                    "replays": len(profile.gateway_replay_s),
                    "replay_median_s": _median(
                        profile.gateway_replay_s),
                    "sheds": profile.gateway_sheds,
                    "throttles": profile.gateway_throttles,
                }
            if profile.is_engine_managed:
                cost.engine = {
                    "queue_median_s": _median(
                        profile.engine_queue_s or profile.queue_s),
                    "prefill_median_s": _median(
                        profile.engine_prefill_s),
                    "decode_median_s": _median(
                        profile.engine_decode_s),
                    "adopt_median_s": _median(profile.engine_adopt_s),
                    "adoptions": len(profile.engine_adopt_s),
                    "checkpoint_median_s": _median(
                        profile.engine_checkpoint_s),
                    "checkpoints": len(profile.engine_checkpoint_s),
                    "preemptions": profile.engine_preemptions,
                    "tokens": profile.engine_tokens,
                    "requests": len(profile.engine_decode_s),
                    "prefix_requests": profile.engine_prefix_requests,
                    "prefix_hits": profile.engine_prefix_hits,
                    "prefix_blocks": profile.engine_prefix_blocks,
                    "prefix_hit_rate": (
                        profile.engine_prefix_hits
                        / profile.engine_prefix_requests
                        if profile.engine_prefix_requests else 0.0),
                }
            static = static_costs.get(name)
            if static:
                rows = max(int(static.get("rows") or 1), 1)
                flops = static.get("flops")
                if flops is not None:
                    cost.flops_per_row = float(flops) / rows
                bytes_total = (static.get("bytes_in", 0)
                               + static.get("bytes_out", 0))
                cost.bytes_per_row = float(bytes_total) / rows
                cost.param_bytes = float(
                    static.get("param_bytes") or 0.0)
                if (cost.flops_per_row and peak_flops
                        and cost.per_call_median_s > 0):
                    # rows per call ~= coalesced frames (the per-frame
                    # row count is folded into the static estimate's
                    # leading axis, so this is a lower bound)
                    cost.achieved_utilization = (
                        cost.flops_per_row * cost.group_median
                        / (cost.per_call_median_s * peak_flops))
            model.elements[name] = cost
        return model


def classify_elements(model: CostModel) -> None:
    """Label every element's dominant floor, in place, with the
    evidence each label was computed from."""
    floor_s = model.dispatch_floor_s
    # the fleet's busiest per-frame element share (compute + queue,
    # engine phases included): the yardstick the gateway's admit-wait
    # is judged against -- admission-bound means streams wait at the
    # gate LONGER than any replica spends serving them
    fleet_busy_s = 0.0
    for cost in model.elements.values():
        if cost.gateway is not None:
            continue
        engine = cost.engine or {}
        compute = max(cost.compute_median_s,
                      engine.get("prefill_median_s", 0.0)
                      + engine.get("decode_median_s", 0.0))
        queue_wait = max(cost.queue_median_s,
                         engine.get("queue_median_s", 0.0))
        fleet_busy_s = max(fleet_busy_s, compute + queue_wait)
    for cost in model.elements.values():
        evidence = {
            "calls": cost.calls,
            "compute_median_ms": round(cost.compute_median_s * 1e3, 4),
            "per_call_median_ms": round(
                cost.per_call_median_s * 1e3, 4),
            "queue_median_ms": round(cost.queue_median_s * 1e3, 4),
            "group_median": round(cost.group_median, 2),
            "compiles": cost.compiles,
            "dispatch_floor_ms": round(floor_s * 1e3, 4),
            "paths": dict(cost.paths),
        }
        if cost.achieved_utilization is not None:
            evidence["achieved_utilization"] = round(
                cost.achieved_utilization, 5)
        if cost.engine is not None:
            evidence["engine"] = {
                key: (round(value, 6)
                      if isinstance(value, float) else value)
                for key, value in cost.engine.items()}
        if cost.gateway is not None:
            # the serving tier has exactly two states worth a label:
            # the gate is the floor (admission-bound -- raise replicas
            # / lower the rate), or the gateway's own per-frame work
            # sits at the dispatch floor and the bottleneck is
            # elsewhere (dispatch-bound: not the tier to tune)
            gateway = cost.gateway
            evidence["gateway"] = {
                key: (round(value, 6)
                      if isinstance(value, float) else value)
                for key, value in gateway.items()}
            evidence["fleet_busy_ms"] = round(fleet_busy_s * 1e3, 4)
            cost.evidence = evidence
            admit = gateway.get("admit_median_s", 0.0)
            if admit > max(fleet_busy_s, floor_s):
                cost.floor = "admission-bound"
            else:
                cost.floor = "dispatch-bound"
            continue
        cost.evidence = evidence
        if cost.calls == 0 and cost.engine is None:
            cost.floor = "unobserved"
            continue
        compile_ratio = (cost.compiles / cost.calls
                         if cost.calls else 0.0)
        evidence["compile_ratio"] = round(compile_ratio, 4)
        engine_queue = (cost.engine or {}).get("queue_median_s", 0.0)
        engine_adopt = (cost.engine or {}).get("adopt_median_s", 0.0)
        engine_checkpoint = (cost.engine or {}).get(
            "checkpoint_median_s", 0.0)
        queue_wait = max(cost.queue_median_s, engine_queue)
        if cost.compiles and compile_ratio >= COMPILE_RATIO_BOUND:
            cost.floor = "compile-bound"
        elif engine_adopt > max(cost.compute_median_s, queue_wait,
                                engine_checkpoint, floor_s):
            # disaggregated adoption dominates: the KV migration, not
            # the kernel or the slot queue, is the floor
            cost.floor = "migration-bound"
        elif engine_checkpoint > max(cost.compute_median_s, queue_wait,
                                     floor_s):
            # the warm-failover snapshot cadence dominates: the engine
            # pump spends its ticks gathering/offering KV deltas, not
            # decoding -- stretch checkpoint_every/max_checkpoint_lag
            # (trading crash-time re-decode for hot-loop headroom), a
            # bigger slot pool will not help
            cost.floor = "checkpoint-bound"
        elif queue_wait > max(cost.compute_median_s, floor_s):
            cost.floor = "queue-bound"
        elif ((cost.engine or {}).get("prefix_requests", 0)
              and (cost.engine or {}).get("prefix_hit_rate", 0.0)
              >= CACHE_HIT_RATE_BOUND):
            # most prefills borrowed their prompt's leading KV from
            # the prefix cache: the measured prefill span is the
            # uncached tail, so the floor is cache residency (what the
            # cache misses), not the prefill kernel's speed
            cost.floor = "cache-bound"
        elif cost.per_call_median_s <= floor_s or (
                cost.achieved_utilization is not None
                and cost.achieved_utilization < LOW_UTILIZATION_BOUND
                and cost.per_call_median_s
                <= floor_s * DISPATCH_SPAN_MULTIPLE):
            cost.floor = "dispatch-bound"
        else:
            cost.floor = "compute-bound"
