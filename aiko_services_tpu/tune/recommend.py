# Recommender: floor classifications + an SLO -> concrete settings,
# each carrying the evidence spans that justify it, plus the
# write-back (`aiko tune --apply`) that turns recommendations into a
# definition document and re-lints it.
#
# Rules are deliberately mechanical (this is the "stop hand-tuning"
# subsystem -- an operator must be able to read WHY a knob moved):
#
#   dispatch-bound + throughput  double micro_batch (amortize the
#                                per-call floor), cap max_micro_batch;
#                                chained-only elements get
#                                micro_batch_fused re-enabled first
#   queue-bound, starved groups  (median occupancy < micro_batch/2)
#                                shrink micro_batch to the observed
#                                occupancy -- the scheduler is waiting
#                                for frames that are not coming
#   queue-bound, full groups     the element is backlogged: raise the
#                                replica floor (autoscale_policy min=)
#   compute-bound (bottleneck)   no per-element knob helps; raise the
#                                replica floor under a throughput SLO
#   compile-bound                pin frame_window to a micro_batch
#                                multiple so arity stays stable
#   engine queue-bound           raise decode_slots; chronic
#                                preemption notes kv block sizing
#   latency SLO                  frame_window -> 1 and micro_batch -> 1
#                                on elements whose queue wait exceeds
#                                compute (coalescing wait IS the
#                                latency)
#
# A p99_ms budget is enforced through the what-if replayer: proposed
# micro_batch values are halved (largest first) until the predicted
# p99 fits.  A TIGHTER budget therefore never RAISES micro_batch --
# the monotonicity contract tests/test_tune.py pins.

from __future__ import annotations

import copy
from dataclasses import dataclass, field

from ..analyze.diagnostics import Diagnostic
from .replay import element_settings_of, predict

__all__ = ["Recommendation", "recommend", "apply_recommendations"]


@dataclass
class Recommendation:
    target: str          # "element:<name>" | "pipeline" | "gateway"
    knob: str
    current: object
    proposed: object
    reason: str
    floor: str = ""
    evidence: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"target": self.target, "knob": self.knob,
                "current": self.current, "proposed": self.proposed,
                "reason": self.reason, "floor": self.floor,
                "evidence": self.evidence}


def _pow2_at_least(value: float) -> int:
    result = 1
    while result < value:
        result *= 2
    return result


def recommend(model, slo, definition_document: dict | None) -> list:
    """The recommendation list for one cost model under one SLO."""
    recommendations: list = []
    settings = element_settings_of(definition_document)
    element_parameters = {
        element.get("name", ""): element.get("parameters") or {}
        for element in (definition_document or {}).get("elements", [])}
    pipeline_parameters = (definition_document or {}).get(
        "parameters") or {}
    latency_mode = slo.objective == "latency"
    replica_floor = 1
    baseline = predict(model, settings)

    for name, cost in sorted(model.elements.items()):
        if cost.floor == "unobserved":
            continue
        current_micro = settings["elements"].get(name, {}).get(
            "micro_batch", 1)
        parameters = element_parameters.get(name, {})
        if cost.gateway is not None:
            # serving-tier pseudo-node (fleet-scope traces): the
            # kernel-floor branches below prescribe element knobs the
            # gateway does not have
            recommendations.extend(_gateway_floor_recommendations(
                name, cost, pipeline_parameters, slo))
            continue
        if cost.engine is not None:
            recommendations.extend(
                _engine_recommendations(name, cost, parameters, slo))
            continue
        if latency_mode:
            if (cost.queue_median_s > cost.compute_median_s
                    and current_micro > 1):
                recommendations.append(Recommendation(
                    f"element:{name}", "micro_batch", current_micro, 1,
                    "latency SLO: coalescing wait exceeds compute -- "
                    "one frame per call removes the group-fill wait",
                    floor=cost.floor, evidence=cost.evidence))
            continue
        if cost.floor == "dispatch-bound":
            if (cost.paths.get("chained", 0)
                    and not cost.paths.get("fused", 0)
                    and current_micro > 1
                    and parameters.get("micro_batch_fused") is False):
                recommendations.append(Recommendation(
                    f"element:{name}", "micro_batch_fused", False,
                    True,
                    "dispatch-bound on the chained path: fusing the "
                    "concat+kernel+split group removes per-element "
                    "dispatches", floor=cost.floor,
                    evidence=cost.evidence))
            proposed = min(max(current_micro * 2, 2),
                           slo.max_micro_batch)
            if proposed > current_micro:
                recommendations.append(Recommendation(
                    f"element:{name}", "micro_batch", current_micro,
                    proposed,
                    "dispatch-bound: per-call time sits at the "
                    "dispatch floor, so doubling the coalesced group "
                    "amortizes it across more frames",
                    floor=cost.floor, evidence=cost.evidence))
        elif cost.floor == "queue-bound":
            occupancy = cost.group_median
            if current_micro > 1 and occupancy < current_micro / 2.0:
                proposed = max(_pow2_at_least(occupancy), 1)
                if proposed < current_micro:
                    recommendations.append(Recommendation(
                        f"element:{name}", "micro_batch",
                        current_micro, proposed,
                        "queue-bound with starved groups (median "
                        f"occupancy {occupancy:g} of {current_micro}):"
                        " the scheduler waits for frames that are not "
                        "arriving -- shrink the group to what the "
                        "stream actually delivers",
                        floor=cost.floor, evidence=cost.evidence))
            else:
                replica_floor = max(replica_floor, 2)
        elif cost.floor == "compile-bound":
            window = settings.get("frame_window", 16)
            proposed_window = max(current_micro * 2, window)
            if proposed_window % max(current_micro, 1):
                proposed_window = current_micro * 2
            if proposed_window != window:
                recommendations.append(Recommendation(
                    "pipeline", "frame_window", window,
                    proposed_window,
                    f"compile-bound at {name}: a frame_window that is "
                    "a micro_batch multiple keeps group arity stable, "
                    "so one executable serves the steady state",
                    floor=cost.floor, evidence=cost.evidence))
        elif cost.floor == "compute-bound":
            if baseline.get("bottleneck") == name:
                replica_floor = max(replica_floor, 2)

    if latency_mode:
        window = settings.get("frame_window", 16)
        if window != 1:
            recommendations.append(Recommendation(
                "pipeline", "frame_window", window, 1,
                "latency SLO: one frame in flight end-to-end makes "
                "p50 true service latency instead of queueing depth",
                floor="", evidence={"frame_window": window}))
    elif replica_floor > 1 and slo.max_replicas > 1:
        replica_floor = min(replica_floor, slo.max_replicas)
        current_policy = pipeline_parameters.get("autoscale_policy")
        if current_policy:
            recommendations.append(Recommendation(
                "gateway", "replicas",
                str(current_policy), replica_floor,
                "bottleneck element is compute/queue-bound at "
                "capacity; an existing autoscale_policy is left "
                "untouched -- raise its min= floor manually",
                floor="", evidence={"replica_floor": replica_floor}))
        else:
            recommendations.append(Recommendation(
                "gateway", "autoscale_policy", None,
                f"min_replicas={replica_floor};"
                f"max_replicas={slo.max_replicas}",
                "bottleneck element is compute/queue-bound at "
                "capacity: only more replicas raise throughput",
                floor="", evidence={"replica_floor": replica_floor}))

    # gateway admission (measured capacity -> rate) is appended by the
    # caller via admission_recommendation, which sees the bench config
    # block the trace embeds
    return _fit_budget(model, slo, settings, recommendations)


def admission_recommendation(config: dict | None,
                             pipeline_parameters: dict | None) -> \
        "Recommendation | None":
    """Gateway admission rate from a measured capacity in the bench
    config block: admit at 90% of what the pipeline demonstrably
    serves.  Skipped when the definition already pins a
    gateway_policy (never silently overwrite an operator's policy)."""
    capacity = None
    source_key = None
    for key in ("goodput_frames_per_sec", "frames_per_sec_total",
                "frames_per_sec_chip"):
        value = (config or {}).get(key)
        if isinstance(value, (int, float)) and value > 0:
            capacity, source_key = float(value), key
            break
    if capacity is None:
        return None
    if (pipeline_parameters or {}).get("gateway_policy"):
        return None
    rate = round(capacity * 0.9, 2)
    burst = max(int(rate // 4), 1)
    return Recommendation(
        "gateway", "gateway_policy", None,
        f"bucket:0={rate:g}/{burst}",
        f"measured capacity {capacity:g} frames/s ({source_key}): "
        "admitting at 90% keeps queue wait bounded under overload",
        floor="", evidence={source_key: capacity})


def _gateway_floor_recommendations(name, cost, pipeline_parameters,
                                   slo) -> list:
    """The admission-bound branch: admit-wait (submit -> dispatch,
    parked wait included) dominates every element's compute+queue
    share, so streams wait at the GATE -- more replicas drain the
    parked queue, and a rate cap keeps the wait bounded (the paired
    admission_recommendation computes the rate from measured
    capacity).  A dispatch-bound gateway gets no recommendation: it is
    not the bottleneck tier."""
    if cost.floor != "admission-bound":
        return []
    if slo.max_replicas <= 1:
        # the operator pinned the fleet to one replica: recommending a
        # higher floor would overrun the stated ceiling (mirroring the
        # compute-bound replica-floor branch) -- only the paired
        # admission-rate recommendation can help here
        return []
    gateway = cost.gateway or {}
    evidence = dict(cost.evidence)
    recommendations = []
    floor = 2
    current_policy = (pipeline_parameters or {}).get("autoscale_policy")
    admit_ms = gateway.get("admit_median_s", 0.0) * 1e3
    reason = (f"admission-bound: median admit-wait {admit_ms:.1f} ms "
              f"exceeds the busiest element's compute+queue share "
              f"({evidence.get('fleet_busy_ms', 0):g} ms) -- streams "
              f"wait at the gate, not in any kernel; raise the replica "
              f"floor (and cap the admission rate at measured "
              f"capacity)")
    if current_policy:
        recommendations.append(Recommendation(
            "gateway", "replicas", str(current_policy), floor,
            reason + " -- an existing autoscale_policy is left "
            "untouched: raise its min= floor manually",
            floor=cost.floor, evidence=evidence))
    else:
        recommendations.append(Recommendation(
            "gateway", "autoscale_policy", None,
            f"min_replicas={floor};max_replicas="
            f"{max(slo.max_replicas, floor)}",
            reason, floor=cost.floor, evidence=evidence))
    return recommendations


def _engine_recommendations(name, cost, parameters, slo) -> list:
    recommendations = []
    engine = cost.engine or {}
    slots = int(parameters.get("decode_slots", 4) or 4)
    block_size = int(parameters.get("kv_block_size", 16) or 16)
    compute = (engine.get("prefill_median_s", 0.0)
               + engine.get("decode_median_s", 0.0))
    if cost.floor == "migration-bound":
        # the KV migration, not the kernel or the slot pool, floors
        # this element: more decode slots cannot help -- grow the
        # PREFILL pool (or shorten the transfer path) so adoptions
        # stop dominating, and skip the slot-wait heuristic below
        # (it would prescribe slots for a wire problem)
        recommendations.append(Recommendation(
            "gateway", "disagg_min_replicas_prefill",
            None, 2,
            f"migration-bound at {name}: KV adoption (median "
            f"{engine.get('adopt_median_s', 0.0) * 1e3:.1f} ms) "
            "dominates compute and queue wait -- raise the prefill "
            "pool floor (disagg `min_replicas:prefill=`) or move the "
            "pools closer",
            floor=cost.floor, evidence=cost.evidence))
        return recommendations
    if cost.floor == "checkpoint-bound":
        # the warm-failover snapshot cadence floors this engine: the
        # pump spends its ticks gathering/offering KV deltas.  Halve
        # the snapshot frequency (double checkpoint_every, and lift
        # max_checkpoint_lag to match so forced snapshots do not
        # reinstate the old cadence) -- the price is a longer crash-
        # time re-decode, bounded by the new max_checkpoint_lag
        spec = str(parameters.get("checkpoint", "") or "")
        keeper = ""
        try:
            from ..decode.checkpoint import CheckpointPolicy
            policy = CheckpointPolicy.parse(spec)
            current_every = policy.checkpoint_every
            current_lag = policy.max_checkpoint_lag
            keeper = policy.keeper
        except ValueError:
            current_every, current_lag = 8, 32
        proposed_lag = max(current_lag, current_every * 2)
        proposed = (f"checkpoint_every={current_every * 2};"
                    f"max_checkpoint_lag={proposed_lag}")
        if keeper:
            # carry the keeper forward: a proposal that dropped it
            # would silently DISABLE checkpointing when applied
            proposed += f";keeper={keeper}"
        recommendations.append(Recommendation(
            f"element:{name}", "checkpoint", spec or None, proposed,
            f"checkpoint-bound at {name}: snapshot shipping (median "
            f"{engine.get('checkpoint_median_s', 0.0) * 1e3:.1f} ms) "
            "dominates compute and queue wait -- stretch the cadence "
            "(crash-time re-decode grows to the new "
            "max_checkpoint_lag, hot-loop headroom returns)",
            floor=cost.floor, evidence=cost.evidence))
        return recommendations
    if cost.floor == "cache-bound":
        # most prefills borrowed their prompt's leading KV from the
        # prefix cache, so the measured prefill median is the uncached
        # TAIL: the slot/block heuristics below would size the pool
        # for work the cache already absorbed.  The knob that matters
        # is keeping the cache armed across redeploys -- pin
        # prefix_policy when the definition leaves it implicit (and
        # only then: a pin of an already-pinned policy would be a
        # proposed==current no-op)
        if not parameters.get("prefix_policy"):
            recommendations.append(Recommendation(
                f"element:{name}", "prefix_policy", None,
                "prefix_cache=on",
                f"cache-bound at {name}: "
                f"{engine.get('prefix_hit_rate', 0.0):.0%} of judged "
                "prefills borrowed cached prefix KV "
                f"({engine.get('prefix_blocks', 0)} blocks total) -- "
                "pin the policy so redeploys keep the cache, and read "
                "prefill medians as cache-residual tail time, not "
                "kernel time",
                floor=cost.floor, evidence=cost.evidence))
        return recommendations
    if engine.get("queue_median_s", 0.0) > max(compute, 1e-9):
        proposed = min(slots * 2, 64)
        if proposed > slots:
            recommendations.append(Recommendation(
                f"element:{name}", "decode_slots", slots, proposed,
                "engine slot wait exceeds prefill+decode: requests "
                "queue for slots, not for the chip -- more concurrent "
                "slots drain the admission queue",
                floor=cost.floor, evidence=cost.evidence))
    requests = max(engine.get("requests", 0), 1)
    tokens_per_request = engine.get("tokens", 0) / requests
    if (engine.get("preemptions", 0) == 0 and tokens_per_request
            and block_size >= 2
            and tokens_per_request < block_size / 2.0):
        proposed_block = max(block_size // 2, 1)
        recommendations.append(Recommendation(
            f"element:{name}", "kv_block_size", block_size,
            proposed_block,
            f"completions average {tokens_per_request:g} tokens but "
            f"KV blocks hold {block_size}: halving the block halves "
            "over-allocation, so the same pool admits more requests",
            floor=cost.floor, evidence=cost.evidence))
    return recommendations


def _fit_budget(model, slo, settings, recommendations) -> list:
    """Enforce an explicit p99 budget through the replayer: halve the
    LARGEST proposed micro_batch until the prediction fits (or every
    proposal is at 1).  Tighter budget -> monotonically smaller (never
    larger) proposed micro_batch."""
    if slo.p99_budget_s is None:
        return recommendations
    budget_ms = slo.p99_budget_s * 1e3

    def proposal_overrides():
        overrides: dict = {"elements": {}}
        for recommendation in recommendations:
            if (recommendation.knob == "micro_batch"
                    and recommendation.target.startswith("element:")):
                element = recommendation.target.split(":", 1)[1]
                overrides["elements"].setdefault(element, {})[
                    "micro_batch"] = recommendation.proposed
            elif (recommendation.target, recommendation.knob) == (
                    "pipeline", "frame_window"):
                overrides["frame_window"] = recommendation.proposed
        return overrides

    while True:
        score = predict(model, settings, proposal_overrides())
        if score["p99_ms"] <= budget_ms:
            break
        candidates = [r for r in recommendations
                      if r.knob == "micro_batch"
                      and isinstance(r.proposed, int)
                      and r.proposed > 1]
        if not candidates:
            break
        largest = max(candidates, key=lambda r: r.proposed)
        largest.proposed = max(largest.proposed // 2, 1)
        largest.reason += (
            f" [halved to fit p99 budget {budget_ms:g} ms]"
            if "[halved to fit" not in largest.reason else "")
    # proposals reduced all the way to the current value say nothing
    return [r for r in recommendations
            if r.proposed != r.current]


def apply_recommendations(definition_document: dict,
                          recommendations: list) -> tuple:
    """Write recommendations back into a COPY of the definition
    document.  Returns (new_document, diagnostics): knobs whose target
    is missing from the definition become AIKO502 diagnostics instead
    of silent drops."""
    document = copy.deepcopy(definition_document)
    diagnostics: list = []
    elements = {element.get("name"): element
                for element in document.get("elements", [])}
    for recommendation in recommendations:
        if recommendation.target.startswith("element:"):
            name = recommendation.target.split(":", 1)[1]
            element = elements.get(name)
            if element is None:
                diagnostics.append(Diagnostic(
                    "AIKO502",
                    f"recommendation {recommendation.knob}="
                    f"{recommendation.proposed} targets element "
                    f"{name!r}, absent from the definition",
                    definition=document.get("name", "")))
                continue
            element.setdefault("parameters", {})[
                recommendation.knob] = recommendation.proposed
        elif recommendation.target == "pipeline":
            document.setdefault("parameters", {})[
                recommendation.knob] = recommendation.proposed
        elif recommendation.target == "gateway":
            if recommendation.knob in ("autoscale_policy",
                                       "gateway_policy"):
                parameters = document.setdefault("parameters", {})
                if parameters.get(recommendation.knob):
                    diagnostics.append(Diagnostic(
                        "AIKO502",
                        f"{recommendation.knob} already set; "
                        f"proposed {recommendation.proposed!r} NOT "
                        f"applied", definition=document.get("name",
                                                            "")))
                else:
                    parameters[recommendation.knob] = \
                        recommendation.proposed
            else:
                diagnostics.append(Diagnostic(
                    "AIKO502",
                    f"gateway knob {recommendation.knob!r} has no "
                    f"definition representation; apply it to the "
                    f"serving tier directly",
                    definition=document.get("name", "")))
    return document, diagnostics
