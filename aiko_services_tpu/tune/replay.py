# What-if replayer: re-score a recorded trace under proposed settings
# WITHOUT hardware.
#
# The cost model gives every element an observed per-call time at an
# observed coalesced group size.  The replayer decomposes that into a
# fixed per-call cost (the dispatch floor -- paid once per call
# regardless of batch) plus a linear per-frame cost, then predicts the
# pipeline's steady-state throughput and latency at a DIFFERENT
# micro_batch / frame_window / replica setting from that decomposition:
#
#   per_call(m)  = fixed + slope * m
#   share(m)     = per_call(m) / m          (per-frame cost)
#   throughput   = replicas / max_e share_e(m_e)  (slowest stage rules)
#   service p50  = sum_e per_call_e(m_e) + coalesce wait
#   coalesce wait= (m_e - 1) / (2 * offered rate)   per micro element
#   p99          = p50 * (observed p99 / observed p50)  (shape carried
#                  over from the recorded distribution)
#
# Deliberately simple, fully deterministic arithmetic over the
# recorded medians: two runs over the same trace + settings produce
# bit-identical scores, which is what lets CI assert recommendation
# determinism on a fixture trace.  The model's job is to RANK settings
# and bound budgets, not to forecast absolute numbers -- every score
# carries the inputs it was computed from.

from __future__ import annotations

__all__ = ["predict", "element_settings_of"]


def element_settings_of(definition_document: dict | None) -> dict:
    """Current knob values per element (micro_batch and the decode
    knobs), plus pipeline-level frame_window -- the baseline the
    replayer scores proposals against."""
    settings: dict = {"elements": {}, "frame_window": 16,
                      "replicas": 1}
    if not definition_document:
        return settings
    parameters = definition_document.get("parameters") or {}
    try:
        settings["frame_window"] = int(
            parameters.get("frame_window", 16))
    except (TypeError, ValueError):
        pass
    for element in definition_document.get("elements") or []:
        element_parameters = element.get("parameters") or {}
        knobs = {}
        for knob in ("micro_batch", "decode_slots", "kv_block_size"):
            value = element_parameters.get(knob)
            if value is not None:
                try:
                    knobs[knob] = int(value)
                except (TypeError, ValueError):
                    continue
        knobs.setdefault("micro_batch", 1)
        settings["elements"][element.get("name", "")] = knobs
    return settings


def _merge(base: dict, overrides: dict | None) -> dict:
    merged = {"elements": {name: dict(knobs) for name, knobs
                           in (base.get("elements") or {}).items()},
              "frame_window": base.get("frame_window", 16),
              "replicas": base.get("replicas", 1)}
    for key, value in (overrides or {}).items():
        if key == "elements":
            for name, knobs in (value or {}).items():
                merged["elements"].setdefault(name, {}).update(knobs)
        else:
            merged[key] = value
    return merged


def predict(model, settings: dict, overrides: dict | None = None,
            offered_rate: float | None = None) -> dict:
    """Score one settings dict against the cost model.  Returns
    {"frames_per_sec", "p50_ms", "p99_ms", "bottleneck",
    "per_element"} -- pure arithmetic, bit-deterministic."""
    merged = _merge(settings, overrides)
    replicas = max(int(merged.get("replicas", 1)), 1)
    offered = offered_rate if offered_rate else model.frames_per_sec
    floor_s = model.dispatch_floor_s
    per_element = {}
    slowest_share = 0.0
    bottleneck = ""
    service_s = 0.0
    for name, cost in sorted(model.elements.items()):
        if cost.calls == 0 and cost.engine is None:
            continue
        knobs = merged["elements"].get(name, {})
        group0 = max(cost.group_median, 1.0)
        per_call0 = max(cost.per_call_median_s,
                        cost.compute_median_s, 0.0)
        micro = max(int(knobs.get("micro_batch", round(group0))), 1)
        if cost.engine is not None:
            # engine-managed: slots scale concurrency, not padding.
            # Service time per request is prefill + decode; the slot
            # wait scales inversely with decode_slots
            slots0 = max(int(knobs.get("decode_slots", 0)) or 1, 1)
            base_slots = max(round(group0), 1)
            wait0 = cost.engine.get("queue_median_s", 0.0)
            wait = wait0 * base_slots / slots0 if slots0 else wait0
            compute = (cost.engine.get("prefill_median_s", 0.0)
                       + cost.engine.get("decode_median_s", 0.0)) \
                or per_call0
            share = compute / max(slots0, 1)
            element_service = compute + wait
        else:
            fixed = min(floor_s, per_call0)
            slope = max((per_call0 - fixed) / group0, 0.0)
            per_call = fixed + slope * micro
            share = per_call / micro
            coalesce_wait = ((micro - 1) / (2.0 * offered)
                             if offered > 0 and micro > 1 else 0.0)
            element_service = per_call + coalesce_wait
        service_s += element_service
        if share > slowest_share:
            slowest_share = share
            bottleneck = name
        per_element[name] = {
            "share_ms": round(share * 1e3, 6),
            "service_ms": round(element_service * 1e3, 6),
        }
    throughput = (replicas / slowest_share if slowest_share > 0
                  else 0.0)
    ratio = (model.frame_p99_s / model.frame_p50_s
             if model.frame_p50_s > 0 else 1.0)
    p50_s = service_s
    return {
        "frames_per_sec": round(throughput, 4),
        "p50_ms": round(p50_s * 1e3, 4),
        "p99_ms": round(p50_s * ratio * 1e3, 4),
        "bottleneck": bottleneck,
        "replicas": replicas,
        "per_element": per_element,
    }
