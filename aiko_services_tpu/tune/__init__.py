# tune/: profile-guided pipeline optimizer -- the layer that closes
# the analyze -> observe loop (ROADMAP open item #5, ISSUE 10).
#
# The repo carries a complete STATIC model of every pipeline (analyze/:
# typed tensor-port flow, jax.eval_shape dry-runs) and a complete
# DYNAMIC one (observe/: per-element spans, queue-wait vs compute
# split, Perfetto traces, metrics snapshots).  This package consumes
# them TOGETHER:
#
#   loader.py     ingest one recorded trace artifact (self-describing
#                 since round 14: definition + fingerprint + bench
#                 config block + metrics snapshot ride the metadata)
#                 and join every span to a typed graph node
#   model.py      per-element cost model (dynamic medians x static
#                 FLOP/byte estimates) + the analytical floor
#                 classifier: dispatch- / compute- / queue- /
#                 compile-bound, with evidence
#   recommend.py  floors + an SLO -> concrete settings (micro_batch,
#                 frame_window, fused-vs-chained, decode_slots /
#                 kv_block_size, replica floor, admission rate), and
#                 the --apply write-back through the linter
#   replay.py     what-if scoring of a trace under proposed settings:
#                 pure deterministic arithmetic, so CI asserts
#                 recommendation determinism on a fixture trace
#   slo.py        the tune directive grammar (AIKO501 via the shared
#                 directive core)
#
# `run_tune` is the CLI's whole pipeline: trace path in, report dict
# out.  The report is rendered with sorted keys and NO timestamps, so
# the same trace + spec always produces byte-identical JSON.

from __future__ import annotations

import json

from .loader import (                                       # noqa: F401
    ElementProfile, LoadedTrace, TraceLoadError, load_trace)
from .model import (                                        # noqa: F401
    CostModel, ElementCost, classify_elements)
from .recommend import (                                    # noqa: F401
    Recommendation, admission_recommendation, apply_recommendations,
    recommend)
from .replay import element_settings_of, predict            # noqa: F401
from .slo import SloSpec, TUNE_GRAMMAR, check_tune_spec     # noqa: F401

__all__ = [
    "ElementProfile", "LoadedTrace", "TraceLoadError", "load_trace",
    "CostModel", "ElementCost", "classify_elements",
    "Recommendation", "admission_recommendation",
    "apply_recommendations", "recommend",
    "element_settings_of", "predict",
    "SloSpec", "TUNE_GRAMMAR", "check_tune_spec",
    "build_report", "render_report", "run_tune", "report_json",
]

REPORT_VERSION = 1


def build_report(loaded: LoadedTrace, model: CostModel, slo: SloSpec,
                 recommendations: list, baseline: dict,
                 proposed: dict) -> dict:
    """The machine-readable tune report (README "Performance tuning"
    documents the schema).  Deterministic: derived from the trace
    content only."""
    elements = {}
    for name, cost in sorted(model.elements.items()):
        elements[name] = {
            "floor": cost.floor,
            "calls": cost.calls,
            "compute_median_ms": round(cost.compute_median_s * 1e3, 4),
            "queue_median_ms": round(cost.queue_median_s * 1e3, 4),
            "per_call_median_ms": round(
                cost.per_call_median_s * 1e3, 4),
            "group_median": round(cost.group_median, 2),
            "paths": dict(sorted(cost.paths.items())),
            "compiles": cost.compiles,
            "evidence": cost.evidence,
        }
        if cost.flops_per_row is not None:
            elements[name]["flops_per_row"] = cost.flops_per_row
        if cost.bytes_per_row is not None:
            elements[name]["bytes_per_row"] = cost.bytes_per_row
        if cost.achieved_utilization is not None:
            elements[name]["achieved_utilization"] = round(
                cost.achieved_utilization, 5)
        if cost.engine is not None:
            elements[name]["engine"] = {
                key: (round(value, 6)
                      if isinstance(value, float) else value)
                for key, value in cost.engine.items()}
        if cost.gateway is not None:
            elements[name]["gateway"] = {
                key: (round(value, 6)
                      if isinstance(value, float) else value)
                for key, value in cost.gateway.items()}
    dominant = ""
    if elements:
        observed = [(record["per_call_median_ms"], name)
                    for name, record in elements.items()
                    if record["floor"] != "unobserved"]
        if observed:
            dominant = max(observed)[1]
    return {
        "version": REPORT_VERSION,
        "pipeline": (loaded.definition_document or {}).get("name", ""),
        "trace": loaded.path,
        "fingerprint": loaded.fingerprint,
        "config_name": loaded.config_name,
        "slo": {
            "objective": slo.objective,
            "p99_ms": (round(slo.p99_budget_s * 1e3, 3)
                       if slo.p99_budget_s is not None else None),
            "spec": slo.spec,
        },
        "observed": {
            "frames": loaded.frame_count,
            "frame_statuses": dict(sorted(
                loaded.frame_statuses.items())),
            "wall_s": round(loaded.wall_s, 6),
            "frames_per_sec": round(model.frames_per_sec, 4),
            "p50_ms": round(model.frame_p50_s * 1e3, 4),
            "p99_ms": round(model.frame_p99_s * 1e3, 4),
        },
        "dominant_floor_element": dominant,
        "elements": elements,
        "recommendations": [recommendation.to_dict()
                            for recommendation in recommendations],
        "replay": {"baseline": baseline, "proposed": proposed},
        "diagnostics": [
            {"code": diagnostic.code, "severity": diagnostic.severity,
             "message": diagnostic.message}
            for diagnostic in loaded.diagnostics],
    }


def report_json(report: dict) -> str:
    """THE byte-deterministic rendering CI diffs two runs of."""
    return json.dumps(report, indent=2, sort_keys=True)


def render_report(report: dict) -> str:
    """Human-readable rendering of the same report."""
    lines = [f"tune report v{report['version']}: "
             f"{report['pipeline'] or '(unjoined trace)'}"
             + (f" [{report['config_name']}]"
                if report.get("config_name") else "")]
    observed = report["observed"]
    lines.append(
        f"observed: {observed['frames']} frames over "
        f"{observed['wall_s']:.3f}s = "
        f"{observed['frames_per_sec']:g} frames/s, "
        f"p50 {observed['p50_ms']:g} ms, p99 {observed['p99_ms']:g} ms")
    slo = report["slo"]
    lines.append(f"slo: {slo['objective']}"
                 + (f", p99 budget {slo['p99_ms']:g} ms"
                    if slo.get("p99_ms") else ""))
    lines.append("floors:")
    for name, record in sorted(report["elements"].items()):
        extra = ""
        if record.get("achieved_utilization") is not None:
            extra = f"  util {record['achieved_utilization']:.4f}"
        lines.append(
            f"  {name:12} {record['floor']:15} "
            f"compute {record['compute_median_ms']:g} ms  "
            f"queue {record['queue_median_ms']:g} ms  "
            f"group {record['group_median']:g}  "
            f"compiles {record['compiles']}{extra}")
    if report["recommendations"]:
        lines.append("recommendations:")
        for recommendation in report["recommendations"]:
            lines.append(
                f"  {recommendation['target']}: "
                f"{recommendation['knob']} "
                f"{recommendation['current']!r} -> "
                f"{recommendation['proposed']!r}  "
                f"({recommendation['reason']})")
    else:
        lines.append("recommendations: none -- the observed floors "
                     "are already at their configured knobs")
    replay = report["replay"]
    if replay.get("proposed"):
        lines.append(
            f"what-if replay: {replay['baseline']['frames_per_sec']:g}"
            f" -> {replay['proposed']['frames_per_sec']:g} frames/s, "
            f"p99 {replay['baseline']['p99_ms']:g} -> "
            f"{replay['proposed']['p99_ms']:g} ms "
            f"(bottleneck {replay['proposed']['bottleneck'] or '-'})")
    for diagnostic in report["diagnostics"]:
        lines.append(f"  {diagnostic['code']} "
                     f"[{diagnostic['severity']}] "
                     f"{diagnostic['message']}")
    return "\n".join(lines)


def run_tune(trace_path: str, slo_spec=None, definition=None,
             run: str | None = None, include_flops: bool = True,
             static_costs: dict | None = None,
             loaded: LoadedTrace | None = None) -> dict:
    """trace artifact -> tune report dict (loader -> cost model ->
    classifier -> recommender -> what-if replay).  Pass `loaded` to
    reuse an already-parsed trace (the CLI's --apply path loads
    once)."""
    slo = slo_spec if isinstance(slo_spec, SloSpec) \
        else SloSpec.parse(slo_spec)
    if loaded is None:
        loaded = load_trace(trace_path, definition=definition,
                            run=run)
    if static_costs is None and loaded.definition is not None:
        from ..analyze.shape_eval import element_cost_estimates
        try:
            static_costs = element_cost_estimates(
                loaded.definition, include_flops=include_flops)
        except Exception:
            static_costs = {}
    model = CostModel.from_trace(
        loaded, static_costs=static_costs,
        dispatch_floor_s=slo.dispatch_floor_s,
        peak_flops=slo.peak_flops)
    classify_elements(model)
    recommendations = recommend(model, slo,
                                loaded.definition_document)
    admission = admission_recommendation(
        loaded.config,
        (loaded.definition_document or {}).get("parameters"))
    if admission is not None:
        recommendations.append(admission)
    settings = element_settings_of(loaded.definition_document)
    baseline = predict(model, settings)
    overrides: dict = {"elements": {}}
    for recommendation in recommendations:
        if recommendation.target.startswith("element:"):
            element = recommendation.target.split(":", 1)[1]
            if isinstance(recommendation.proposed, int):
                overrides["elements"].setdefault(element, {})[
                    recommendation.knob] = recommendation.proposed
        elif (recommendation.target, recommendation.knob) == (
                "pipeline", "frame_window"):
            overrides["frame_window"] = recommendation.proposed
        elif recommendation.knob == "autoscale_policy":
            try:
                floor = int(str(recommendation.proposed)
                            .split("min_replicas=")[1].split(";")[0])
                overrides["replicas"] = floor
            except (IndexError, ValueError):
                pass
    proposed = predict(model, settings, overrides)
    return build_report(loaded, model, slo, recommendations,
                        baseline, proposed)
