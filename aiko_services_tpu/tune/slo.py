# Tune directive grammar: the operator-facing SLO/knob spec `aiko
# tune` is pointed at, parsed through the SAME shared directive core
# (analyze/grammar.py) as the fault, admission, autoscale, and journal
# grammars -- so a typo'd SLO is an offline AIKO501 lint finding (a
# definition may pin its intended operating point in a `tune`
# parameter), and the CLI and `aiko lint` can never disagree about
# what a valid spec is.
#
# Grammar (`;`-separated key=value):
#
#   slo=throughput|latency      optimization objective (default
#                               throughput)
#   p99_ms=<float>              explicit p99 frame-latency budget: the
#                               recommender may trade throughput knobs
#                               away until the what-if replay predicts
#                               p99 under budget (tighter budgets can
#                               only LOWER micro_batch -- monotonicity
#                               is tested)
#   dispatch_floor_ms=<float>   per-call dispatch floor used by the
#                               floor classifier (default 1.5 ms, the
#                               measured tunnel call floor; on-die
#                               runtimes want ~0.05)
#   peak_tflops=<float>         per-chip peak for achieved-utilization
#                               evidence (default: from the trace's
#                               embedded bench config block)
#   max_micro_batch=<int>       recommendation ceiling (default 64)
#   max_replicas=<int>          recommendation ceiling (default 8)
#
# Shorthand: a bare "throughput" / "latency" means "slo=<word>".

from __future__ import annotations

from dataclasses import dataclass

from ..analyze.grammar import DirectiveGrammar, Field

__all__ = ["TUNE_GRAMMAR", "SloSpec", "check_tune_spec"]

DEFAULT_DISPATCH_FLOOR_MS = 1.5
DEFAULT_MAX_MICRO_BATCH = 64
DEFAULT_MAX_REPLICAS = 8

TUNE_GRAMMAR = DirectiveGrammar(
    "tune",
    options={
        "slo": Field("str", choices=("throughput", "latency")),
        "p99_ms": Field("float", minimum=1e-3),
        "dispatch_floor_ms": Field("float", minimum=0.0),
        "peak_tflops": Field("float", minimum=0.0),
        "max_micro_batch": Field("int", minimum=1),
        "max_replicas": Field("int", minimum=1),
    },
)


def _normalize(spec) -> str | dict | None:
    if spec is None:
        return None
    if isinstance(spec, dict):
        return spec
    text = str(spec).strip()
    if text.lower() in ("throughput", "latency"):
        return f"slo={text.lower()}"
    return text


@dataclass
class SloSpec:
    """One parsed tune directive spec."""

    objective: str = "throughput"       # throughput | latency
    p99_budget_s: float | None = None
    dispatch_floor_s: float = DEFAULT_DISPATCH_FLOOR_MS / 1000.0
    peak_flops: float | None = None
    max_micro_batch: int = DEFAULT_MAX_MICRO_BATCH
    max_replicas: int = DEFAULT_MAX_REPLICAS
    spec: str = ""

    @classmethod
    def parse(cls, spec) -> "SloSpec":
        """Parse with full validation (GrammarError on a bad spec)."""
        parsed = TUNE_GRAMMAR.parse(_normalize(spec))
        options = parsed.options
        slo = cls(spec="" if spec is None else str(spec))
        slo.objective = options.get("slo", "throughput")
        if "p99_ms" in options:
            slo.p99_budget_s = options["p99_ms"] / 1000.0
        if "dispatch_floor_ms" in options:
            slo.dispatch_floor_s = options["dispatch_floor_ms"] / 1000.0
        if "peak_tflops" in options:
            slo.peak_flops = options["peak_tflops"] * 1e12
        slo.max_micro_batch = options.get("max_micro_batch",
                                          DEFAULT_MAX_MICRO_BATCH)
        slo.max_replicas = options.get("max_replicas",
                                       DEFAULT_MAX_REPLICAS)
        return slo


def check_tune_spec(spec) -> list:
    """(code, message) problems in a tune directive spec -- the
    `aiko lint` surface (AIKO501; unknown directives are AIKO404),
    validated by the SAME grammar SloSpec.parse uses."""
    return TUNE_GRAMMAR.check(_normalize(spec), value_code="AIKO501")
