# Trace/metrics loader: one recorded bench run -> a per-element span
# profile joined against the static graph.
#
# Input is a Perfetto/Chrome-trace JSON artifact as exported by
# observe/trace.py (bench.py --trace writes one per config).  A
# round-14+ artifact is SELF-DESCRIBING: its metadata block embeds the
# pipeline definition, a parameter fingerprint, the bench config block,
# and a metrics-registry snapshot, so this loader needs no side-channel
# files.  Older traces still load, but carry an AIKO503 "metadata
# absent" diagnostic and need a --definition side channel before any
# classification can be attributed to typed nodes.
#
# The join: every span is attributed to its graph node by name, per
# THE span taxonomy (categories, "{kind}:{node}" naming scheme, and
# the time_queue_* vs time_* split) documented ONCE in
# observe/trace.py's module docstring; "gateway"-category spans join
# the "gateway" pseudo-node (no definition element by design).  Spans
# naming a node the definition does not declare, and definition
# elements that never produced a span, both surface as diagnostics
# instead of being silently dropped -- tune's whole value is that its
# numbers are attributable.

from __future__ import annotations

import json
from dataclasses import dataclass, field

from ..analyze.diagnostics import Diagnostic
from ..observe.trace import TRACE_METADATA_SCHEMA, trace_metadata_of

__all__ = ["ElementProfile", "LoadedTrace", "TraceLoadError",
           "load_trace"]


class TraceLoadError(ValueError):
    """The artifact is not a loadable trace (not JSON, not a
    Chrome-trace document, or an unknown metadata schema)."""


@dataclass
class ElementProfile:
    """Every span the trace attributes to ONE graph node."""

    name: str
    compute_s: list = field(default_factory=list)   # per-frame share
    queue_s: list = field(default_factory=list)     # scheduler wait
    groups: list = field(default_factory=list)      # coalesced sizes
    paths: dict = field(default_factory=dict)       # path -> count
    compiles: int = 0
    block_ready_s: list = field(default_factory=list)
    # engine-managed (decode/) spans, when present
    engine_queue_s: list = field(default_factory=list)
    engine_prefill_s: list = field(default_factory=list)
    engine_decode_s: list = field(default_factory=list)
    # disaggregated adoption: KV-migration fetch + pool scatter spans
    engine_adopt_s: list = field(default_factory=list)
    # warm KV failover: decode-state snapshot spans (global lane --
    # a checkpoint covers every due slot, not one frame)
    engine_checkpoint_s: list = field(default_factory=list)
    engine_preemptions: int = 0
    engine_tokens: int = 0
    # cross-request prefix reuse: per-completion evidence off the
    # prefill span args (requests with >= 1 borrowed block, and the
    # total blocks borrowed) -- the cache-bound floor's input
    engine_prefix_hits: int = 0
    engine_prefix_requests: int = 0
    engine_prefix_blocks: int = 0
    # serving-gateway spans (fleet-scope traces): admit-wait (frame
    # submit -> replica dispatch, parked wait included), route
    # decision, failover replay waves, and shed/throttle counts --
    # what the admission-bound floor classifies on
    gateway_admit_s: list = field(default_factory=list)
    gateway_route_s: list = field(default_factory=list)
    gateway_replay_s: list = field(default_factory=list)
    gateway_sheds: int = 0
    gateway_throttles: int = 0

    @property
    def calls(self) -> int:
        return len(self.compute_s)

    @property
    def is_engine_managed(self) -> bool:
        return bool(self.engine_prefill_s or self.engine_decode_s
                    or self.engine_adopt_s
                    or self.engine_checkpoint_s)

    @property
    def is_gateway(self) -> bool:
        """A serving-tier profile (the "gateway" pseudo-node): joined
        against no definition element, classified by the
        admission-bound branch instead of the kernel floors."""
        return bool(self.gateway_admit_s or self.gateway_route_s
                    or self.gateway_replay_s or self.gateway_sheds
                    or self.gateway_throttles)


@dataclass
class LoadedTrace:
    """One parsed artifact: profiles + the static context it embeds."""

    path: str
    metadata: dict | None
    definition_document: dict | None
    definition: object | None           # PipelineDefinition when joined
    config: dict = field(default_factory=dict)
    config_name: str = ""
    fingerprint: str = ""
    metrics: dict = field(default_factory=dict)
    elements: dict = field(default_factory=dict)    # name -> profile
    frame_durations_s: list = field(default_factory=list)
    frame_statuses: dict = field(default_factory=dict)
    wall_s: float = 0.0                 # first span start -> last end
    diagnostics: list = field(default_factory=list)

    @property
    def frame_count(self) -> int:
        return len(self.frame_durations_s)

    def diagnostic(self, code: str, message: str) -> None:
        self.diagnostics.append(Diagnostic(
            code, message,
            definition=(self.definition_document or {}).get("name", "")))


def _node_of(name: str) -> str:
    """Span name -> graph node: strip the category prefix
    ("queue:asr" -> "asr") and the engine row suffix
    ("decode_steps:lm[3]" -> "lm")."""
    if ":" in name:
        name = name.split(":", 1)[1]
    if name.endswith("]") and "[" in name:
        name = name[:name.rindex("[")]
    return name


def _select_run(metadata: dict, run: str | None, loaded: LoadedTrace):
    """Combined multi-pipeline artifacts (bench.py's legacy single
    file) carry per-run metadata under "runs"; pick one."""
    runs = metadata.get("runs")
    if not isinstance(runs, dict) or not runs:
        return metadata
    if run is None:
        if len(runs) == 1:
            return next(iter(runs.values()))
        loaded.diagnostic(
            "AIKO503",
            f"combined trace carries {len(runs)} runs "
            f"({sorted(runs)}); pass --run to pick one")
        return {}
    selected = runs.get(run)
    if selected is None:
        loaded.diagnostic(
            "AIKO503",
            f"run {run!r} not in trace (have {sorted(runs)})")
        return {}
    return selected


def load_trace(path: str, definition=None,
               run: str | None = None,
               document: dict | None = None) -> LoadedTrace:
    """Load one trace artifact and join it against the static graph.

    `definition` (document/path/PipelineDefinition) is the side
    channel for metadata-absent traces; when BOTH are present the
    explicit one wins and a fingerprint mismatch is diagnosed.

    Pass `document` to load an IN-MEMORY Chrome-trace document (the
    autopilot and `aiko tune --live` tune a live wire harvest without
    an artifact file); `path` then only labels the report."""
    from ..pipeline.definition import (
        DefinitionError, PipelineDefinition, parse_pipeline_definition)

    if document is None:
        try:
            with open(path) as handle:
                document = json.load(handle)
        except OSError as error:
            raise TraceLoadError(f"cannot read trace {path}: {error}") \
                from None
        except ValueError as error:
            raise TraceLoadError(f"{path} is not JSON: {error}") \
                from None
    if not isinstance(document, dict) \
            or not isinstance(document.get("traceEvents"), list):
        raise TraceLoadError(
            f"{path} is not a Chrome-trace document "
            f"(no traceEvents list)")

    metadata = trace_metadata_of(document)
    loaded = LoadedTrace(path=path, metadata=metadata,
                         definition_document=None, definition=None)
    allowed_pids: set | None = None
    if metadata is None:
        loaded.diagnostic(
            "AIKO503",
            f"{path} carries no aiko metadata block (recorded before "
            f"the self-describing trace schema, or by another tool); "
            f"pass an explicit definition to join its spans")
    else:
        schema = metadata.get("schema")
        if schema != TRACE_METADATA_SCHEMA:
            raise TraceLoadError(
                f"{path}: unknown trace metadata schema {schema!r} "
                f"(this build reads schema {TRACE_METADATA_SCHEMA})")
        combined = isinstance(metadata.get("runs"), dict)
        metadata = _select_run(metadata, run, loaded)
        loaded.definition_document = metadata.get("definition")
        loaded.config = metadata.get("config") or {}
        loaded.config_name = metadata.get("config_name") or ""
        loaded.fingerprint = metadata.get("fingerprint") or ""
        loaded.metrics = metadata.get("metrics") or {}
        if combined:
            # a COMBINED artifact carries every benched pipeline's
            # spans: keep only the selected run's tracer pids, or
            # other configs' same-named nodes would corrupt this
            # run's medians and frame counts
            pids = metadata.get("pids")
            if pids:
                allowed_pids = {int(pid) for pid in pids}
            elif metadata:
                loaded.diagnostic(
                    "AIKO503",
                    "combined trace run carries no tracer pid list; "
                    "spans from every run are ingested -- medians "
                    "may mix configs (re-record with this build)")

    if definition is not None:
        try:
            if isinstance(definition, PipelineDefinition):
                parsed = definition
            else:
                parsed = parse_pipeline_definition(definition,
                                                   validate=False)
            from ..pipeline.definition import definition_to_document
            side_document = definition_to_document(parsed)
            if loaded.definition_document is not None:
                from ..observe.trace import definition_fingerprint
                if (loaded.fingerprint
                        and definition_fingerprint(side_document)
                        != loaded.fingerprint):
                    loaded.diagnostic(
                        "AIKO503",
                        "explicit definition does not match the "
                        "fingerprint embedded in the trace; "
                        "recommendations are joined against the "
                        "EXPLICIT definition")
            loaded.definition_document = side_document
            loaded.definition = parsed
        except DefinitionError as error:
            loaded.diagnostic("AIKO503",
                              f"side-channel definition unusable: "
                              f"{error}")
    elif loaded.definition_document is not None:
        try:
            loaded.definition = parse_pipeline_definition(
                loaded.definition_document, validate=False)
        except DefinitionError as error:
            loaded.diagnostic(
                "AIKO503",
                f"embedded definition does not parse: {error}")

    _ingest_events(loaded, document["traceEvents"],
                   allowed_pids=allowed_pids)
    _join(loaded)
    return loaded


def _ingest_events(loaded: LoadedTrace, events: list,
                   allowed_pids: set | None = None) -> None:
    first_us = None
    last_us = None
    profiles = loaded.elements
    # merged fleet artifacts carry one frame span PER PROCESS for the
    # same logical frame (gateway root + each replica's slice, all
    # sharing one trace id): keep the LONGEST span per trace id -- the
    # root's end-to-end duration -- so frame stats are not inflated
    frames_by_trace: dict = {}
    for event in events:
        if not isinstance(event, dict):
            continue
        if allowed_pids is not None \
                and event.get("pid") not in allowed_pids:
            continue
        kind = event.get("ph")
        category = event.get("cat", "")
        name = str(event.get("name", ""))
        ts = event.get("ts")
        dur = event.get("dur", 0.0)
        if kind in ("X", "i") and isinstance(ts, (int, float)):
            first_us = ts if first_us is None else min(first_us, ts)
            end = ts + (dur if isinstance(dur, (int, float)) else 0.0)
            last_us = end if last_us is None else max(last_us, end)
        if kind == "X" and category == "frame":
            args = event.get("args") or {}
            status = str(args.get("status", "ok"))
            trace_id = args.get("trace_id")
            duration_s = float(dur) / 1e6
            if trace_id:
                known = frames_by_trace.get(trace_id)
                if known is None or duration_s > known[0]:
                    frames_by_trace[trace_id] = (duration_s, status)
            else:
                loaded.frame_durations_s.append(duration_s)
                loaded.frame_statuses[status] = (
                    loaded.frame_statuses.get(status, 0) + 1)
            continue
        node = _node_of(name)
        if not node:
            continue
        if kind == "X" and category == "element":
            profile = profiles.setdefault(node, ElementProfile(node))
            profile.compute_s.append(float(dur) / 1e6)
            args = event.get("args") or {}
            path = str(args.get("path", "inline"))
            profile.paths[path] = profile.paths.get(path, 0) + 1
            group = args.get("group")
            if isinstance(group, (int, float)):
                profile.groups.append(int(group))
        elif kind == "X" and category == "queue":
            profile = profiles.setdefault(node, ElementProfile(node))
            wait = float(dur) / 1e6
            if name.startswith("queue:") and "[" in name:
                profile.engine_queue_s.append(wait)
            else:
                profile.queue_s.append(wait)
        elif kind == "X" and category == "engine":
            profile = profiles.setdefault(node, ElementProfile(node))
            span = float(dur) / 1e6
            if name.startswith("prefill:"):
                profile.engine_prefill_s.append(span)
                args = event.get("args") or {}
                shared = args.get("prefix_blocks")
                if isinstance(shared, (int, float)):
                    # the span carries prefix_blocks ONLY when the
                    # replica ran a prefix cache: its presence marks a
                    # judged request, its value the blocks borrowed
                    profile.engine_prefix_requests += 1
                    if int(shared) > 0:
                        profile.engine_prefix_hits += 1
                        profile.engine_prefix_blocks += int(shared)
            elif name.startswith("adopt:"):
                # disaggregated serving: the decode replica's KV
                # migration (batched transfer-plane fetch + pool
                # scatter) -- classified apart from slot-queue waits
                profile.engine_adopt_s.append(span)
            elif name.startswith("checkpoint:"):
                # warm KV failover: time the engine pump spent
                # building/offering decode-state snapshots -- a
                # cadence set too hot floors the engine here
                profile.engine_checkpoint_s.append(span)
            elif name.startswith("decode_steps:"):
                profile.engine_decode_s.append(span)
                args = event.get("args") or {}
                preempted = args.get("preemptions")
                if isinstance(preempted, (int, float)):
                    profile.engine_preemptions += int(preempted)
                tokens = args.get("tokens")
                if isinstance(tokens, (int, float)):
                    profile.engine_tokens += int(tokens)
            # engine-managed frames report their slot wait under a
            # row-suffixed queue span; an un-suffixed single-row one
            # lands in queue_s above, which is the same quantity
        elif category == "gateway":
            # serving-tier spans: "admit:gateway" / "route:gateway" /
            # "replay:gateway" X spans plus shed/throttle instants --
            # all attributed to the "gateway" pseudo-node (there is no
            # matching definition element; _join skips it)
            profile = profiles.setdefault(node, ElementProfile(node))
            span = float(dur) / 1e6 if isinstance(
                dur, (int, float)) else 0.0
            if kind == "X" and name.startswith("admit:"):
                profile.gateway_admit_s.append(span)
            elif kind == "X" and name.startswith("route:"):
                profile.gateway_route_s.append(span)
            elif kind == "X" and ("replay:" in name):
                profile.gateway_replay_s.append(span)
            elif kind == "i" and name.startswith("shed:"):
                profile.gateway_sheds += 1
            elif kind == "i" and name.startswith("throttle:"):
                # rate 0 is the LIFT instant (backpressure cleared):
                # only count the onset, mirroring gateway.throttled
                # vs gateway.unthrottled
                rate = args.get("rate") if isinstance(args, dict) \
                    else None
                if not isinstance(rate, (int, float)) or rate > 0:
                    profile.gateway_throttles += 1
        elif kind == "i" and category == "compile":
            if name.startswith("compile:"):
                profile = profiles.setdefault(node,
                                              ElementProfile(node))
                profile.compiles += 1
    for duration_s, status in frames_by_trace.values():
        loaded.frame_durations_s.append(duration_s)
        loaded.frame_statuses[status] = (
            loaded.frame_statuses.get(status, 0) + 1)
    if first_us is not None and last_us is not None:
        loaded.wall_s = max((last_us - first_us) / 1e6, 0.0)


def _join(loaded: LoadedTrace) -> None:
    """Attribute every profiled node to a typed graph element; surface
    both directions of mismatch."""
    if loaded.definition is None:
        if loaded.elements and loaded.definition_document is None:
            loaded.diagnostic(
                "AIKO503",
                f"{len(loaded.elements)} profiled node(s) cannot be "
                f"joined: no definition available")
        return
    declared = {element.name for element
                in loaded.definition.elements}
    for name in sorted(loaded.elements):
        if name not in declared:
            if loaded.elements[name].is_gateway:
                # the serving tier is not a graph element by design:
                # its spans classify the admission-bound floor
                continue
            loaded.diagnostic(
                "AIKO503",
                f"trace span node {name!r} is not an element of "
                f"definition {loaded.definition.name!r}")
    for name in sorted(declared):
        if name not in loaded.elements:
            # declared but never observed: keep an empty profile so
            # the classifier reports it as unobserved instead of
            # omitting it from the report
            loaded.elements[name] = ElementProfile(name)
            loaded.diagnostic(
                "AIKO503",
                f"element {name!r} produced no spans in this trace")
