// Native S-expression parser: C++ implementation of the control-plane
// codec (exact semantics of aiko_services_tpu/utils/sexpr.py::parse).
//
// The reference framework is pure Python (SURVEY.md section 2: "zero
// C++/Rust/CUDA components"); this framework gives the hottest non-JAX
// path -- every inbound control message is parsed -- a native fast path.
// The Python wrapper (native/__init__.py) loads this extension when built
// and falls back to the pure-Python tokenizer otherwise; both must stay
// behaviorally identical (tests/test_native.py runs the shared corpus
// against both).
//
// Contract with the wrapper: parse_bytes(bytes) -> (command, parameters).
// Text is latin-1 (byte-per-char), so canonical "len:data" symbols are
// binary-safe.  ParseError is injected via set_parse_error() so native
// and Python paths raise the same exception type.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <string>
#include <vector>

namespace {

PyObject *parse_error = nullptr;  // utils.sexpr.ParseError

struct Tokenizer {
    const char *text;
    Py_ssize_t pos;
    Py_ssize_t length;
};

bool is_space(char ch) {
    return ch == ' ' || ch == '\t' || ch == '\r' || ch == '\n';
}

bool is_delim(char ch) {
    return is_space(ch) || ch == '(' || ch == ')' || ch == '"';
}

void skip_whitespace(Tokenizer &tok) {
    while (tok.pos < tok.length && is_space(tok.text[tok.pos])) {
        tok.pos++;
    }
}

PyObject *raise_parse_error(const char *message, Py_ssize_t offset) {
    PyErr_Format(parse_error ? parse_error : PyExc_ValueError,
                 "%s at offset %zd", message, offset);
    return nullptr;
}

PyObject *latin1(const char *data, Py_ssize_t size) {
    return PyUnicode_DecodeLatin1(data, size, nullptr);
}

// positioned on the opening quote; returns str
PyObject *read_quoted(Tokenizer &tok) {
    Py_ssize_t pos = tok.pos + 1;
    std::string out;
    while (pos < tok.length) {
        char ch = tok.text[pos];
        if (ch == '\\' && pos + 1 < tok.length) {
            out.push_back(tok.text[pos + 1]);
            pos += 2;
            continue;
        }
        if (ch == '"') {
            tok.pos = pos + 1;
            return latin1(out.data(), (Py_ssize_t)out.size());
        }
        out.push_back(ch);
        pos++;
    }
    return raise_parse_error("Unterminated quoted string", tok.pos);
}

// returns str (atom or canonical "len:data" payload)
PyObject *read_atom(Tokenizer &tok) {
    const char *text = tok.text;
    Py_ssize_t pos = tok.pos;
    Py_ssize_t start = pos;
    while (pos < tok.length && !is_delim(text[pos])) {
        char ch = text[pos];
        pos++;
        if (ch == ':' && pos > start + 1) {
            // candidate canonical symbol: digits before the colon
            bool all_digits = true;
            for (Py_ssize_t i = start; i < pos - 1; i++) {
                if (text[i] < '0' || text[i] > '9') {
                    all_digits = false;
                    break;
                }
            }
            if (all_digits) {
                long long size = 0;
                for (Py_ssize_t i = start; i < pos - 1; i++) {
                    size = size * 10 + (text[i] - '0');
                    if (size > tok.length) break;  // overflow guard
                }
                Py_ssize_t end = pos + (Py_ssize_t)size;
                if (end > tok.length) {
                    return raise_parse_error(
                        "Canonical symbol overruns payload", start);
                }
                tok.pos = end;
                return latin1(text + pos, end - pos);
            }
        }
    }
    tok.pos = pos;
    return latin1(text + start, pos - start);
}

bool is_keyword_key(PyObject *item) {
    if (!PyUnicode_Check(item)) return false;
    Py_ssize_t size = PyUnicode_GET_LENGTH(item);
    if (size < 2) return false;
    return PyUnicode_READ_CHAR(item, size - 1) == ':';
}

PyObject *parse_expression(Tokenizer &tok);

// positioned past '('; returns list or keyword dict
PyObject *parse_list(Tokenizer &tok) {
    PyObject *items = PyList_New(0);
    if (!items) return nullptr;
    for (;;) {
        skip_whitespace(tok);
        if (tok.pos >= tok.length) {
            Py_DECREF(items);
            return raise_parse_error("Unterminated list", tok.pos);
        }
        if (tok.text[tok.pos] == ')') {
            tok.pos++;
            break;
        }
        PyObject *item = parse_expression(tok);
        if (!item) {
            Py_DECREF(items);
            return nullptr;
        }
        int failed = PyList_Append(items, item);
        Py_DECREF(item);
        if (failed) {
            Py_DECREF(items);
            return nullptr;
        }
    }
    // alternating "name:" keys fold into a dict (even, non-empty lists)
    Py_ssize_t count = PyList_GET_SIZE(items);
    if (count > 0 && count % 2 == 0) {
        bool keyword_mode = true;
        for (Py_ssize_t i = 0; i < count; i += 2) {
            if (!is_keyword_key(PyList_GET_ITEM(items, i))) {
                keyword_mode = false;
                break;
            }
        }
        if (keyword_mode) {
            PyObject *dict = PyDict_New();
            if (!dict) {
                Py_DECREF(items);
                return nullptr;
            }
            for (Py_ssize_t i = 0; i < count; i += 2) {
                PyObject *key_full = PyList_GET_ITEM(items, i);
                PyObject *key = PyUnicode_Substring(
                    key_full, 0, PyUnicode_GET_LENGTH(key_full) - 1);
                if (!key || PyDict_SetItem(
                        dict, key, PyList_GET_ITEM(items, i + 1))) {
                    Py_XDECREF(key);
                    Py_DECREF(dict);
                    Py_DECREF(items);
                    return nullptr;
                }
                Py_DECREF(key);
            }
            Py_DECREF(items);
            return dict;
        }
    }
    return items;
}

PyObject *parse_expression(Tokenizer &tok) {
    skip_whitespace(tok);
    if (tok.pos >= tok.length) {
        return raise_parse_error("Unexpected end of payload", tok.pos);
    }
    char ch = tok.text[tok.pos];
    if (ch == '(') {
        tok.pos++;
        return parse_list(tok);
    }
    if (ch == '"') {
        return read_quoted(tok);
    }
    return read_atom(tok);
}

// parse_bytes(payload: bytes) -> (command, parameters)
PyObject *py_parse_bytes(PyObject *, PyObject *arg) {
    char *data;
    Py_ssize_t length;
    if (PyBytes_AsStringAndSize(arg, &data, &length) < 0) {
        return nullptr;
    }
    Tokenizer tok{data, 0, length};
    skip_whitespace(tok);
    if (tok.pos >= tok.length) {
        return Py_BuildValue("(s[])", "");
    }
    PyObject *expression = parse_expression(tok);
    if (!expression) return nullptr;
    skip_whitespace(tok);
    if (tok.pos < tok.length) {
        Py_DECREF(expression);
        return raise_parse_error("Trailing data", tok.pos);
    }
    if (PyUnicode_Check(expression)) {
        PyObject *result = Py_BuildValue("(N[])", expression);
        return result;
    }
    if (PyDict_Check(expression)) {
        return Py_BuildValue("(s[N])", "", expression);
    }
    Py_ssize_t count = PyList_GET_SIZE(expression);
    if (count == 0) {
        Py_DECREF(expression);
        return Py_BuildValue("(s[])", "");
    }
    PyObject *head = PyList_GET_ITEM(expression, 0);
    if (!PyUnicode_Check(head)) {
        return Py_BuildValue("(sN)", "", expression);
    }
    PyObject *tail = PyList_GetSlice(expression, 1, count);
    if (!tail) {
        Py_DECREF(expression);
        return nullptr;
    }
    Py_INCREF(head);
    Py_DECREF(expression);
    return Py_BuildValue("(NN)", head, tail);
}

PyObject *py_set_parse_error(PyObject *, PyObject *arg) {
    Py_XDECREF(parse_error);
    Py_INCREF(arg);
    parse_error = arg;
    Py_RETURN_NONE;
}

PyMethodDef methods[] = {
    {"parse_bytes", py_parse_bytes, METH_O,
     "parse_bytes(payload: bytes) -> (command, parameters)"},
    {"set_parse_error", py_set_parse_error, METH_O,
     "Install the exception class raised on malformed payloads"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef module_def = {
    PyModuleDef_HEAD_INIT, "_sexpr_native",
    "Native S-expression parser (C++)", -1, methods,
    nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit__sexpr_native(void) {
    return PyModule_Create(&module_def);
}
