# Build the native extensions in place: python -m
# aiko_services_tpu.native.build
#
# Direct g++ invocation against the running interpreter's headers (no
# pybind11/setuptools needed -- the extension uses the raw CPython API).
# Produces _sexpr_native.<abi>.so next to this file; native/__init__.py
# picks it up on the next import and the sexpr codec switches to the
# native fast path automatically.

from __future__ import annotations

import subprocess
import sys
import sysconfig
from pathlib import Path

HERE = Path(__file__).resolve().parent


def build(verbose: bool = True) -> Path | None:
    source = HERE / "sexpr_codec.cpp"
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    target = HERE / f"_sexpr_native{suffix}"
    if (target.exists()
            and target.stat().st_mtime >= source.stat().st_mtime):
        return target
    include = sysconfig.get_paths()["include"]
    command = [
        "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
        f"-I{include}", str(source), "-o", str(target),
    ]
    result = subprocess.run(command, capture_output=True, text=True)
    if result.returncode != 0:
        if verbose:
            print(f"native build failed:\n{result.stderr}",
                  file=sys.stderr)
        return None
    if verbose:
        print(f"built {target.name}")
    return target


if __name__ == "__main__":
    sys.exit(0 if build() else 1)
