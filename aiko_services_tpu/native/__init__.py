# Native extension loader: exposes sexpr_parse_native (or None when the
# extension is not built).  Build with:
#   python -m aiko_services_tpu.native.build

from __future__ import annotations

sexpr_parse_native = None

try:
    from . import _sexpr_native as _ext
except ImportError:
    _ext = None

if _ext is not None:
    def sexpr_parse_native(payload):
        if isinstance(payload, str):
            payload = payload.encode("latin-1")
        return _ext.parse_bytes(payload)

    def install_parse_error(exception_class) -> None:
        _ext.set_parse_error(exception_class)
else:  # pragma: no cover
    def install_parse_error(exception_class) -> None:
        pass
