# Transport abstraction for the control plane.
#
# Capability parity with the reference Message ABC (reference:
# src/aiko_services/main/message/message.py:11-46): publish / subscribe /
# unsubscribe / last-will-and-testament over hierarchical topics with MQTT
# wildcard semantics ('+' single level, '#' multi-level tail).  The data
# plane never rides this interface -- tensors stay on device -- so payloads
# are small strings/bytes.

from __future__ import annotations

from abc import ABC, abstractmethod

__all__ = ["Transport", "topic_matches"]


def topic_matches(pattern: str, topic: str) -> bool:
    """MQTT-style topic filter match (reference process.py:334-350)."""
    if pattern == topic:
        return True
    pattern_parts = pattern.split("/")
    topic_parts = topic.split("/")
    for index, part in enumerate(pattern_parts):
        if part == "#":
            return True
        if index >= len(topic_parts):
            return False
        if part == "+":
            continue
        if part != topic_parts[index]:
            return False
    return len(pattern_parts) == len(topic_parts)


class Transport(ABC):
    """Connection to a pub/sub broker.

    on_message(topic: str, payload: str) is invoked on the transport's
    dispatch thread; implementations must never run user code inline with
    publish().  The runtime re-queues every delivery onto the event loop.
    """

    def __init__(self, on_message=None):
        self.on_message = on_message

    @abstractmethod
    def connect(self) -> None: ...

    @abstractmethod
    def disconnect(self, send_lwt: bool = False) -> None: ...

    @abstractmethod
    def publish(self, topic: str, payload, retain: bool = False) -> None: ...

    @abstractmethod
    def subscribe(self, topic: str) -> None: ...

    @abstractmethod
    def unsubscribe(self, topic: str) -> None: ...

    @abstractmethod
    def set_last_will_and_testament(
        self, topic: str, payload, retain: bool = False) -> None: ...

    def clear_last_will_and_testament(self, topic: str) -> None:
        """Remove a previously-set will.  Transports with a single will per
        connection (MQTT) clear it entirely; the loopback broker supports
        one will per topic."""


    @property
    @abstractmethod
    def connected(self) -> bool: ...
