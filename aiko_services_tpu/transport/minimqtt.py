# Self-contained MQTT 3.1.1 over stdlib sockets: a paho-compatible
# client plus a tiny embedded broker.
#
# Why: the reference deploys over a real MQTT broker (reference:
# src/aiko_services/main/message/mqtt.py:65-289 -- paho client, LWT set
# before CONNECT, retained service announcements, wildcard
# subscriptions), but neither paho-mqtt nor mosquitto exist in this
# image, so until round 4 the MQTT transport had only ever executed
# against an in-repo fake.  This module closes that gap with the wire
# protocol itself: CONNECT (with will), CONNACK, PUBLISH (QoS 0, QoS 1
# acknowledged), SUBSCRIBE/SUBACK (+ retained replay), UNSUBSCRIBE,
# PINGREQ/PINGRESP, DISCONNECT, and broker-side will delivery on
# abnormal socket loss.
#
# The `Client` class exposes the paho v2 callback surface MqttTransport
# already speaks (transport/mqtt.py), so the SAME transport code runs
# over real TCP by assigning `transport.mqtt._paho = minimqtt`.

from __future__ import annotations

import os as _os
import socket
import struct
import threading
import time as _time

from ..observe.metrics import get_registry
from ..utils import get_logger
from .base import topic_matches
from .trie import TopicTrie

__all__ = ["CallbackAPIVersion", "Client", "MiniMqttBroker"]

_LOGGER = get_logger("minimqtt")

CONNECT, CONNACK, PUBLISH, PUBACK = 1, 2, 3, 4
SUBSCRIBE, SUBACK, UNSUBSCRIBE, UNSUBACK = 8, 9, 10, 11
PINGREQ, PINGRESP, DISCONNECT = 12, 13, 14


class CallbackAPIVersion:  # paho-compatible constant
    VERSION2 = 2


# -- wire encoding -----------------------------------------------------------

def _encode_varint(value: int) -> bytes:
    out = bytearray()
    while True:
        digit = value % 128
        value //= 128
        out.append(digit | (0x80 if value else 0))
        if not value:
            return bytes(out)


def _encode_string(text) -> bytes:
    data = text.encode("utf-8") if isinstance(text, str) else bytes(text)
    return struct.pack(">H", len(data)) + data


def _packet(packet_type: int, flags: int, body: bytes) -> bytes:
    return (bytes([(packet_type << 4) | flags])
            + _encode_varint(len(body)) + body)


def _read_exact(sock, count: int) -> bytes | None:
    data = b""
    while len(data) < count:
        chunk = sock.recv(count - len(data))
        if not chunk:
            return None
        data += chunk
    return data


def _read_packet(sock):
    """(type, flags, body) or None on EOF."""
    first = _read_exact(sock, 1)
    if first is None:
        return None
    length, shift = 0, 0
    while True:
        byte = _read_exact(sock, 1)
        if byte is None:
            return None
        length |= (byte[0] & 0x7F) << shift
        if not byte[0] & 0x80:
            break
        shift += 7
    body = _read_exact(sock, length) if length else b""
    if body is None:
        return None
    return first[0] >> 4, first[0] & 0x0F, body


class _Reader:
    """Cursor over a packet body."""

    def __init__(self, body: bytes):
        self.body = body
        self.at = 0

    def u16(self) -> int:
        value = struct.unpack_from(">H", self.body, self.at)[0]
        self.at += 2
        return value

    def chunk(self, count: int) -> bytes:
        data = self.body[self.at:self.at + count]
        self.at += count
        return data

    def string(self) -> bytes:
        return self.chunk(self.u16())

    @property
    def rest(self) -> bytes:
        return self.body[self.at:]


# -- embedded broker ---------------------------------------------------------

class _Session:
    def __init__(self, sock, address):
        self.sock = sock
        self.address = address
        self.client_id = ""
        self.filters: list[str] = []
        self.will = None            # (topic, payload bytes, retain)
        self.clean_close = False
        self.will_sent = False
        self.write_lock = threading.Lock()

    def send(self, data: bytes) -> bool:
        try:
            with self.write_lock:
                self.sock.sendall(data)
            return True
        except OSError:
            return False


class MiniMqttBroker:
    """Minimal in-process MQTT 3.1.1 broker: one thread per client,
    retained store, wildcard routing, will delivery on abnormal loss.
    Not a production broker -- it exists so the transport stack can be
    exercised over REAL sockets in images without mosquitto."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._server = socket.create_server((host, port))
        self.host = host
        self.port = self._server.getsockname()[1]
        self.retained: dict[str, bytes] = {}
        self._sessions: list[_Session] = []
        self._lock = threading.Lock()
        # trie-indexed routing (transport/trie.py): one walk over the
        # topic's levels per publish instead of every session's whole
        # filter list; AIKO_BROKER_MATCH=linear keeps the historical
        # scan as the A/B reference arm (same instruments either way)
        self._trie = TopicTrie()
        self.match_mode = _os.environ.get("AIKO_BROKER_MATCH", "trie")
        registry = get_registry()
        self._m_messages = registry.counter("broker.messages")
        self._m_delivered = registry.counter("broker.fanout_delivered")
        self._m_avoided = registry.counter("broker.fanout_avoided")
        self._m_match = registry.histogram("broker.match_s")
        self._running = True
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="minimqtt-broker", daemon=True)
        self._accept_thread.start()

    def stop(self) -> None:
        self._running = False
        try:
            self._server.close()
        except OSError:
            pass
        with self._lock:
            sessions = list(self._sessions)
        for session in sessions:
            try:
                session.sock.close()
            except OSError:
                pass

    def drop_client(self, client_id: str) -> None:
        """Abort a client's socket WITHOUT a DISCONNECT (test hook for
        abnormal loss); the will publishes synchronously before
        returning."""
        with self._lock:
            session = next((s for s in self._sessions
                            if s.client_id == client_id), None)
        if session is None:
            return
        self._publish_will(session)
        try:
            session.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass

    # -- internals --

    def _accept_loop(self) -> None:
        while self._running:
            try:
                sock, address = self._server.accept()
            except OSError:
                return
            session = _Session(sock, address)
            with self._lock:
                self._sessions.append(session)
            threading.Thread(target=self._serve, args=(session,),
                             name="minimqtt-session", daemon=True).start()

    def _serve(self, session: _Session) -> None:
        try:
            while True:
                packet = _read_packet(session.sock)
                if packet is None:
                    break
                if not self._handle(session, *packet):
                    break
        except OSError:
            pass
        finally:
            if not session.clean_close:
                self._publish_will(session)
            with self._lock:
                if session in self._sessions:
                    self._sessions.remove(session)
                self._trie.remove_value(session)
            try:
                session.sock.close()
            except OSError:
                pass

    def _handle(self, session: _Session, packet_type: int, flags: int,
                body: bytes) -> bool:
        reader = _Reader(body)
        if packet_type == CONNECT:
            reader.string()                      # protocol name
            reader.chunk(1)                      # protocol level
            connect_flags = reader.chunk(1)[0]
            reader.u16()                         # keepalive
            session.client_id = reader.string().decode("utf-8", "replace")
            if connect_flags & 0x04:             # will flag
                will_topic = reader.string().decode("utf-8", "replace")
                will_payload = reader.string()
                session.will = (will_topic, will_payload,
                                bool(connect_flags & 0x20))
            if connect_flags & 0x80:
                reader.string()                  # username
            if connect_flags & 0x40:
                reader.string()                  # password
            session.send(_packet(CONNACK, 0, b"\x00\x00"))
        elif packet_type == PUBLISH:
            qos = (flags >> 1) & 0x03
            retain = bool(flags & 0x01)
            topic = reader.string().decode("utf-8", "replace")
            if qos:
                packet_id = reader.u16()
                session.send(_packet(PUBACK, 0,
                                     struct.pack(">H", packet_id)))
            payload = reader.rest
            self._route(topic, payload, retain)
        elif packet_type == SUBSCRIBE:
            packet_id = reader.u16()
            granted = bytearray()
            new_filters = []
            while reader.at < len(body):
                topic_filter = reader.string().decode("utf-8", "replace")
                reader.chunk(1)                  # requested qos
                if topic_filter not in session.filters:
                    session.filters.append(topic_filter)
                    with self._lock:
                        self._trie.add(topic_filter, session)
                new_filters.append(topic_filter)
                granted.append(0x00)
            session.send(_packet(SUBACK, 0,
                                 struct.pack(">H", packet_id) + granted))
            # retained replay AFTER SUBACK (3.1.1 normative behavior)
            for topic, payload in list(self.retained.items()):
                if any(topic_matches(f, topic) for f in new_filters):
                    session.send(self._publish_packet(topic, payload,
                                                      retain=True))
        elif packet_type == UNSUBSCRIBE:
            packet_id = reader.u16()
            while reader.at < len(body):
                topic_filter = reader.string().decode("utf-8", "replace")
                if topic_filter in session.filters:
                    session.filters.remove(topic_filter)
                    with self._lock:
                        self._trie.discard(topic_filter, session)
            session.send(_packet(UNSUBACK, 0,
                                 struct.pack(">H", packet_id)))
        elif packet_type == PINGREQ:
            session.send(_packet(PINGRESP, 0, b""))
        elif packet_type == DISCONNECT:
            session.clean_close = True           # will discarded
            return False
        return True

    @staticmethod
    def _publish_packet(topic: str, payload: bytes,
                        retain: bool = False) -> bytes:
        return _packet(PUBLISH, 0x01 if retain else 0x00,
                       _encode_string(topic) + payload)

    def _route(self, topic: str, payload: bytes, retain: bool) -> None:
        if retain:
            if payload:
                self.retained[topic] = payload
            else:
                self.retained.pop(topic, None)  # empty payload clears
        start = _time.perf_counter()
        if self.match_mode == "linear":
            with self._lock:
                sessions = list(self._sessions)
                total = len(sessions)
            matched = [session for session in sessions
                       if any(topic_matches(f, topic)
                              for f in session.filters)]
        else:
            with self._lock:
                matched = self._trie.match(topic)
                total = len(self._sessions)
            matched.sort(key=id)   # deterministic within one route
        self._m_match.record(_time.perf_counter() - start)
        self._m_messages.inc()
        self._m_delivered.inc(len(matched))
        self._m_avoided.inc(total - len(matched))
        packet = self._publish_packet(topic, payload)
        for session in matched:
            session.send(packet)

    def _publish_will(self, session: _Session) -> None:
        if session.will is None or session.will_sent:
            return
        session.will_sent = True
        topic, payload, retain = session.will
        self._route(topic, payload, retain)


# -- paho-compatible client --------------------------------------------------

class _Message:
    __slots__ = ("topic", "payload")

    def __init__(self, topic: str, payload: bytes):
        self.topic = topic
        self.payload = payload


class Client:
    """paho-v2-compatible subset speaking real MQTT 3.1.1 over a
    socket: exactly the surface transport/mqtt.py uses, plus flush()
    (a PINGREQ round-trip -- everything written before it has been
    processed by the broker, and every self-delivery it triggered has
    been dispatched, because the reader handles those PUBLISHes before
    the PINGRESP on the same TCP stream)."""

    _counter = 0
    _counter_lock = threading.Lock()

    def __init__(self, callback_api_version=CallbackAPIVersion.VERSION2):
        with Client._counter_lock:
            Client._counter += 1
            self._client_id = f"minimqtt-{Client._counter}"
        self.on_connect = None
        self.on_disconnect = None
        self.on_message = None
        self._username = None
        self._password = None
        self._will = None
        self._sock = None
        self._thread = None
        self._connected = threading.Event()
        # ping accounting: flush() must wait for ITS OWN PINGREQ's
        # response, not any PINGRESP (a keepalive ping answered just
        # after flush starts must not satisfy the barrier), so pings
        # are counted and flush waits for acked >= the count it
        # observed at send time; PINGRESPs arrive in request order
        self._ping_cond = threading.Condition()
        self._ping_sent = 0
        self._ping_acked = 0
        self._ping_gen = 0  # bumped per connection loss: aborts waiters
        self._packet_id = 0
        self._write_lock = threading.Lock()
        self._host = None
        self._port = None
        self._keepalive = 60
        self._closing = False
        # offline publish queue: during a broker outage publishes park
        # here and replay on reconnect (after resubscription), instead
        # of silently vanishing with rc=4.  BOUNDED -- a long outage
        # under steady publish load must not grow memory without limit
        # -- drop-OLDEST (the stalest state update is the least
        # valuable), every drop counted on `mqtt.offline_dropped` so
        # queued == replayed + dropped + len(pending) reconciles
        import os as _os
        try:
            self._offline_max = int(
                _os.environ.get("AIKO_MQTT_OFFLINE_MAX", 256))
        except ValueError:
            self._offline_max = 256
        self._offline: list = []      # (topic, payload bytes, retain)
        self._offline_lock = threading.Lock()

    # paho surface ----------------------------------------------------------

    def username_pw_set(self, username, password=None) -> None:
        self._username = username
        self._password = password

    def tls_set(self) -> None:
        raise NotImplementedError(
            "minimqtt has no TLS; install paho-mqtt for TLS brokers")

    def will_set(self, topic, payload=None, retain=False) -> None:
        data = (payload.encode("utf-8") if isinstance(payload, str)
                else bytes(payload or b""))
        self._will = (topic, data, retain)

    def connect_async(self, host, port, keepalive=60) -> None:
        self._host, self._port = host, int(port)
        self._keepalive = max(int(keepalive), 5)

    def loop_start(self) -> None:
        if self._thread is not None:
            return
        self._closing = False
        self._thread = threading.Thread(
            target=self._network_loop, name="minimqtt-client", daemon=True)
        self._thread.start()

    def loop_stop(self) -> None:
        self._closing = True
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        thread, self._thread = self._thread, None
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=2.0)

    def disconnect(self) -> None:
        self._closing = True   # a deliberate disconnect stops reconnects
        sock = self._sock
        if sock is not None and self._connected.is_set():
            try:
                with self._write_lock:
                    sock.sendall(_packet(DISCONNECT, 0, b""))
            except OSError:
                pass
        self._connected.clear()
        if self.on_disconnect is not None:
            self.on_disconnect(self, None, None, 0, None)

    def publish(self, topic, payload=None, retain=False) -> int:
        data = (payload.encode("utf-8") if isinstance(payload, str)
                else bytes(payload or b""))
        if not self._connected.is_set() and not self._closing:
            self._offline_enqueue(topic, data, retain)
            return 0
        flags = 0x01 if retain else 0x00
        packet = _packet(PUBLISH, flags, _encode_string(topic) + data)
        result = self._send(packet)
        if result == 0:
            metrics = get_registry()
            metrics.counter("mqtt.publish_count").inc()
            metrics.counter("mqtt.publish_bytes").inc(len(packet))
        elif not self._closing:
            # the socket died under us (outage starting): park it with
            # the offline queue rather than dropping one message on the
            # disconnect boundary
            self._offline_enqueue(topic, data, retain)
            result = 0
        return result

    def _offline_enqueue(self, topic, data: bytes, retain: bool) -> None:
        if self._offline_max <= 0:
            get_registry().counter("mqtt.offline_dropped").inc()
            return
        with self._offline_lock:
            self._offline.append((topic, data, retain))
            dropped = len(self._offline) - self._offline_max
            if dropped > 0:
                del self._offline[:dropped]
            else:
                dropped = 0
        metrics = get_registry()
        metrics.counter("mqtt.offline_queued").inc()
        if dropped:
            metrics.counter("mqtt.offline_dropped").inc(dropped)

    def _offline_flush(self) -> None:
        """Replay parked publishes after a reconnect -- called AFTER
        on_connect so subscriptions are restored first and replayed
        state lands on a fully resubscribed session."""
        with self._offline_lock:
            pending, self._offline = self._offline, []
        if not pending:
            return
        replayed = 0
        for index, (topic, data, retain) in enumerate(pending):
            flags = 0x01 if retain else 0x00
            packet = _packet(PUBLISH, flags, _encode_string(topic) + data)
            if self._send(packet) == 0:
                replayed += 1
            else:
                # connection died again mid-flush: re-park the rest in
                # order (ahead of anything queued meanwhile)
                with self._offline_lock:
                    self._offline = pending[index:] + self._offline
                break
        if replayed:
            metrics = get_registry()
            metrics.counter("mqtt.offline_replayed").inc(replayed)
            metrics.counter("mqtt.publish_count").inc(replayed)

    def subscribe(self, topic) -> int:
        self._packet_id = (self._packet_id % 0xFFFF) + 1
        body = (struct.pack(">H", self._packet_id)
                + _encode_string(topic) + b"\x00")
        return self._send(_packet(SUBSCRIBE, 0x02, body))

    def unsubscribe(self, topic) -> int:
        self._packet_id = (self._packet_id % 0xFFFF) + 1
        body = struct.pack(">H", self._packet_id) + _encode_string(topic)
        return self._send(_packet(UNSUBSCRIBE, 0x02, body))

    # extras ----------------------------------------------------------------

    def flush(self, timeout: float = 5.0) -> bool:
        """PINGREQ round-trip: barrier over everything this client sent
        AND every delivery the broker wrote to this socket before the
        PINGRESP.  Waits for the response to THIS flush's own PINGREQ
        (counted, not an any-ping event): a PINGRESP answering an
        earlier keepalive ping cannot release the barrier early."""
        with self._ping_cond:
            generation = self._ping_gen
            self._ping_sent += 1
            target = self._ping_sent
        if self._send(_packet(PINGREQ, 0, b"")) != 0:
            with self._ping_cond:
                # roll the phantom count back: a ping that never hit
                # the wire gets no PINGRESP, and (unlike the keepalive
                # path, whose send failure is followed by the read
                # loop's connection-loss resync) nothing else would
                # ever clear the deficit -- every later flush() would
                # time out until the next disconnect
                if self._ping_gen == generation:
                    self._ping_sent -= 1
            return False
        deadline = _time.monotonic() + timeout
        with self._ping_cond:
            while (self._ping_acked < target
                   and self._ping_gen == generation):
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    return False
                self._ping_cond.wait(remaining)
            # a connection loss resyncs acked=sent, which would satisfy
            # the count -- the generation check keeps a voided barrier
            # from reporting success
            return self._ping_gen == generation

    # internals -------------------------------------------------------------

    def _send(self, data: bytes) -> int:
        """Write a packet; SOFT-fails like paho (returns a non-zero rc
        instead of raising) -- the runtime publishes state from paths
        that never expected transport exceptions, and the reconnect
        loop replays subscriptions once the broker returns."""
        sock = self._sock
        if sock is None:
            return 4                             # MQTT_ERR_NO_CONN
        try:
            with self._write_lock:
                sock.sendall(data)
            return 0
        except OSError as error:
            _LOGGER.debug("minimqtt send failed: %s", error)
            return 4

    def _connect_body(self) -> bytes:
        connect_flags = 0x02                     # clean session
        tail = _encode_string(self._client_id)
        if self._will is not None:
            topic, payload, retain = self._will
            connect_flags |= 0x04 | (0x20 if retain else 0)
            tail += _encode_string(topic)
            tail += struct.pack(">H", len(payload)) + payload
        if self._username is not None:
            connect_flags |= 0x80
            tail += _encode_string(self._username)
            if self._password is not None:
                connect_flags |= 0x40
                tail += _encode_string(self._password)
        # advertise the REAL keepalive: a hardcoded 60 here with a
        # client pinging at self._keepalive/2 lets a real broker's
        # 1.5x-keepalive idle cutoff (90 s) fire before the first ping
        # whenever keepalive > 90
        return (_encode_string("MQTT") + bytes([4, connect_flags])
                + struct.pack(">H", min(self._keepalive, 0xFFFF)) + tail)

    def _network_loop(self) -> None:
        """Connect / read / keepalive / reconnect, paho-style: recv
        timeouts at keepalive/2 drive PINGREQ so a real broker's
        1.5x-keepalive idle cutoff never fires on a healthy client, and
        a lost connection retries with backoff, replaying on_connect
        (which resubscribes) when the broker returns."""
        backoff = 0.5
        while not self._closing:
            try:
                sock = socket.create_connection(
                    (self._host, self._port), timeout=5.0)
                sock.settimeout(self._keepalive / 2.0)
                self._sock = sock
                with self._write_lock:
                    sock.sendall(_packet(CONNECT, 0, self._connect_body()))
                backoff = 0.5
                self._read_until_closed(sock)
            except OSError as error:
                if not self._closing:
                    _LOGGER.debug("minimqtt connect failed: %s", error)
            was_connected = self._connected.is_set()
            if was_connected and not self._closing:
                # abnormal loss about to retry: the reconnect rate is
                # the first thing to look at on a flapping deployment
                get_registry().counter("mqtt.reconnects").inc()
            self._connected.clear()
            with self._ping_cond:
                # outstanding pings died with the socket: resync the
                # counters and wake flush() waiters so they fail fast
                # instead of timing out on a response that cannot come
                self._ping_gen += 1
                self._ping_acked = self._ping_sent
                self._ping_cond.notify_all()
            if self._closing:
                return
            if was_connected and self.on_disconnect is not None:
                self.on_disconnect(self, None, None, 1, None)
            _time.sleep(backoff)
            backoff = min(backoff * 2, 8.0)

    def _send_keepalive_ping(self) -> None:
        """Counted keepalive PINGREQ, with the same rollback as flush():
        a ping that never hit the wire gets no PINGRESP, so the count
        must not stand.  The read loop's connection-loss resync USUALLY
        covers a failed send, but a transient failure on a socket that
        then recovers would otherwise leave flush() waiters one
        PINGRESP short forever."""
        with self._ping_cond:
            self._ping_sent += 1
            generation = self._ping_gen
        if self._send(_packet(PINGREQ, 0, b"")) != 0:
            with self._ping_cond:
                if self._ping_gen == generation:
                    self._ping_sent -= 1

    def _read_until_closed(self, sock) -> None:
        while not self._closing:
            try:
                packet = _read_packet(sock)
            except socket.timeout:
                self._send_keepalive_ping()
                continue
            if packet is None:
                return
            packet_type, _flags_unused, body = packet
            if packet_type == CONNACK:
                # replay the parked backlog BEFORE opening the direct
                # publish path: a fresh publish racing the flush could
                # otherwise hit the wire first and have a STALE parked
                # retained value replayed over it.  Publishers during
                # the first drain still park (not yet connected); the
                # second drain picks those up after the gate opens
                self._offline_flush()
                self._connected.set()
                self._offline_flush()
                get_registry().counter("mqtt.connects").inc()
                if self.on_connect is not None:
                    self.on_connect(self, None, None, 0, None)
            elif packet_type == PUBLISH:
                metrics = get_registry()
                metrics.counter("mqtt.receive_count").inc()
                metrics.counter("mqtt.receive_bytes").inc(len(body))
                reader = _Reader(body)
                topic = reader.string().decode("utf-8", "replace")
                if self.on_message is not None:
                    self.on_message(self, None,
                                    _Message(topic, reader.rest))
            elif packet_type == PINGRESP:
                with self._ping_cond:
                    self._ping_acked += 1
                    self._ping_cond.notify_all()
            # PUBACK/SUBACK/UNSUBACK: fire-and-forget acks
