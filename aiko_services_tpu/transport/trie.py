# Topic trie: MQTT-wildcard-aware subscription index.
#
# Every broker publish used to fan out to EVERY client, and every
# client linearly scanned its whole subscription set per message
# (O(clients x patterns) matching per publish) -- the measured
# control-plane ceiling once stream counts reach the thousands.  This
# trie replaces the scan with one walk over the topic's levels:
# matching costs O(levels x branching) regardless of how many
# patterns are registered, and it is shared by the loopback broker
# (route each publish only to subscribed clients), the minimqtt
# broker's session routing, and the process message-handler table.
#
# Semantics contract: for every registered pattern,
# `value in trie.match(topic)` iff `topic_matches(pattern, topic)`
# (transport/base.py) -- including the edge cases the linear matcher
# defines: '#' matches the remainder INCLUDING zero levels ("a/#"
# matches "a"), '#' anywhere in a pattern terminates it ("a/#/b"
# behaves as "a/#"), '+' matches exactly one level including an empty
# one ("a/+" matches "a/"), and leading '/' introduces an empty first
# level.  tests/test_scaleout.py proves the equivalence over a
# generated corpus, bit for bit.
#
# Not thread-safe: callers (broker, process) hold their own lock
# around mutation and match -- matching never yields, so the critical
# section is a few dict lookups per topic level.

from __future__ import annotations

__all__ = ["TopicTrie"]


class _Node:
    __slots__ = ("children", "plus", "values", "hash_values")

    def __init__(self):
        self.children: dict[str, _Node] = {}
        self.plus: _Node | None = None
        # patterns terminating exactly at this level
        self.values: set = set()
        # patterns whose next level is '#' (match everything from
        # here, including zero further levels)
        self.hash_values: set = set()

    def empty(self) -> bool:
        return (not self.children and self.plus is None
                and not self.values and not self.hash_values)


class TopicTrie:
    """pattern -> set-of-values index with MQTT wildcard matching."""

    def __init__(self):
        self._root = _Node()
        self._pattern_count = 0

    def __len__(self) -> int:
        """Registered (pattern, value) pairs."""
        return self._pattern_count

    @staticmethod
    def _walk_levels(pattern: str):
        """The pattern's stored levels: everything past a '#' is
        unreachable in topic_matches (the '#' check short-circuits), so
        it is normalized away at insert time."""
        levels = pattern.split("/")
        if "#" in levels:
            levels = levels[:levels.index("#") + 1]
        return levels

    def add(self, pattern: str, value) -> None:
        node = self._root
        for level in self._walk_levels(pattern):
            if level == "#":
                if value not in node.hash_values:
                    node.hash_values.add(value)
                    self._pattern_count += 1
                return
            if level == "+":
                if node.plus is None:
                    node.plus = _Node()
                node = node.plus
            else:
                child = node.children.get(level)
                if child is None:
                    child = node.children[level] = _Node()
                node = child
        if value not in node.values:
            node.values.add(value)
            self._pattern_count += 1

    def discard(self, pattern: str, value) -> None:
        """Remove one (pattern, value) registration; prunes emptied
        branches so long-lived brokers don't accrete dead nodes."""
        path: list[tuple[_Node, str]] = []
        node = self._root
        levels = self._walk_levels(pattern)
        for level in levels:
            if level == "#":
                if value in node.hash_values:
                    node.hash_values.discard(value)
                    self._pattern_count -= 1
                break
            path.append((node, level))
            node = node.plus if level == "+" else node.children.get(level)
            if node is None:
                return
        else:
            if value in node.values:
                node.values.discard(value)
                self._pattern_count -= 1
        # prune: drop empty leaf nodes bottom-up
        for parent, level in reversed(path):
            child = parent.plus if level == "+" else parent.children.get(
                level)
            if child is None or not child.empty():
                break
            if level == "+":
                parent.plus = None
            else:
                del parent.children[level]

    def remove_value(self, value) -> None:
        """Remove `value` from EVERY registered pattern (a client
        detaching from the broker)."""
        self._remove_value(self._root, value)

    def _remove_value(self, node: _Node, value) -> None:
        if value in node.values:
            node.values.discard(value)
            self._pattern_count -= 1
        if value in node.hash_values:
            node.hash_values.discard(value)
            self._pattern_count -= 1
        for level in list(node.children):
            child = node.children[level]
            self._remove_value(child, value)
            if child.empty():
                del node.children[level]
        if node.plus is not None:
            self._remove_value(node.plus, value)
            if node.plus.empty():
                node.plus = None

    def match(self, topic: str) -> list:
        """Every value whose pattern matches `topic`, deduplicated
        (one value registered under several matching patterns appears
        once).  Order is unspecified -- callers needing determinism
        sort by their own sequence."""
        results = set(self._root.hash_values)
        current = [self._root]
        for level in topic.split("/"):
            following: list[_Node] = []
            for node in current:
                child = node.children.get(level)
                if child is not None:
                    following.append(child)
                if node.plus is not None:
                    following.append(node.plus)
            if not following:
                return list(results)
            for node in following:
                results.update(node.hash_values)
            current = following
        for node in current:
            results.update(node.values)
        return list(results)

    def matches(self, topic: str) -> bool:
        """True when ANY registered pattern matches `topic` -- the
        client-side fast path (a delivery gate needs the boolean, not
        the value set)."""
        if self._root.hash_values:
            return True
        current = [self._root]
        for level in topic.split("/"):
            following = []
            for node in current:
                child = node.children.get(level)
                if child is not None:
                    following.append(child)
                if node.plus is not None:
                    following.append(node.plus)
            if not following:
                return False
            for node in following:
                if node.hash_values:
                    return True
            current = following
        return any(node.values for node in current)
