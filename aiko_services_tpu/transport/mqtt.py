# MQTT transport: paho-mqtt when installed, the in-repo MQTT 3.1.1
# client (transport/minimqtt.py, real sockets) otherwise.
#
# Capability parity with the reference MQTT transport (reference:
# src/aiko_services/main/message/mqtt.py:65-289): background network thread,
# LWT set before connect, TLS + username/password (TLS requires paho),
# wildcard subscriptions, bounded waits for connect.  With neither paho
# nor a broker host configured, the loopback broker remains the default
# transport.

from __future__ import annotations

import threading

from .base import Transport
from ..utils import get_mqtt_configuration, get_logger

__all__ = ["MqttTransport", "mqtt_available"]

try:
    import paho.mqtt.client as _paho
    _PAHO_ERROR = None
except ImportError as _error:
    # self-contained fallback: the same wire protocol over stdlib
    # sockets -- MQTT deployment no longer needs the dependency
    from . import minimqtt as _paho
    _PAHO_ERROR = _error

_LOGGER = get_logger("mqtt")
_CONNECT_TIMEOUT_SECONDS = 10.0


def mqtt_available() -> bool:
    """True when an MQTT client implementation is available -- always,
    since the in-repo minimqtt fallback ships with the package."""
    return True


def paho_available() -> bool:
    """True when the real paho-mqtt is importable (required for TLS
    brokers; the minimqtt fallback raises on tls_set)."""
    return _PAHO_ERROR is None


class MqttTransport(Transport):
    def __init__(self, on_message=None, configuration: dict | None = None):
        super().__init__(on_message)
        self._configuration = configuration or get_mqtt_configuration()
        self._connected_event = threading.Event()
        self._subscriptions: set[str] = set()
        self._lock = threading.Lock()
        self.lwt_topic = None
        self.lwt_payload = None
        self.lwt_retain = False
        self._client = None

    def _build_client(self):
        client = _paho.Client(
            callback_api_version=_paho.CallbackAPIVersion.VERSION2)
        client.on_connect = self._on_connect
        client.on_disconnect = self._on_disconnect
        client.on_message = self._on_message
        configuration = self._configuration
        if configuration.get("username"):
            client.username_pw_set(
                configuration["username"], configuration.get("password"))
        if configuration.get("tls"):
            client.tls_set()
        if self.lwt_topic is not None:
            client.will_set(
                self.lwt_topic, self.lwt_payload, retain=self.lwt_retain)
        return client

    def connect(self) -> None:
        self._client = self._build_client()
        configuration = self._configuration
        self._client.connect_async(
            configuration["host"], configuration["port"], keepalive=60)
        self._client.loop_start()  # paho network thread
        if not self._connected_event.wait(_CONNECT_TIMEOUT_SECONDS):
            raise TimeoutError(
                f"MQTT connect timed out: {configuration['host']}:"
                f"{configuration['port']}")

    def disconnect(self, send_lwt: bool = False) -> None:
        if self._client is None:
            return
        if send_lwt and self.lwt_topic is not None:
            self._client.publish(
                self.lwt_topic, self.lwt_payload, retain=self.lwt_retain)
        self._client.disconnect()
        self._client.loop_stop()
        self._connected_event.clear()

    def publish(self, topic, payload, retain=False) -> None:
        self._client.publish(topic, payload, retain=retain)

    def subscribe(self, topic) -> None:
        with self._lock:
            self._subscriptions.add(topic)
        if self._connected_event.is_set():
            self._client.subscribe(topic)

    def unsubscribe(self, topic) -> None:
        with self._lock:
            self._subscriptions.discard(topic)
        if self._connected_event.is_set():
            self._client.unsubscribe(topic)

    def set_last_will_and_testament(self, topic, payload, retain=False):
        # Changing the LWT requires a reconnect cycle (MQTT protocol level;
        # the reference does the same disconnect/reconnect dance,
        # reference mqtt.py:192-201).
        self.lwt_topic = topic
        self.lwt_payload = payload
        self.lwt_retain = retain
        if self._client is not None and self._connected_event.is_set():
            self.disconnect()
            self.connect()

    def clear_last_will_and_testament(self, topic: str) -> None:
        # MQTT supports a single will per connection
        if self.lwt_topic == topic:
            self.lwt_topic = None
            self.lwt_payload = None
            if self._client is not None and self._connected_event.is_set():
                self.disconnect()
                self.connect()

    @property
    def connected(self) -> bool:
        return self._connected_event.is_set()

    # -- paho callbacks (network thread) -----------------------------------

    def _on_connect(self, client, userdata, flags, reason_code, properties):
        with self._lock:
            patterns = list(self._subscriptions)
        for pattern in patterns:
            client.subscribe(pattern)
        self._connected_event.set()

    def _on_disconnect(self, client, userdata, flags, reason_code,
                       properties):
        self._connected_event.clear()

    def _on_message(self, client, userdata, message):
        if self.on_message is not None:
            try:
                payload = message.payload.decode("latin-1")
                self.on_message(message.topic, payload)
            except Exception:
                _LOGGER.exception("on_message handler failed")
