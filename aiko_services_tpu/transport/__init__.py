from .base import Transport, topic_matches                    # noqa: F401
from .trie import TopicTrie                                   # noqa: F401
from .loopback import (                                       # noqa: F401
    LoopbackBroker, LoopbackTransport, get_broker, reset_brokers)
from .null import NullTransport                               # noqa: F401
from .mqtt import MqttTransport, mqtt_available               # noqa: F401


def create_transport(kind: str = None, on_message=None, **kwargs):
    """Transport factory honoring AIKO_TRANSPORT (loopback|mqtt|null)."""
    from ..utils import get_transport_configuration
    if kind is None:
        kind = get_transport_configuration()["kind"]
    if kind == "loopback":
        return LoopbackTransport(on_message, **kwargs)
    if kind == "mqtt":
        return MqttTransport(on_message, **kwargs)
    if kind == "null":
        return NullTransport(on_message)
    raise ValueError(f"Unknown transport kind: {kind}")
