# Null transport: every operation no-ops (capability parity with the
# reference "Castaway" null object, reference:
# src/aiko_services/main/message/castaway.py:9-44).  Enables fully
# transport-less single-process pipeline runs.

from __future__ import annotations

from .base import Transport

__all__ = ["NullTransport"]


class NullTransport(Transport):
    def connect(self) -> None:
        pass

    def disconnect(self, send_lwt: bool = False) -> None:
        pass

    def publish(self, topic, payload, retain=False) -> None:
        pass

    def subscribe(self, topic) -> None:
        pass

    def unsubscribe(self, topic) -> None:
        pass

    def set_last_will_and_testament(self, topic, payload, retain=False):
        pass

    @property
    def connected(self) -> bool:
        return False
