# In-process loopback broker: the hermetic default transport.
#
# The reference framework required a live mosquitto broker for every test and
# offered only a no-op "Castaway" fallback (reference:
# src/aiko_services/main/message/castaway.py:9-44) -- SURVEY.md section 4
# identifies the missing in-memory broker as the key testing gap.  This
# broker provides real MQTT semantics in-process: wildcard subscriptions,
# retained messages, and last-will-and-testament delivery on unclean
# disconnect, with deliveries dispatched from a dedicated broker thread so
# publish() never runs subscriber code inline (mirroring the paho network
# thread boundary, reference mqtt.py:125-127).

from __future__ import annotations

import itertools
import os
import queue
import threading
import time
import zlib

from .base import Transport, topic_matches
from .trie import TopicTrie

__all__ = ["LoopbackBroker", "LoopbackTransport", "get_broker", "reset_brokers"]

_BROKERS: dict[str, "LoopbackBroker"] = {}
_BROKERS_LOCK = threading.Lock()


def get_broker(name: str = "default") -> "LoopbackBroker":
    with _BROKERS_LOCK:
        broker = _BROKERS.get(name)
        if broker is None:
            broker = _BROKERS[name] = LoopbackBroker(name)
        return broker


def reset_brokers() -> None:
    """Tear down all brokers (test isolation)."""
    with _BROKERS_LOCK:
        brokers = list(_BROKERS.values())
        _BROKERS.clear()
    for broker in brokers:
        broker.shutdown()


class LoopbackBroker:
    """In-process broker with a topic-trie subscription index.

    Matching: a publish routes through a broker-side TopicTrie mapping
    each subscription pattern to its clients -- one trie walk per
    message instead of scanning every client's whole pattern set
    (`match_mode="linear"` keeps the historical O(clients x patterns)
    scan as the bench A/B arm; delivery semantics are identical: same
    messages, same per-topic order).  Clients with zero matching
    subscriptions are never woken (`broker.fanout_avoided`).

    Sharded dispatch: `shards` (or AIKO_BROKER_SHARDS) runs N dispatch
    workers with topic-hashed queues -- the SAME topic always lands on
    the SAME worker, so per-topic delivery order (and therefore the
    bit-identity discipline that rides per-stream order) is preserved
    while unrelated topics stop convoying each other.  Default 1: one
    thread, exactly the historical global ordering."""

    def __init__(self, name: str = "default", shards: int | None = None,
                 match_mode: str | None = None):
        self.name = name
        self._lock = threading.Lock()
        self._clients: list[LoopbackTransport] = []
        self._trie = TopicTrie()
        self._retained: dict[str, str] = {}
        self.match_mode = (match_mode
                           or os.environ.get("AIKO_BROKER_MATCH", "trie"))
        if shards is None:
            try:
                shards = int(os.environ.get("AIKO_BROKER_SHARDS", "1"))
            except ValueError:
                shards = 1
        self._shards = max(1, shards)
        self._alive = True
        # instruments resolved once (observe/metrics.py global
        # registry): the per-message cost is int adds + one bisect
        from ..observe.metrics import get_registry
        registry = get_registry()
        self._m_messages = registry.counter("broker.messages")
        self._m_delivered = registry.counter("broker.fanout_delivered")
        self._m_avoided = registry.counter("broker.fanout_avoided")
        self._m_match = registry.histogram("broker.match_s")
        # WAN fault plane (faults.py link_latency/link_loss/link_jitter):
        # fired-injection evidence the chaos arms reconcile against
        self._m_link_delays = registry.counter("faults.link_delays")
        self._m_link_drops = registry.counter("faults.link_drops")
        self._queues = [queue.Queue() for _ in range(self._shards)]
        self._threads = [
            threading.Thread(
                target=self._dispatch_loop, args=(shard_queue,),
                name=f"loopback-{name}-{index}", daemon=True)
            for index, shard_queue in enumerate(self._queues)]
        for thread in self._threads:
            thread.start()

    def _shard_of(self, topic: str) -> int:
        if self._shards == 1:
            return 0
        return zlib.crc32(topic.encode("utf-8")) % self._shards

    # -- client management -------------------------------------------------

    def attach(self, client: "LoopbackTransport") -> None:
        with self._lock:
            if client not in self._clients:
                self._clients.append(client)
                for pattern in client.subscription_snapshot():
                    self._trie.add(pattern, client)

    def detach(self, client: "LoopbackTransport", send_lwt: bool) -> None:
        with self._lock:
            if client in self._clients:
                self._clients.remove(client)
                self._trie.remove_value(client)
        if send_lwt:
            for topic, (payload, retain) in list(client.wills.items()):
                self.publish(topic, payload, retain=retain)

    def subscribe_client(self, client: "LoopbackTransport",
                         pattern: str) -> None:
        with self._lock:
            if client in self._clients:
                self._trie.add(pattern, client)

    def unsubscribe_client(self, client: "LoopbackTransport",
                           pattern: str) -> None:
        with self._lock:
            self._trie.discard(pattern, client)

    # -- pub/sub -----------------------------------------------------------

    def publish(self, topic: str, payload, retain: bool = False,
                origin=None) -> None:
        """`origin` is the WAN fault plane's provenance tag -- a
        (region, publish ordinal, client name) triple a chaos-labeled
        transport attaches so cross-region deliveries can consult the
        seeded link_latency/link_loss/link_jitter points at fan-out.
        None (every production publish) costs one is-None check."""
        payload = _to_text(payload)
        if retain:
            with self._lock:
                if payload == "":
                    self._retained.pop(topic, None)  # MQTT clears on empty
                else:
                    self._retained[topic] = payload
        self._queues[self._shard_of(topic)].put(
            ("publish", topic, payload, origin))

    def deliver_retained(self, client: "LoopbackTransport",
                         pattern: str) -> None:
        with self._lock:
            matches = [(topic, payload)
                       for topic, payload in self._retained.items()
                       if topic_matches(pattern, topic)]
        for topic, payload in matches:
            # retained replays shard by TOPIC too, so they order
            # consistently against live publishes on the same topic
            self._queues[self._shard_of(topic)].put(
                ("retained", topic, payload, client))

    def retained(self, topic: str):
        with self._lock:
            return self._retained.get(topic)

    # -- dispatch threads --------------------------------------------------

    def _dispatch_loop(self, shard_queue: queue.Queue) -> None:
        while True:
            item = shard_queue.get()
            if item is None:
                return
            if item[0] == "publish":
                _, topic, payload, origin = item
                matched = self._match_clients(topic)
                for client in matched:
                    if not client._connected:
                        continue
                    if origin is not None and not self._link_admits(
                            origin, client):
                        continue
                    client._deliver(topic, payload)
            else:  # retained delivery to one client
                _, topic, payload, client = item
                client._deliver(topic, payload)

    def _link_admits(self, origin, client) -> bool:
        """WAN fault plane: should this delivery cross its region link
        now, and after how long?  Consulted per (publish, subscriber)
        pair only when the publisher carried an `origin` tag AND the
        subscriber declares a different `chaos_region`; intra-region
        (or unlabeled) deliveries never reach the injector.  The draw
        keys on (link, subscriber, publish ordinal), so firing is
        identical across runs regardless of shard-thread timing.  A
        fired link_latency/link_jitter sleeps ON the dispatch shard --
        deliveries over one topic's shard serialize behind the slow
        link, which is exactly the convoy a congested WAN path
        creates."""
        src_region, publish_seq, _publisher = origin
        dst_region = client.chaos_region
        if dst_region is None or dst_region == src_region:
            return True
        from ..faults import get_injector
        injector = get_injector()
        if injector is None:
            return True
        scope = client.chaos_name or str(client.client_id)
        if injector.link_drop(src_region, dst_region,
                              frame_id=publish_seq, scope=scope):
            self._m_link_drops.inc()
            return False
        delay = injector.link_delay(src_region, dst_region,
                                    frame_id=publish_seq, scope=scope)
        if delay > 0:
            self._m_link_delays.inc()
            time.sleep(delay)
        return True

    def _match_clients(self, topic: str) -> list:
        """The clients this message must wake.  Trie-mode order is
        deterministic (client_id); per-client per-topic order -- the
        contract bit-identity rides on -- is identical in both modes,
        cross-client interleaving was never guaranteed."""
        start = time.perf_counter()
        if self.match_mode == "linear":
            # A/B reference arm: the historical per-client linear scan
            with self._lock:
                clients = list(self._clients)
            matched = [client for client in clients
                       if client._subscription_match_linear(topic)]
            total = len(clients)
        else:
            with self._lock:
                matched = self._trie.match(topic)
                total = len(self._clients)
            matched.sort(key=lambda client: client.client_id)
        self._m_match.record(time.perf_counter() - start)
        self._m_messages.inc()
        self._m_delivered.inc(len(matched))
        self._m_avoided.inc(total - len(matched))
        return matched

    def drain(self, timeout: float = 5.0) -> None:
        """Block until every queued delivery has been dispatched (tests)."""
        events = []
        for shard_queue in self._queues:
            done = threading.Event()
            events.append(done)
            shard_queue.put(("retained", None, None, _Sentinel(done)))
        deadline = time.monotonic() + timeout
        for done in events:
            done.wait(max(0.0, deadline - time.monotonic()))

    def shutdown(self) -> None:
        if self._alive:
            self._alive = False
            for shard_queue in self._queues:
                shard_queue.put(None)
            for thread in self._threads:
                thread.join(timeout=2)


class _Sentinel:
    def __init__(self, event):
        self._event = event

    def _deliver(self, topic, payload):
        self._event.set()


def _to_text(payload) -> str:
    if payload is None:
        return ""
    if isinstance(payload, bytes):
        return payload.decode("latin-1")
    return str(payload)


class LoopbackTransport(Transport):
    _ids = itertools.count()

    def __init__(self, on_message=None, broker: str = "default"):
        super().__init__(on_message)
        self._broker_name = broker
        self._broker: LoopbackBroker | None = None
        self._subscriptions: set[str] = set()
        self._lock = threading.Lock()
        self._connected = False
        self.client_id = next(self._ids)
        # Unlike MQTT's single will per connection, the loopback broker
        # supports one will PER TOPIC so a process-liveness will and a
        # registrar-election will can coexist in one process.
        self.wills: dict[str, tuple[str, bool]] = {}
        # chaos harness: name this client under the seeded
        # `broker_partition` fault point (faults.py).  None (the
        # default) costs one attribute check per publish
        self.chaos_name: str | None = None
        # WAN fault plane: the region this client lives in.  None (the
        # default) keeps every publish on the partition-only fast
        # path; set, each publish carries an (region, ordinal, name)
        # origin tag and consults the seeded `region_partition` point
        # with this client's OWN publish ordinal -- so one spec severs
        # every client in a region deterministically (faults.py)
        self.chaos_region: str | None = None
        self._publish_seq = 0
        self._partitioned = False
        self.partition_dropped = 0   # publishes lost to a partition

    def connect(self) -> None:
        self._broker = get_broker(self._broker_name)
        self._broker.attach(self)
        self._connected = True
        with self._lock:
            patterns = list(self._subscriptions)
        for pattern in patterns:
            self._broker.deliver_retained(self, pattern)

    def disconnect(self, send_lwt: bool = False) -> None:
        if self._broker is not None:
            self._broker.detach(self, send_lwt)
        self._connected = False

    def sever(self) -> None:
        """Abnormal death: drop off the broker WITHOUT a clean
        disconnect, firing every registered last-will (exactly what a
        real broker does when a client's TCP session dies).  Tests use
        this to crash a replica process mid-stream -- the registrar
        reaps it from the LWT "(absent)" notice and discovery-driven
        consumers (ServicesCache, the serving gateway) must converge."""
        self.disconnect(send_lwt=True)

    def partition(self) -> None:
        """Broker partition: traffic drops in BOTH directions and the
        broker -- having lost the client past its keepalive -- fires
        the last-wills, exactly the >1.5x-keepalive cutoff shape a
        real broker applies.  Unlike sever(), the CLIENT keeps its
        subscriptions and wills, so heal() restores service (and the
        process layer re-registers, Process.rejoin())."""
        if self._partitioned:
            return
        self._partitioned = True
        if self._broker is not None:
            self._broker.detach(self, send_lwt=True)

    def heal(self) -> None:
        """End a partition: re-attach to the broker and replay retained
        messages for every subscription (the reconnect contract)."""
        if not self._partitioned:
            return
        self._partitioned = False
        if self._broker is not None and self._connected:
            self._broker.attach(self)
            with self._lock:
                patterns = list(self._subscriptions)
            for pattern in patterns:
                self._broker.deliver_retained(self, pattern)

    @property
    def partitioned(self) -> bool:
        return self._partitioned

    def publish(self, topic: str, payload, retain: bool = False) -> None:
        if self._broker is None:
            raise RuntimeError("LoopbackTransport not connected")
        if self.chaos_name is not None and not self._partitioned:
            self._consult_partition_point()
        origin = None
        if self.chaos_region is not None:
            seq = self._publish_seq
            self._publish_seq += 1
            if not self._partitioned:
                self._consult_region_point(seq)
            origin = (self.chaos_region, seq,
                      self.chaos_name or str(self.client_id))
        if self._partitioned:
            # a partitioned client's publishes die on the wire (QoS 0
            # semantics); the counter is the reconcile evidence
            self.partition_dropped += 1
            return
        self._broker.publish(topic, payload, retain, origin=origin)

    def _consult_partition_point(self) -> None:
        """Seeded chaos: one `broker_partition` draw per publish
        (faults.py; frame=k partitions on this client's k-th publish,
        ms= schedules the heal)."""
        from ..faults import get_injector
        injector = get_injector()
        if injector is None:
            return
        duration = injector.broker_partition(self.chaos_name)
        if duration == 0.0:
            return
        self.partition()
        if duration > 0:
            timer = threading.Timer(duration, self.heal)
            timer.daemon = True
            timer.start()

    def _consult_region_point(self, seq: int) -> None:
        """Seeded chaos: one `region_partition` draw per publish for a
        region-labeled client (faults.py; node= the region, frame=k
        severs at THIS client's k-th publish so the whole region dies
        as a unit, ms= schedules the heal)."""
        from ..faults import get_injector
        injector = get_injector()
        if injector is None:
            return
        duration = injector.region_partition(
            self.chaos_region, frame_id=seq,
            scope=self.chaos_name or str(self.client_id))
        if duration == 0.0:
            return
        self.partition()
        if duration > 0:
            timer = threading.Timer(duration, self.heal)
            timer.daemon = True
            timer.start()

    def subscribe(self, topic: str) -> None:
        with self._lock:
            if topic in self._subscriptions:
                return
            self._subscriptions.add(topic)
        if self._broker is not None and self._connected:
            # broker-side routing index: only attached clients index
            # (subscribe_client checks membership, so a partitioned
            # client's new patterns wait for heal()'s re-attach)
            self._broker.subscribe_client(self, topic)
            self._broker.deliver_retained(self, topic)

    def unsubscribe(self, topic: str) -> None:
        with self._lock:
            self._subscriptions.discard(topic)
        if self._broker is not None:
            self._broker.unsubscribe_client(self, topic)

    def subscription_snapshot(self) -> list[str]:
        with self._lock:
            return list(self._subscriptions)

    def set_last_will_and_testament(
            self, topic: str, payload, retain: bool = False) -> None:
        self.wills[topic] = (_to_text(payload), retain)

    def clear_last_will_and_testament(self, topic: str) -> None:
        self.wills.pop(topic, None)

    @property
    def connected(self) -> bool:
        return self._connected

    # -- broker-side delivery (broker dispatch thread) ---------------------
    #
    # Routing moved broker-side: the broker's TopicTrie picks the
    # matched clients and calls _deliver directly, so subscribed-set
    # scans no longer ride the per-message hot path at all.

    def _subscription_match_linear(self, topic: str) -> bool:
        """The historical O(patterns) scan -- kept as the broker's
        `match_mode="linear"` A/B reference arm."""
        if not self._connected:
            return False
        with self._lock:
            return any(topic_matches(pattern, topic)
                       for pattern in self._subscriptions)

    def _deliver(self, topic: str, payload: str) -> None:
        if self.on_message is not None:
            try:
                self.on_message(topic, payload)
            except Exception:  # broker thread must survive handler errors
                import traceback
                traceback.print_exc()
