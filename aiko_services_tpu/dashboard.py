# Dashboard: live view of services, share variables, and logs.
#
# Capability parity with the reference dashboard (reference:
# src/aiko_services/main/dashboard.py:286-648: asciimatics TUI with a
# services table, live share-variable view over ECConsumer, log page,
# variable editing publishing "(update name value)" to /control, and
# service kill).  asciimatics is not available here; the TUI is stdlib
# curses, and --snapshot mode prints one plain-text table (hermetically
# testable, usable in scripts).

from __future__ import annotations

import time

from .runtime import ECConsumer, Process
from .runtime.service import ServiceFilter
from .runtime.share import ServicesCache
from .utils import generate, get_logger, parse

__all__ = ["DashboardModel", "run_dashboard", "render_snapshot",
           "register_plugin", "plugin_for", "format_snapshot_lines"]

_LOGGER = get_logger("dashboard")

# Per-protocol detail renderers (reference dashboard _PLUGINS,
# dashboard.py:726-730): plugin(model) -> list[str] extra detail lines
# for the selected service.
_PLUGINS: dict = {}


def register_plugin(protocol_name: str, renderer) -> None:
    _PLUGINS[protocol_name] = renderer


def plugin_for(protocol: str):
    from .runtime.service import ServiceProtocol
    name, _ = ServiceProtocol.name_version(str(protocol))
    return _PLUGINS.get(name)


def _registrar_plugin(model: "DashboardModel") -> list:
    share = model.selected_share
    return [f"registrar state: {share.get('state', '?')}   "
            f"services: {share.get('service_count', '?')}   "
            f"started: {share.get('time_started', '?')}"]


register_plugin("registrar", _registrar_plugin)


def _pipeline_plugin(model: "DashboardModel") -> list:
    """Pipeline detail lines: the telemetry summary the pipeline mirrors
    into its EC share (observe.PipelineTelemetry.summary) plus stream
    state -- the at-a-glance serving health row."""
    share = model.selected_share
    lines = [f"streams: {share.get('stream_count', '?')}   "
             f"frames: {share.get('frame_count', '?')}   "
             f"elements: {share.get('element_count', '?')}"]
    metrics = share.get("metrics")
    if isinstance(metrics, dict):
        lines.append(
            f"telemetry: frames {metrics.get('frames', 0)}  "
            f"dropped {metrics.get('dropped', 0)}  "
            f"errors {metrics.get('errors', 0)}")
        lines.append(
            f"groups: fused {metrics.get('fused_groups', 0)}  "
            f"chained {metrics.get('chained_groups', 0)}  "
            f"compiles {metrics.get('compiles_fused', 0)}  "
            f"cohort splits {metrics.get('cohort_splits', 0)}")
        decode = metrics.get("decode")
        if isinstance(decode, dict):
            # continuous-batching engine occupancy (LMGenerate
            # `continuous: true`): the per-replica serving health row.
            # No numeric format specs: EC-share values arrive over the
            # S-expression wire as STRINGS (like every other line here)
            lines.append(
                f"decode: slots {decode.get('active_slots', 0)}  "
                f"waiting {decode.get('waiting', 0)}  "
                f"free blocks {decode.get('free_blocks', 0)}  "
                f"admitted {decode.get('admitted', 0)}  "
                f"completed {decode.get('completed', 0)}  "
                f"preempted {decode.get('preempted', 0)}  "
                f"deferred {decode.get('deferred', 0)}")
    else:
        lines.append("telemetry: (no summary yet -- disabled or "
                     "first interval pending; press m for live metrics)")
    return lines


register_plugin("pipeline", _pipeline_plugin)


def _gateway_plugin(model: "DashboardModel") -> list:
    """Serving-gateway detail lines: admission/routing totals from the
    telemetry summary plus a per-gateway `pool:` row (elastic fleet:
    size, scale decisions, last time-to-healthy) and one line per
    replica (state, load, warm/cold) -- the same view `aiko system
    status` prints from the EC share."""
    share = model.selected_share
    lines = [f"replicas: {share.get('replica_count', '?')}   "
             f"streams: {share.get('stream_count', '?')}   "
             f"policy: {share.get('policy', '') or '(defaults)'}"]
    metrics = share.get("metrics")
    if not isinstance(metrics, dict):
        lines.append("telemetry: (no summary yet -- disabled or first "
                     "interval pending; press m for live metrics)")
        return lines
    admission_line = (
        f"admission: admitted {metrics.get('admitted', 0)}  "
        f"shed {metrics.get('shed_frames', 0)}  "
        f"routed {metrics.get('routed', 0)}  "
        f"completed {metrics.get('completed', 0)}  "
        f"parked {metrics.get('parked', 0)}  "
        f"failovers {metrics.get('failovers', 0)}")
    if "admit_latency_p99_ms" in metrics:
        admission_line += (
            f"  latency p50 {metrics.get('admit_latency_p50_ms')}ms "
            f"p99 {metrics.get('admit_latency_p99_ms')}ms")
    lines.append(admission_line)
    slo = metrics.get("slo")
    if isinstance(slo, dict):
        # per-priority SLO attainment/burn (streams that declared
        # slo_ms): the per-tenant accounting row
        parts = []
        for priority in sorted(
                slo, key=lambda p: (not str(p).isdigit(),
                                    int(p) if str(p).isdigit() else 0,
                                    str(p))):
            record = slo[priority]
            if not isinstance(record, dict):
                continue
            attainment = record.get("attainment")
            part = (
                f"p{priority} {attainment if attainment is not None else '?'}"
                f" ({record.get('ok', 0)}/{record.get('miss', 0)} "
                f"ok/miss)")
            if record.get("burn_window") is not None:
                # sliding-window burn (autopilot gate input): the
                # miss fraction over the LAST window only, not the
                # lifetime ratio attainment reports
                part += f" burn {record.get('burn_window')}"
            parts.append(part)
        if parts:
            lines.append("slo: " + "  ".join(parts))
    autopilot = metrics.get("autopilot")
    if isinstance(autopilot, dict):
        convergence = autopilot.get("convergence")
        autopilot_line = (
            f"autopilot: {'apply' if autopilot.get('apply') else 'dry-run'}"
            f"/{autopilot.get('scope', 'local')}  "
            f"deltas {autopilot.get('deltas_applied', 0)} applied "
            f"{autopilot.get('deltas_clamped', 0)} clamped "
            f"{autopilot.get('deltas_skipped', 0)} skipped  "
            f"backoffs {autopilot.get('backoffs', 0)}")
        if convergence is not None:
            autopilot_line += (
                f"  convergence {convergence}"
                f"{' (converged)' if autopilot.get('converged') else ''}")
        if autopilot.get("rebalances"):
            autopilot_line += (
                f"  rebalances {autopilot.get('rebalances')}")
        lines.append(autopilot_line)
    decomposition = metrics.get("stream_decomposition")
    if isinstance(decomposition, dict):
        total = decomposition.get("_total")
        if isinstance(total, dict):
            # fleet end-to-end decomposition: where admitted streams'
            # latency went (admit+route+queue+prefill+decode+emit)
            lines.append("e2e: " + "  ".join(
                f"{stage} {total.get(stage)}ms"
                for stage in ("admit", "route", "queue", "prefill",
                              "decode", "emit") if stage in total))
    pool_line = (
        f"pool: size {metrics.get('pool_size', 0)}  "
        f"pending {metrics.get('pending_spawns', 0)}  "
        f"scale_up {metrics.get('scale_ups', 0)}  "
        f"scale_down {metrics.get('scale_downs', 0)}")
    if "time_to_healthy_ms" in metrics:
        pool_line += (f"  time_to_healthy "
                      f"{metrics.get('time_to_healthy_ms')}ms")
    lines.append(pool_line)
    ha = metrics.get("ha")
    if isinstance(ha, dict):
        ha_line = (
            f"ha: role {ha.get('role', '?')}  "
            f"journal {ha.get('backend', '?')} "
            f"({ha.get('journal_entries', 0)} entries, "
            f"{ha.get('journal_appends', 0)} appends)  "
            f"takeovers {ha.get('takeovers', 0)}  "
            f"replayed {ha.get('replayed', 0)}  "
            f"stale {ha.get('dropped_stale', 0)}")
        if "takeover_ms" in ha:
            ha_line += f"  last_takeover {ha.get('takeover_ms')}ms"
        lines.append(ha_line)
    pool = metrics.get("pool")
    if isinstance(pool, dict):
        for name in sorted(pool):
            replica = pool[name]
            if not isinstance(replica, dict):
                continue
            # EC-share values may arrive as wire STRINGS ("True")
            warm = str(replica.get("warm", False)).lower() in (
                "true", "1")
            lines.append(
                f"  {name}: {replica.get('state', '?')}  "
                f"{'warm' if warm else 'cold'}  "
                f"inflight {replica.get('outstanding', 0)}/"
                f"{replica.get('inflight', 0)}  "
                f"queue {replica.get('queue_depth', 0)}  "
                f"streams {replica.get('streams', 0)}")
    return lines


register_plugin("gateway", _gateway_plugin)


def format_snapshot_lines(snapshot: dict, limit: int = 40) -> list:
    """Human-readable lines for one metrics snapshot: counters first
    (sorted), then histograms as count/mean/p50/p99/max milliseconds.
    Quantiles come from the shared snapshot_quantile helper (the one
    implementation tune and the gateway summary also read), not an
    ad-hoc re-derivation."""
    from .observe.metrics import DEFAULT_BOUNDS, snapshot_quantile
    lines = []
    for name, value in sorted((snapshot.get("counters") or {}).items()):
        lines.append(f"{name:40} {value}")
    for name, value in sorted((snapshot.get("gauges") or {}).items()):
        lines.append(f"{name:40} {value:g}")
    for name, hist in sorted((snapshot.get("histograms") or {}).items()):
        count = hist.get("count", 0)
        mean = (hist.get("sum", 0.0) / count) if count else 0.0
        high = hist.get("max", 0.0)
        # timing histograms (the "_s" naming convention) read in ms;
        # occupancy/size histograms stay in their own unit (their
        # custom bucket ladders are not in the snapshot, so quantiles
        # are only printed for the standard timing ladder)
        if ("_s:" in name or name.endswith("_s")) and (
                len(hist.get("buckets") or []) == len(DEFAULT_BOUNDS) + 1):
            p50 = snapshot_quantile(hist, 0.5)
            p99 = snapshot_quantile(hist, 0.99)
            lines.append(f"{name:40} n={count} mean={mean * 1000:.3f}ms "
                         f"p50={p50 * 1000:.3f}ms "
                         f"p99={p99 * 1000:.3f}ms "
                         f"max={high * 1000:.3f}ms")
        elif "_s:" in name or name.endswith("_s"):
            lines.append(f"{name:40} n={count} mean={mean * 1000:.3f}ms "
                         f"max={high * 1000:.3f}ms")
        else:
            lines.append(f"{name:40} n={count} mean={mean:.2f} "
                         f"max={high:g}")
    return lines[:limit]


class DashboardModel:
    """Transport-facing half, UI-agnostic: the services table, one
    selected service's mirrored share dict, and control actions."""

    def __init__(self, process: Process):
        self.process = process
        self.services_cache = ServicesCache(process)
        self.services_cache.add_handler(self._service_event, ServiceFilter())
        self.rows: dict[str, object] = {}       # topic_path -> fields
        self.selected: str | None = None
        self.selected_share: dict = {}
        self._consumer: ECConsumer | None = None
        self.log_lines: list = []
        self._log_topic = None
        self.history_lines: list = []
        self._history_topic = None
        self.metrics_lines: list = []
        self._metrics_topic = None
        self._metrics_by_source: dict = {}

    def _service_event(self, command, fields) -> None:
        # copy-on-write: the curses thread iterates self.rows concurrently
        rows = dict(self.rows)
        if command == "add":
            rows[fields.topic_path] = fields
        else:
            rows.pop(fields.topic_path, None)
        self.rows = rows
        if command != "add" and fields.topic_path == self.selected:
            self.select(None)

    # -- selection + share mirror (reference dashboard.py:344-366) ---------

    def select(self, topic_path: str | None) -> None:
        if self._consumer is not None:
            self._consumer.terminate()
            self._consumer = None
        if self._log_topic is not None:
            self.process.remove_message_handler(
                self._log_handler, self._log_topic)
            self._log_topic = None
        if self._metrics_topic is not None:
            self.process.remove_message_handler(
                self._metrics_handler, self._metrics_topic)
            self._metrics_topic = None
        self.selected = topic_path
        self.selected_share = {}
        self.log_lines = []
        self.metrics_lines = []
        self._metrics_by_source = {}
        if topic_path is not None:
            self._consumer = ECConsumer(
                self.process, self.selected_share, topic_path)
            self._log_topic = f"{topic_path}/log"  # service.topic_log
            self.process.add_message_handler(
                self._log_handler, self._log_topic)
            # live telemetry: pipelines publish "(metrics source
            # snapshot)" here on their metrics_interval
            self._metrics_topic = f"{topic_path}/metrics"
            self.process.add_message_handler(
                self._metrics_handler, self._metrics_topic)

    def _log_handler(self, topic, payload) -> None:
        self.log_lines.append(payload)
        del self.log_lines[:-200]

    def _metrics_handler(self, topic, payload) -> None:
        from .observe.metrics import parse_metrics_payload
        decoded = parse_metrics_payload(payload)
        if decoded is None:
            return
        source, snapshot = decoded
        # one topic carries several sources (the pipeline's own
        # registry + the process-global one): keep the latest per
        # source and render them as labeled sections
        self._metrics_by_source[source] = format_snapshot_lines(snapshot)
        lines = []
        for source in sorted(self._metrics_by_source):
            lines.append(f"== {source}")
            lines.extend(self._metrics_by_source[source])
        self.metrics_lines = lines

    # -- actions (reference dashboard.py:232-235, 368-377) ------------------

    def update_variable(self, name: str, value) -> None:
        if self.selected:
            self.process.publish(f"{self.selected}/control",
                                 generate("update", [name, value]))

    def kill_selected(self) -> None:
        if self.selected:
            self.process.publish(f"{self.selected}/in",
                                 generate("terminate", []))

    # -- registrar history page (reference dashboard.py:565-648) ------------

    def request_history(self, count: int = 20) -> None:
        """Ask the selected service for its event history ring (the
        registrar's `(history response_topic count)` actor command,
        runtime/registrar.py:155).  Each request gets its OWN response
        topic (a per-request sequence number): over a real broker,
        still-in-flight replies from a previous request land on the
        retired topic -- no handler -- instead of interleaving into the
        new page."""
        if not self.selected:
            return
        self.history_lines = []
        if self._history_topic is not None:
            self.process.remove_message_handler(
                self._history_handler, self._history_topic)
        self._history_seq = getattr(self, "_history_seq", 0) + 1
        self._history_topic = (
            f"{self.process.topic_path_process}/0/dashboard/history/"
            f"{self._history_seq}")
        self.process.add_message_handler(
            self._history_handler, self._history_topic)
        self.process.publish(
            f"{self.selected}/in",
            generate("history", [self._history_topic, str(count)]))

    def _history_handler(self, topic, payload) -> None:
        try:
            command, parameters = parse(str(payload))
        except ValueError:
            return
        if command == "history" and len(parameters) >= 4:
            event, timestamp, topic_path, name = parameters[:4]
            self.history_lines.append(
                f"{event:8} {str(name):18.18} {topic_path}  @{timestamp}")
        del self.history_lines[:-200]


def render_snapshot(model: DashboardModel) -> str:
    lines = [f"{'TOPIC PATH':40} {'NAME':20} {'PROTOCOL':30} TAGS"]
    for topic_path, fields in sorted(model.rows.items()):
        protocol = str(fields.protocol).rsplit("/", 1)[-1]
        lines.append(f"{topic_path:40} {str(fields.name):20} "
                     f"{protocol:30} {','.join(fields.tags or [])}")
    lines.append(f"-- {len(model.rows)} service(s)")
    if model.selected is not None:
        lines.append(f"-- log {model.selected} "
                     f"({len(model.log_lines)} record(s))")
        lines.extend(f"  {line}" for line in model.log_lines[-10:])
    return "\n".join(lines)


def run_dashboard(transport_kind: str | None = None,
                  snapshot: bool = False, wait: float = 3.0) -> None:
    process = Process(transport_kind=transport_kind)
    model = DashboardModel(process)
    process.run(in_thread=True)
    if snapshot:
        deadline = time.time() + wait
        while time.time() < deadline and not model.rows:
            time.sleep(0.1)
        print(render_snapshot(model))
        process.terminate()
        return
    _run_curses(model)
    process.terminate()


def _run_curses(model: DashboardModel) -> None:
    import curses

    curses.wrapper(lambda screen: _dashboard_ui(model, screen, curses))


def _parse_edit_value(text: str):
    """Edit input values cross as the most natural type: int/float when
    they parse, bare string otherwise (the EC wire is text anyway)."""
    for caster in (int, float):
        try:
            return caster(text)
        except ValueError:
            continue
    return text


def _page_rows(screen, reserved: int = 4, cap: int = 40) -> int:
    """Visible line budget for a full-screen page: addstr past the
    window's last row raises curses.error and would kill the UI loop,
    so clamp to the terminal height (fake screens without getmaxyx get
    the legacy cap)."""
    getmaxyx = getattr(screen, "getmaxyx", None)
    if getmaxyx is None:
        return cap
    try:
        return max(min(cap, getmaxyx()[0] - reserved), 0)
    except Exception:
        return cap


def _dashboard_ui(model: DashboardModel, screen, curses) -> None:
    """The curses loop, with screen + curses injectable so the
    fake-curses tests drive it end-to-end.  Keys (reference
    dashboard.py:232-235 edit, 565-648 history/log pages):
      q quit | up/down select | k kill | l toggle log page
      e edit -- type "name value", Enter publishes (update name value)
        to the selected service's /control, Esc cancels
      h history -- requests the selected registrar's event ring and
        shows it; any key returns to the services page
      m metrics -- live telemetry page: counters/gauges/histograms from
        the selected service's metrics topic; any key returns
    """
    curses.curs_set(0)
    screen.nodelay(True)
    index = 0
    page = "services"
    edit_buffer: str | None = None
    status = ""
    while True:
        screen.erase()
        rows = sorted(model.rows.items())
        screen.addstr(0, 0, "aiko_services_tpu dashboard   "
                      "(q quit, up/down select, k kill, e edit, "
                      "h history, l log, m metrics)", curses.A_BOLD)
        if edit_buffer is not None:
            screen.addstr(1, 0, f"update> {edit_buffer}", curses.A_BOLD)
        elif status:
            screen.addstr(1, 0, status, curses.A_DIM)
        if page == "history":
            screen.addstr(2, 0, f"history: {model.selected or '-'}",
                          curses.A_BOLD)
            if not model.history_lines:
                screen.addstr(3, 0, "(waiting for history...)",
                              curses.A_DIM)
            # newest entries: the handler trims keeping the TAIL, so
            # with >40 buffered lines the head is the stale end
            # ([-0:] would be the WHOLE buffer, hence the rows guard)
            rows_budget = _page_rows(screen)
            for row, line in enumerate(
                    model.history_lines[-rows_budget:]
                    if rows_budget else []):
                screen.addstr(row + 3, 0, str(line)[:120])
        elif page == "log":
            screen.addstr(2, 0, f"log: {model.selected or '-'}",
                          curses.A_BOLD)
            rows_budget = _page_rows(screen)
            for row, line in enumerate(
                    model.log_lines[-rows_budget:]
                    if rows_budget else []):
                screen.addstr(row + 3, 0, str(line)[:120])
        elif page == "metrics":
            screen.addstr(2, 0, f"metrics: {model.selected or '-'}",
                          curses.A_BOLD)
            if not model.metrics_lines:
                screen.addstr(3, 0, "(waiting for a metrics publish -- "
                              "pipelines export every metrics_interval)",
                              curses.A_DIM)
            for row, line in enumerate(
                    model.metrics_lines[:_page_rows(screen)]):
                screen.addstr(row + 3, 0, str(line)[:120])
        else:
            for row, (topic_path, fields) in enumerate(rows[:30]):
                marker = ">" if row == index else " "
                line = (f"{marker} {topic_path:38.38} "
                        f"{str(fields.name):18.18} "
                        f"{str(fields.protocol).rsplit('/', 1)[-1]:20.20}")
                screen.addstr(row + 3, 0, line)
            if rows and index < len(rows):
                selected_topic, selected_fields = rows[index]
                if model.selected != selected_topic:
                    model.select(selected_topic)
                base = min(len(rows), 30) + 4
                screen.addstr(base, 0, "share:", curses.A_BOLD)
                offset = 0
                for offset, (key, value) in enumerate(
                        sorted(model.selected_share.items())[:15]):
                    screen.addstr(base + 1 + offset, 2,
                                  f"{key} = {value}"[:100])
                plugin = plugin_for(selected_fields.protocol)
                if plugin is not None:
                    for extra, line in enumerate(plugin(model)):
                        screen.addstr(base + offset + 2 + extra, 2,
                                      str(line)[:100], curses.A_DIM)
        screen.refresh()
        key = screen.getch()
        if key == -1:
            time.sleep(0.1)
            continue
        if edit_buffer is not None:
            # inline edit line: printable chars accumulate, Enter
            # commits, Esc cancels, backspace erases
            if key in (10, 13):
                parts = edit_buffer.strip().split(None, 1)
                if len(parts) == 2:
                    model.update_variable(
                        parts[0], _parse_edit_value(parts[1]))
                    status = f"sent (update {parts[0]} {parts[1]})"
                else:
                    status = "edit needs: name value"
                edit_buffer = None
            elif key == 27:
                edit_buffer, status = None, "edit cancelled"
            elif key in (curses.KEY_BACKSPACE, 127, 8):
                edit_buffer = edit_buffer[:-1]
            elif 32 <= key < 127:
                edit_buffer += chr(key)
            continue
        if key == ord("q"):
            return
        if page in ("history", "log", "metrics"):
            page = "services"  # any key returns
            continue
        if key == curses.KEY_DOWN:
            index = min(index + 1, max(len(rows) - 1, 0))
        elif key == curses.KEY_UP:
            index = max(index - 1, 0)
        elif key == ord("k"):
            model.kill_selected()
        elif key == ord("e") and model.selected:
            edit_buffer, status = "", ""
        elif key == ord("h") and model.selected:
            model.request_history()
            page = "history"
        elif key == ord("l") and model.selected:
            page = "log"
        elif key == ord("m") and model.selected:
            page = "metrics"
