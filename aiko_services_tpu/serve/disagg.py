# Disaggregated-serving policy: the gateway-side knobs of the
# prefill/decode split (decode/disagg.py holds the data plane).
#
# Grammar (gateway parameter `disagg`, same directive style as the
# admission/autoscale/journal policies -- operators learn one shape):
#
#   policy    := directive (";" directive)*
#   directive := "role=" ("prefill"|"decode")
#                          a REPLICA-side spec pins the replica's pool;
#                          on the gateway spec it is rejected by
#                          DisaggPolicy.parse's cross-field check
#                          (a gateway fronts BOTH pools)
#              | "adopt_timeout=" float
#                          seconds a decode replica's KV fetch may take
#                          before the adopt falls back to a local
#                          re-prefill (bounds how long one dead prefill
#                          replica can stall a stream's first token)
#              | "min_replicas:" pool "=" int
#                          per-pool floor for the autoscaler (pool in
#                          prefill|decode); the two pools scale on
#                          DIFFERENT signals -- prefill on queue wait,
#                          decode on slot occupancy -- so they need
#                          separate floors
#
# Example: "adopt_timeout=2;min_replicas:prefill=1;min_replicas:decode=2"
#
# Validation is at parse time through the shared directive core
# (analyze/grammar.py): `aiko lint` checks it offline as AIKO408 with
# the same messages Gateway construction raises.

from __future__ import annotations

from ..analyze.grammar import DirectiveGrammar, Field, GrammarError

__all__ = ["DISAGG_GRAMMAR", "DisaggPolicy", "DISAGG_ROLES"]

DISAGG_ROLES = ("prefill", "decode")
DEFAULT_ADOPT_TIMEOUT_S = 5.0


def _parse_pool_floor(tail, value):
    """`min_replicas:pool=n` -> (pool, floor)."""
    pool = str(tail).strip()
    if pool not in DISAGG_ROLES:
        raise GrammarError(
            f"disagg policy: min_replicas pool must be one of "
            f"{DISAGG_ROLES}, got {pool!r}", kind="unknown")
    floor = int(value)
    if floor < 0:
        raise GrammarError(
            f"disagg policy: min_replicas:{pool}={floor} is below the "
            f"minimum 0")
    return pool, floor


DISAGG_GRAMMAR = DirectiveGrammar(
    "disagg policy",
    options={
        "role": Field("str", choices=DISAGG_ROLES),
        "adopt_timeout": Field("float", minimum=0.0),
    },
    prefixes={"min_replicas": _parse_pool_floor})


class DisaggPolicy:
    """Parsed disagg spec.  `role` stays None on a gateway policy (the
    gateway fronts both pools); a replica-side spec carries exactly the
    role and nothing else."""

    __slots__ = ("role", "adopt_timeout_s", "min_replicas", "spec")

    def __init__(self):
        self.role: str | None = None
        self.adopt_timeout_s = DEFAULT_ADOPT_TIMEOUT_S
        self.min_replicas: dict[str, int] = {}
        self.spec = ""

    @classmethod
    def parse(cls, spec) -> "DisaggPolicy":
        """Parse a spec (directive string, dict of the same keys, or
        None/"" for all defaults)."""
        policy = cls()
        if spec is None or spec == "":
            return policy
        if isinstance(spec, DisaggPolicy):
            return spec
        parsed = DISAGG_GRAMMAR.parse(spec)
        if not isinstance(spec, dict):
            policy.spec = str(spec)
        if "role" in parsed.options:
            policy.role = parsed.options["role"]
        if "adopt_timeout" in parsed.options:
            policy.adopt_timeout_s = parsed.options["adopt_timeout"]
        for _, _, (pool, floor) in parsed.prefixed:
            policy.min_replicas[pool] = floor
        if policy.role is not None and (policy.min_replicas
                                        or "adopt_timeout"
                                        in parsed.options):
            raise GrammarError(
                "disagg policy: role= is a replica-side directive; a "
                "gateway spec carries adopt_timeout/min_replicas only")
        return policy

    def floor(self, pool: str, default: int = 0) -> int:
        return self.min_replicas.get(pool, default)

    def __repr__(self):
        return (f"DisaggPolicy(role={self.role}, "
                f"adopt_timeout={self.adopt_timeout_s}, "
                f"min_replicas={dict(sorted(self.min_replicas.items()))})")
