# Gateway: the serving tier in front of a pool of pipeline replicas.
#
# The ROADMAP north star is heavy traffic from millions of users; until
# this subsystem, every client talked straight to ONE Pipeline actor,
# `process_frame` admitted without limit, and overload meant unbounded
# queue growth until the micro-batch scheduler drowned.  The Gateway
# closes that gap with the load-shedding / least-loaded-routing designs
# of datacenter inference frontends (Orca-style continuous-batching
# routers, Clockwork's SLO-aware admission):
#
#   admission   per-priority token buckets gate STREAM creation; an
#               over-budget or unplaceable stream gets a typed
#               `(overloaded ...)` reply, never silent queue growth
#   routing     power-of-two-choices over live load gauges picks the
#               least-loaded healthy replica; a stream PINS to its
#               replica for its lifetime (stateful elements, ordered
#               frames)
#   backpressure a bounded priority queue parks frames when the pinned
#               replica saturates; past the high-water mark the gateway
#               sends `(throttle stream rate)` so DataSources slow
#               generation (PipelineElement.throttle_frame_generation);
#               a full queue sheds the LOWEST-priority parked frame
#   failover    replica death (discovery remove, or the seeded
#               `replica_kill` fault point) migrates its streams to
#               another replica and replays every un-acknowledged frame
#               from the stream cursor -- zero lost frames, duplicate
#               responses deduped, so outputs match an unfaulted run
#               bit for bit
#
# Replicas come from two sources: `attach_replica(pipeline)` wires an
# in-process Pipeline directly (responses hand off as Python objects,
# no codec -- the bench/test fast path), and `discover(...)` watches
# the registrar through the shared ServicesCache, mirroring each
# replica's EC share (`inflight` / `queue_depth`, refreshed by
# Pipeline._update_stream_share and the periodic telemetry summary)
# through an ECConsumer whose `last_update` age gates trust in the
# view (a wedged replica's stale share must not keep attracting
# streams).
#
# The wire surface mirrors the Pipeline protocol (create_stream /
# process_frame / destroy_stream), so pointing an existing client at a
# gateway topic instead of a pipeline topic is a config change, not a
# code change.

from __future__ import annotations

import json
import time

from ..faults import create_injector, get_injector
from ..observe import GatewayTelemetry
from ..observe.trace import attach_trace_context, make_trace_context
from ..pipeline.pipeline import DEFAULT_GRACE_TIME
from ..pipeline.tensors import decode_frame_data, encode_frame_data
from ..runtime import Actor, Lease, RetainedElection, ServiceFilter
from ..runtime.service import PROTOCOL_PREFIX, SERVICE_PROTOCOL_PIPELINE
from ..utils import (
    epoch_now, generate, get_logger, parse, parse_float, parse_int)
from .journal import GatewayJournal, JournalPolicy
from .policy import AdmissionPolicy

__all__ = ["Gateway", "SERVICE_PROTOCOL_GATEWAY"]

_LOGGER = get_logger("gateway")

SERVICE_PROTOCOL_GATEWAY = f"{PROTOCOL_PREFIX}/gateway:0"
# completion-rate estimator: SLO shedding stays off until this many
# completions have been observed (a cold estimate would shed blindly)
_RATE_WINDOW = 64
_RATE_WARMUP = 8


class _LocalResponder:
    """queue_response shim handed to an in-process replica's stream:
    successful frames hand off to the gateway mailbox as live Python
    objects (no tensor codec on the fast path).  Error/drop releases
    ride the stream's topic_response instead -- the pipeline engine
    only notifies queue responders on success.

    Responses ride the CONTROL mailbox: under overload the `in`
    mailbox holds thousands of queued submissions, and a slot-freeing
    release parked behind them would starve every replica (measured:
    goodput collapsed to ~15% of capacity with FIFO ordering).  The
    actor layer's control-preempts-data rule is exactly this
    priority."""

    __slots__ = ("gateway",)

    def __init__(self, gateway):
        self.gateway = gateway

    def put(self, item) -> None:
        from ..runtime import ActorTopic
        stream, frame, outputs = item
        self.gateway.post_message("process_frame_response", [
            {"stream_id": stream.stream_id, "frame_id": frame.frame_id},
            outputs], actor_topic=ActorTopic.CONTROL)


class _Replica:
    __slots__ = ("topic_path", "name", "pipeline", "consumer", "cache",
                 "outstanding", "streams", "dead", "saturated",
                 "below_since", "routed", "draining", "warm", "role")

    def __init__(self, topic_path: str, name: str, pipeline=None,
                 consumer=None, cache=None, warm: bool = False,
                 role: str = "decode"):
        self.topic_path = topic_path
        self.name = name
        self.pipeline = pipeline      # local direct attach (else None)
        self.consumer = consumer      # ECConsumer for discovered replicas
        self.cache = cache if cache is not None else {}
        self.outstanding = 0          # gateway-routed frames in flight
        self.streams: set[str] = set()
        self.dead = False
        self.draining = False         # scale-down: no NEW placements
        self.warm = warm              # warm-started (hand-off + cache)
        self.role = role              # disagg pool: prefill | decode
        self.saturated = False
        self.below_since: float | None = None
        self.routed = 0

    def pool_role(self) -> str:
        """Which disagg pool this replica serves: the attach-time role
        for local replicas; for discovered ones the EC share's `role`
        key (published by prefill-pool pipelines), so pool membership
        rides the ordinary discovery plane."""
        if self.pipeline is not None or self.consumer is None:
            return self.role
        return str(self.cache.get("role") or self.role)

    def reported_inflight(self) -> int:
        """The replica's OWN load claim: live for local replicas, the
        EC share mirror for discovered ones."""
        if self.pipeline is not None:
            return int(self.pipeline.load()["inflight"])
        return parse_int(self.cache.get("inflight", 0), 0)

    def prefix_heads(self) -> set:
        """Chain-head digests this replica's prefix cache holds --
        live from the pipeline share for local replicas, the EC
        mirror for discovered ones (elements/ml.py publishes the
        comma-joined summary on change).  Empty when the replica runs
        without a prefix cache."""
        if self.pipeline is not None:
            raw = self.pipeline.share.get("prefix_heads", "")
        else:
            raw = self.cache.get("prefix_heads", "")
        return {head for head in str(raw or "").split(",") if head}

    def reported_queue_depth(self) -> int:
        if self.pipeline is not None:
            return int(self.pipeline.load()["queue_depth"])
        return parse_int(self.cache.get("queue_depth", 0), 0)

    def score(self) -> int:
        """Routing load: the gateway's instant view of what it routed,
        or the replica's own claim when other clients load it too --
        max, never sum (the gateway's frames appear in both)."""
        return max(self.outstanding, self.reported_inflight())

    def fresh(self, now: float, stale_after: float) -> bool:
        if self.consumer is None:
            return True   # local: the load read IS the live value
        last_update = self.consumer.last_update
        return (last_update is not None
                and (stale_after <= 0
                     or now - last_update <= stale_after))

    def note_load(self, now: float, policy: AdmissionPolicy) -> None:
        """Refresh the hysteresis state machine after an outstanding
        change: saturation latches at the cap and only clears after the
        replica sits at/below HALF the cap for `hysteresis` seconds --
        a flapping replica must not oscillate in and out of stream
        placement."""
        cap = policy.max_inflight
        if self.outstanding >= cap:
            self.saturated = True
            self.below_since = None
        elif self.saturated:
            if self.outstanding <= max(1, cap // 2):
                if self.below_since is None:
                    self.below_since = now
                elif now - self.below_since >= policy.hysteresis_s:
                    self.saturated = False
                    self.below_since = None
            else:
                self.below_since = None

    def placeable(self, now: float, policy: AdmissionPolicy) -> bool:
        self.note_load(now, policy)
        return (not self.dead
                and not self.draining
                and not self.saturated
                and self.fresh(now, policy.stale_after_s))

    def has_capacity(self, policy: AdmissionPolicy) -> bool:
        return not self.dead and self.outstanding < policy.max_inflight


class _GatewayStream:
    __slots__ = ("stream_id", "priority", "slo_ms", "parameters",
                 "grace_time", "replica", "queue_response",
                 "topic_response", "throttle", "inflight", "delivered",
                 "delivered_floor", "cursor", "parked", "throttled",
                 "lease", "prefill_created", "keeper", "traces",
                 "dispatch_s", "restore_hint", "tenant")

    def __init__(self, stream_id: str, priority: int, slo_ms: float,
                 parameters: dict, grace_time: float, replica: _Replica,
                 queue_response=None, topic_response=None, throttle=None):
        self.stream_id = stream_id
        self.priority = priority
        self.slo_ms = slo_ms
        self.parameters = parameters
        self.grace_time = grace_time
        self.replica = replica
        self.queue_response = queue_response
        self.topic_response = topic_response
        self.throttle = throttle      # local source rate-cap callable
        # frame_id -> [frame_data, submitted_s, seq]: retained until the
        # response arrives so replica death can replay from the cursor
        self.inflight: dict[int, list] = {}
        # exactly-once dedupe: every id <= delivered_floor has been
        # delivered (the CONTIGUOUS prefix collapses into one int -- the
        # journaled high-water mark), `delivered` holds the sparse ids
        # above it
        self.delivered: set[int] = set()
        self.delivered_floor = -1
        self.cursor = 0
        self.parked = 0               # this stream's parked-queue entries
        self.throttled = False
        self.lease: Lease | None = None
        # prefill replicas that already hold this stream (disagg hop 1
        # creates lazily on first dispatch to each prefill replica)
        self.prefill_created: set[str] = set()
        # checkpoint keeper name this stream's restore hints carry:
        # the gateway policy's keeper, or the journaled one after a
        # takeover -- "checkpoint locations ride the gateway journal"
        self.keeper: str | None = None
        # one-shot warm-restore hint for ADOPTED streams (cross-group
        # journal adoption rebuilds a stream with EMPTY inflight, so
        # _migrate_streams has no frame to attach the restore hint to):
        # the next dispatched frame carries it, then it clears --
        # the adopting decode replica restores the checkpointed KV and
        # re-decodes only the post-snapshot tail instead of
        # cold re-prefilling
        self.restore_hint: dict | None = None
        # multi-tenant admission: the tenant this stream declared (""
        # = untenanted), driving per-tenant buckets and SLO counters
        self.tenant: str = ""
        # fleet tracing (telemetry-gated; both stay empty with
        # telemetry off): the gateway-owned ROOT trace per in-flight
        # frame, and each frame's first-dispatch perf_counter stamp
        # (admit-wait span boundary + decode-stage decomposition)
        self.traces: dict[int, object] = {}
        self.dispatch_s: dict[int, float] = {}

    def is_delivered(self, frame_id: int) -> bool:
        return (frame_id <= self.delivered_floor
                or frame_id in self.delivered)


class Gateway(Actor):
    def __init__(self, process, name: str = "gateway", policy=None,
                 router_seed: int = 0, faults=None, telemetry: bool = True,
                 metrics_interval: float = 10.0, autoscale=None,
                 replica_factory=None, journal=None, ha=None,
                 disagg=None, checkpoint=None, federation=None,
                 prefix=None, autopilot=None):
        super().__init__(process, name, protocol=SERVICE_PROTOCOL_GATEWAY)
        # construction-time validation through the shared
        # directive-grammar core (analyze/grammar.py): a typo'd policy
        # fails HERE with the lint rule code, exactly as `aiko lint`
        # would report it offline -- never silently admits everything
        try:
            self.policy = AdmissionPolicy.parse(policy)
        except ValueError as error:
            code = ("AIKO404" if getattr(error, "kind", "") == "unknown"
                    else "AIKO403")
            raise ValueError(
                f"{code}: gateway admission policy rejected: "
                f"{error}") from None
        # prefill/decode disaggregation (serve/disagg.py): with a
        # disagg policy set, streams pin to the DECODE pool and every
        # dispatchable frame takes a prefill hop through the
        # least-loaded prefill replica first; the handoff rides the
        # frame data to the pinned decode replica, which adopts the
        # prompt's KV blocks instead of re-prefilling
        try:
            from .disagg import DisaggPolicy
            self.disagg = (DisaggPolicy.parse(disagg)
                           if disagg is not None else None)
            if self.disagg is not None and self.disagg.role is not None:
                raise ValueError(
                    "a gateway disagg spec must not pin role= (the "
                    "gateway fronts both pools)")
        except ValueError as error:
            code = ("AIKO404" if getattr(error, "kind", "") == "unknown"
                    else "AIKO408")
            raise ValueError(
                f"{code}: gateway disagg policy rejected: "
                f"{error}") from None
        # warm KV failover (decode/checkpoint.py): with a checkpoint
        # policy set, a dead decode replica's replayed frames carry a
        # RESTORE hint (the keeper name) so the survivor adopts each
        # stream's checkpointed decode state instead of re-prefilling,
        # and the replay wave is PACED at recovery_rate streams/s so
        # survivors' live decode is not convoyed by the recovery storm
        try:
            from ..decode.checkpoint import CheckpointPolicy
            self.checkpoint = (CheckpointPolicy.parse(checkpoint)
                               if checkpoint is not None else None)
            if self.checkpoint is not None:
                self.checkpoint.validate_gateway()
        except ValueError as error:
            code = ("AIKO404" if getattr(error, "kind", "") == "unknown"
                    else "AIKO409")
            raise ValueError(
                f"{code}: gateway checkpoint policy rejected: "
                f"{error}") from None
        # federated tier (serve/federation.py): with a federation spec
        # set, this gateway owns exactly the streams whose id hashes to
        # its group (rendezvous over the full group set) and sheds the
        # rest with the typed reason "wrong_group" -- a misrouted
        # client fails fast instead of splitting a stream across
        # groups.  None (the default) = single-group tier, behavior
        # identical to every pre-federation deployment
        try:
            from .federation import FederationPolicy
            self.federation = (FederationPolicy.parse(federation)
                               if federation is not None else None)
        except ValueError as error:
            code = ("AIKO404" if getattr(error, "kind", "") == "unknown"
                    else "AIKO410")
            raise ValueError(
                f"{code}: gateway federation policy rejected: "
                f"{error}") from None
        # prefix-affinity routing (decode/prefix.py): with a prefix
        # policy set, a hinted stream's placement biases the
        # power-of-two-choices sample toward replicas whose mirrored
        # chain-head summary already holds the stream's prefix
        # (score - affinity_weight), and -- when a checkpoint keeper
        # is ALSO configured -- streams carry the keeper name so a
        # cold replica pre-warms from the cross-replica prefix store.
        # None (or prefix_cache=off) = pre-prefix routing, bit for bit
        try:
            from ..decode.prefix import PrefixPolicy
            self.prefix = (PrefixPolicy.parse(prefix)
                           if prefix is not None else None)
            if self.prefix is not None:
                self.prefix.validate_gateway()
                if not self.prefix.enabled:
                    self.prefix = None
        except ValueError as error:
            code = ("AIKO404" if getattr(error, "kind", "") == "unknown"
                    else "AIKO411")
            raise ValueError(
                f"{code}: gateway prefix policy rejected: "
                f"{error}") from None
        # online SLO autopilot (serve/autopilot.py): with an autopilot
        # policy set, the gateway runs the observe -> tune -> apply
        # loop on a cadence -- live trace harvest, bounded deltas
        # through the live setters below, every apply write-ahead
        # journaled.  apply=off (the default) is a dry-run audit.
        # The attribute exists BEFORE the parse: stop() on a process
        # torn down after a rejected spec must find it
        self.autopilot = None
        try:
            from .autopilot import AutopilotPolicy
            self.autopilot_policy = (AutopilotPolicy.parse(autopilot)
                                     if autopilot is not None else None)
        except ValueError as error:
            code = ("AIKO404" if getattr(error, "kind", "") == "unknown"
                    else "AIKO412")
            raise ValueError(
                f"{code}: gateway autopilot policy rejected: "
                f"{error}") from None
        self.federation_group = None
        if self.federation is not None and self.federation.groups:
            self.federation_group = (self.federation.group
                                     or (str(ha) if ha else None) or name)
            if self.federation_group not in self.federation.groups:
                raise ValueError(
                    f"AIKO410: gateway federation policy rejected: this "
                    f"gateway's group {self.federation_group!r} (from "
                    f"ha/name) is not in groups="
                    f"{','.join(self.federation.groups)}; set group= "
                    f"explicitly")
        # stream_id -> {"ids": [frame ids], "hint": restore hint}:
        # failover replays deferred by recovery pacing -- in inflight,
        # neither dispatched nor parked.  The hint is FROZEN at
        # failover time so the paced wave keeps _restore_hint's
        # drain/prefill-pool guards
        self._paced_frames: dict[str, dict] = {}
        # region-aware degradation (serve/federation.py): federation
        # groups known DEAD (a severed region, a lost HA pair).
        # Placement audit and journal adoption both consult this set,
        # so a lost region's streams remap onto the survivors (each
        # survivor adopting exactly its rendezvous share) while every
        # other stream keeps its pin
        self._lost_groups: set[str] = set()
        # lost group -> its (foreign) journal mirror, warmed at
        # note_group_lost so the retained backend has replayed by
        # adoption time
        self._foreign_journals: dict = {}
        self.replicas: dict[str, _Replica] = {}
        self.streams: dict[str, _GatewayStream] = {}
        # parked frames: (priority, seq, stream_id, frame_id), dispatched
        # min-first (highest priority, oldest), shed max-first.  Bounded
        # by policy.queue_capacity, so linear scans stay cheap
        self._parked: list[tuple] = []
        self._depth_priorities: set[int] = set()
        self._seq = 0
        import random
        self._rng = random.Random(router_seed)
        self.faults = (create_injector(faults) if isinstance(faults, str)
                       else (faults if faults is not None
                             else get_injector()))
        self.telemetry = GatewayTelemetry(
            self, enabled=telemetry, interval=metrics_interval)
        self._completions: list[float] = []
        self._throttle_on = False
        self._services_cache = None
        self._discovery_handler = None
        self.autoscaler = None
        self.autopilot = None
        # -- crash consistency (serve/journal.py): a journaled gateway
        # rebuilds pins/cursors/dedupe floors after a crash; an HA
        # group member additionally runs the registrar-style retained
        # election and takes over when the primary's LWT fires
        self.ha_group = str(ha) if ha else None
        if self.ha_group and journal is None:
            journal = ""          # HA implies journaled (retained mirror)
        try:
            self.journal_policy = (JournalPolicy.parse(journal)
                                   if journal is not None else None)
        except ValueError as error:
            code = ("AIKO404" if getattr(error, "kind", "") == "unknown"
                    else "AIKO407")
            raise ValueError(
                f"{code}: gateway journal policy rejected: "
                f"{error}") from None
        self.journal: GatewayJournal | None = None
        self.election: RetainedElection | None = None
        self.role = "single"
        self._journal_dirty: set[str] = set()
        self._journal_forgotten: set[str] = set()
        # ids THIS incarnation has journaled whose forget has not yet
        # flushed: self-adoption must never treat them as crash
        # orphans (under churn, the replay_timeout recovery can race
        # the forget flush and resurrect just-destroyed streams)
        self._journal_session: set[str] = set()
        self._buckets_dirty = False
        self._journal_timer = None
        self._takeover_started: float | None = None
        if self.journal_policy is not None:
            root = (f"{process.namespace}/gateway/"
                    f"{self.ha_group or name}/journal")
            self.journal = GatewayJournal(self.journal_policy, process,
                                          root)
        self.share.update({
            "policy": self.policy.spec,
            "replica_count": 0,
            "stream_count": 0,
            "role": self.role,
        })
        if self.federation_group is not None:
            # discovery surface: clients resolving the tier can read
            # each gateway's group off its EC share
            self.share["federation_group"] = self.federation_group
            self.share["federation_groups"] = ",".join(
                self.federation.groups)
        self._ha_was_secondary = False
        if self.ha_group:
            self.role = "standby"

            def note_state(state):
                if state == "secondary":
                    self._ha_was_secondary = True

            self.election = RetainedElection(
                process, f"{process.namespace}/gateway/{self.ha_group}",
                self.topic_path, announce=self._announce_primary,
                search_timeout=self.journal_policy.search_timeout_s,
                on_promote=self._ha_promote, on_demote=self._ha_demote,
                on_state=note_state)
            self.share["role"] = self.role
        elif self.journal is not None:
            # restarted single gateway: adopt whatever the previous
            # incarnation journaled, once replicas have had
            # `replay_timeout` to (re)attach or be rediscovered
            self._start_journal_tick()
            self.post_message_later(
                "_journal_recover", [],
                self.journal_policy.replay_timeout_s)
        if autoscale is not None:
            self.enable_autoscale(autoscale, replica_factory)
        if self.autopilot_policy is not None:
            from .autopilot import AutoPilot
            self.autopilot = AutoPilot(self, self.autopilot_policy)
            if not self.ha_group:
                # HA members arm the loop on promote only: a standby
                # must never tune a fleet it does not own
                self.autopilot.start()

    def _post_message(self, actor_topic: str, command: str,
                      parameters) -> None:
        # replica releases preempt queued client submissions (see
        # _LocalResponder): without this, an overload backlog in the
        # `in` mailbox starves every replica of slot-freeing responses
        if command in ("process_frame_response", "_release_dead_letter",
                       "_replica_lost", "_autoscale_ready",
                       "_paced_replay"):
            # _paced_replay rides CONTROL too: recovery waves fire
            # exactly when the `in` mailbox is deepest, and a wave
            # parked behind queued submissions would defeat the pacing
            from ..runtime import ActorTopic
            actor_topic = ActorTopic.CONTROL
        super()._post_message(actor_topic, command, parameters)

    # -- replica pool ------------------------------------------------------

    def attach_replica(self, pipeline, warm: bool = False,
                       role: str | None = None) -> None:
        """Wire an in-process Pipeline as a replica (the bench/test fast
        path: frame data and responses hand off as live objects).
        `warm` marks a warm-started replica (sibling weight hand-off +
        persistent compile cache) for the pool telemetry; `role` pins
        the disagg pool (defaults to the pipeline's own `role` share
        key -- set by a `disagg: "role=prefill"` definition parameter
        -- else the decode pool)."""
        if role is None:
            role = str(pipeline.share.get("role") or "decode")
        replica = _Replica(pipeline.topic_path, pipeline.name,
                          pipeline=pipeline, warm=warm, role=role)
        self._add_replica(replica)

    # -- elastic fleet (serve/autoscale.py drives these) -------------------

    def enable_autoscale(self, policy, factory=None) -> None:
        """Attach the load-driven autoscaler: `policy` parses through
        the shared directive grammar (AIKO406 on bad values, AIKO404 on
        unknown directives, exactly like the admission policy), and
        `factory` supplies/retires replicas (serve/autoscale.py
        factories, or anything matching their spawn/retire shape)."""
        from .autoscale import AutoScaler
        if self.autoscaler is not None:
            raise ValueError(f"{self.name}: autoscaler already enabled")
        self.autoscaler = AutoScaler(self, policy, factory)

    def _autoscale_ready(self, handle, info=None) -> None:
        """Mailbox continuation for a finished spawn (the factory
        thread must never touch gateway state directly).  Rides the
        CONTROL mailbox: scale-ups happen exactly when the `in` mailbox
        is drowning in queued submissions, and an attach parked behind
        them would arrive after the overload it was meant to absorb."""
        if self.autoscaler is not None:
            self.autoscaler.spawn_finished(handle, info or {})

    # -- crash consistency: journal + hot-standby election ------------------
    #
    # The journal records ROUTING state (pins, cursors, delivered
    # floors, bucket levels), never frame payloads: after a takeover
    # the client replays its un-acked frame DATA and the journaled
    # dedupe floor guarantees exactly-once, exactly as replica
    # failover's cursor replay does.  Batched per `interval` tick --
    # the crash window is one tick, and anything younger is covered by
    # the client-side replay.

    def _announce_primary(self) -> None:
        self.process.publish(
            f"{self.process.namespace}/gateway/{self.ha_group}",
            generate("primary", ["found", self.topic_path, "1",
                                 repr(self.election.time_started)]),
            retain=True)

    def _ha_promote(self) -> None:
        """Election won (cold start, or the primary's LWT fired): adopt
        the journal, re-pin every live journaled stream through the
        shared _migrate_streams path, start journaling."""
        was_standby = self.role == "standby"
        self.role = "primary"
        self.share["role"] = self.role
        if self.ec_producer is not None:
            self.ec_producer.update("role", self.role)
        started = time.monotonic()
        adopted = self._adopt_journal()
        self._start_journal_tick()
        if self.autopilot is not None:
            self.autopilot.start()
        takeover_ms = (time.monotonic() - started) * 1000.0
        if was_standby and self._ha_was_secondary:
            # promotion after standing by = a real takeover (a cold
            # start that never saw a primary is just a boot); the
            # histogram records promote -> streams re-pinned
            self.telemetry.record_takeover(takeover_ms)
        _LOGGER.warning(
            "%s: promoted to HA primary (%s); adopted %d journaled "
            "stream(s) in %.1f ms", self.name, self.ha_group, adopted,
            takeover_ms)
        self._update_share()

    def _ha_demote(self) -> None:
        """An older primary exists (split-brain resolution): stop
        journaling; existing streams keep serving but new clients will
        follow the retained announcement to the real primary."""
        self.role = "standby"
        self.share["role"] = self.role
        if self.ec_producer is not None:
            self.ec_producer.update("role", self.role)
        self._stop_journal_tick()
        if self.autopilot is not None:
            self.autopilot.stop()
        _LOGGER.warning("%s: demoted to HA standby (%s)", self.name,
                        self.ha_group)

    def _start_journal_tick(self) -> None:
        if self.journal is None or self._journal_timer is not None:
            return
        interval = self.journal_policy.interval_s
        if interval > 0:
            self._journal_timer = self._journal_tick
            self.process.event.add_timer_handler(self._journal_timer,
                                                 interval)
        else:
            # interval=0: synchronous journaling (every mark flushes) --
            # the deterministic mode chaos tests pin the crash window
            # shut with
            self._journal_timer = None

    def _stop_journal_tick(self) -> None:
        if self._journal_timer is not None:
            self.process.event.remove_timer_handler(self._journal_timer)
            self._journal_timer = None

    def _mark_journal(self, stream: _GatewayStream) -> None:
        if self.journal is None or self.role == "standby":
            return
        self._journal_dirty.add(stream.stream_id)
        self._journal_session.add(stream.stream_id)
        if self.journal_policy.interval_s <= 0:
            self._journal_tick()

    def _journal_forget(self, stream_id: str) -> None:
        if self.journal is None or self.role == "standby":
            return
        self._journal_dirty.discard(stream_id)
        self._journal_forgotten.add(stream_id)
        if self.journal_policy.interval_s <= 0:
            self._journal_tick()

    def _journal_tick(self) -> None:
        """One batched flush: serialize every dirty stream still
        alive, delete the forgotten, refresh bucket levels."""
        if self.journal is None or self.role == "standby":
            return
        if (not self._journal_dirty and not self._journal_forgotten
                and not self._buckets_dirty):
            return
        records = {}
        for stream_id in list(self._journal_dirty):
            stream = self.streams.get(stream_id)
            if stream is not None:
                records[stream_id] = self._journal_record(stream)
        forgotten = self._journal_forgotten
        buckets = self._bucket_levels() if self._buckets_dirty else None
        self._journal_dirty = set()
        self._journal_forgotten = set()
        self._buckets_dirty = False
        written = self.journal.write(records, forgotten, buckets)
        # flushed forgets are really gone from the backend -- their ids
        # can no longer be mistaken for crash orphans, so the session
        # set stays bounded by live + pending-forget streams
        self._journal_session.difference_update(forgotten)
        if written:
            self.telemetry.journal_appends.inc(written)
        self.telemetry.journal_entries.set(self.journal.entry_count())

    def journal_flush(self) -> None:
        """Force a journal tick NOW (deterministic tests/benches pin
        the crash window shut before injecting a kill)."""
        self._journal_tick()

    def _journal_record(self, stream: _GatewayStream) -> dict:
        parameters = stream.parameters
        try:
            json.dumps(parameters)
        except (TypeError, ValueError):
            # non-JSON-able local parameters: journal the stream's
            # identity/cursor anyway (the pin survives; the new primary
            # serves with replica-side parameters)
            parameters = {}
        record = {
            "stream_id": stream.stream_id,
            "priority": stream.priority,
            "slo_ms": stream.slo_ms,
            "parameters": parameters,
            "grace_time": stream.grace_time,
            "topic_response": stream.topic_response or "",
            "replica": (stream.replica.topic_path
                        if stream.replica is not None else ""),
            "cursor": stream.cursor,
            "delivered_upto": stream.delivered_floor,
            "expires_at": epoch_now() + max(stream.grace_time, 0.0),
        }
        if stream.keeper:
            # checkpoint LOCATION rides the journal: a promoted
            # standby's failovers restore from the same keeper
            record["keeper"] = stream.keeper
        return record

    def _bucket_levels(self) -> dict:
        return {str(priority): round(bucket.tokens, 6)
                for priority, bucket
                in list(self.policy.buckets.items())}

    def _journal_recover(self) -> None:
        """Mailbox continuation of the restart path (non-HA journaled
        gateway): adopt after `replay_timeout` gave replicas time to
        re-attach/rediscover."""
        if self.role == "single":
            adopted = self._adopt_journal()
            if adopted:
                _LOGGER.warning(
                    "%s: restart recovery adopted %d journaled "
                    "stream(s)", self.name, adopted)

    def _journal_recover_retry(self) -> None:
        """Deferred adoption retry: the pool was empty at promote/
        restart time (full-outage cold start)."""
        if self.journal is not None and self.role != "standby":
            adopted = self._adopt_journal()
            if adopted:
                _LOGGER.warning(
                    "%s: deferred recovery adopted %d journaled "
                    "stream(s)", self.name, adopted)

    def recover_now(self) -> int:
        """Synchronous journal adoption (deterministic tests)."""
        return self._adopt_journal()

    def _adopt_journal(self) -> int:
        """Rebuild gateway state from the journal: recreate each live
        stream (cursor + dedupe floor restored), group them under
        per-old-replica ghost pins, then run the SHARED zero-loss
        migration path -- destroy on the old replica (fencing a
        survivor that still serves the stream), re-pin on the current
        pool, replay handled by client resubmission against the
        restored floor.  Expired entries are dropped, never re-pinned
        (journal.replay purges them)."""
        if self.journal is None:
            return 0
        records, buckets, dropped = self.journal.replay()
        if self._journal_session:
            # an entry THIS incarnation wrote is not a crash orphan:
            # it is either a live stream (skipped below anyway) or a
            # just-destroyed one whose forget has not flushed yet --
            # adopting it would resurrect a deliberately torn-down
            # stream
            records = [record for record in records
                       if str(record.get("stream_id", ""))
                       not in self._journal_session]
        if dropped:
            self.telemetry.journal_dropped_stale.inc(dropped)
        if self.autopilot is not None:
            # autopilot config deltas replay FIRST (and on every
            # adoption pass -- absolute values make re-application
            # idempotent, and the deferred empty-pool retry below needs
            # the second pass to reach late-attaching replicas): the
            # adopted streams must land on the exact knob settings the
            # previous primary had applied
            self.autopilot.adopt_journal()
        if records and not any(not replica.dead for replica
                               in list(self.replicas.values())):
            # cold start after a FULL outage: the pool is empty because
            # rediscovery is still in flight, and adopting now would
            # hard-fail (and forget) every journaled stream.  Wait one
            # replay_timeout and try again -- record expiry bounds the
            # retries, so a fleet that never comes back converges to an
            # empty journal instead of looping forever
            self._adopt_buckets(buckets)
            _LOGGER.warning(
                "%s: %d journaled stream(s) but no live replicas yet; "
                "deferring adoption", self.name, len(records))
            self.post_message_later(
                "_journal_recover_retry", [],
                max(self.journal_policy.replay_timeout_s, 0.05))
            return 0
        adopted = self._adopt_records(records)
        self._adopt_buckets(buckets)
        if adopted:
            self.telemetry.journal_replayed.inc(adopted)
            self._update_share()
            self._journal_tick()
        return adopted

    def _adopt_records(self, records) -> int:
        """The shared record-adoption core: rebuild each journaled
        stream (cursor + dedupe floor restored), group them under
        per-old-replica ghost pins, then run the zero-loss migration
        path.  Used by _adopt_journal (own crash/takeover) and
        _adopt_group_ready (a LOST federation group's streams)."""
        ghosts: dict[str, _Replica] = {}
        adopted = 0
        for record in records:
            stream_id = str(record.get("stream_id", ""))
            if not stream_id or stream_id in self.streams:
                continue
            old_topic = str(record.get("replica", "") or "")
            ghost = ghosts.get(old_topic)
            if ghost is None:
                ghost = ghosts[old_topic] = _Replica(
                    old_topic, f"journal:{old_topic or 'unpinned'}")
                ghost.dead = True
                live = self.replicas.get(old_topic)
                if live is not None and live.pipeline is not None:
                    # the old pin is a DIRECT-attached survivor: route
                    # the fencing destroy through the same mailbox the
                    # re-pin create uses, so the two cannot reorder
                    ghost.pipeline = live.pipeline
            try:
                grace_time = float(record.get("grace_time",
                                              DEFAULT_GRACE_TIME))
            except (TypeError, ValueError):
                grace_time = DEFAULT_GRACE_TIME
            parameters = dict(record.get("parameters") or {})
            stream = _GatewayStream(
                stream_id, parse_int(record.get("priority", 0), 0),
                parse_float(record.get("slo_ms", 0.0), 0.0),
                parameters, grace_time, ghost,
                topic_response=(record.get("topic_response") or None))
            stream.tenant = str(parameters.get("tenant", "") or "")
            stream.cursor = parse_int(record.get("cursor", 0), 0)
            stream.delivered_floor = parse_int(
                record.get("delivered_upto", -1), -1)
            stream.keeper = (str(record.get("keeper"))
                             if record.get("keeper") else
                             (self.checkpoint.keeper
                              if self.checkpoint is not None
                              and self.checkpoint.keeper else None))
            stream.lease = Lease(
                self.process.event, grace_time, stream_id,
                lease_expired_handler=self._stream_lease_expired,
                jitter=self._lease_jitter(stream_id))
            self.streams[stream_id] = stream
            ghost.streams.add(stream_id)
            adopted += 1
            self._journal_dirty.add(stream_id)  # re-journal the new pin
        for ghost in ghosts.values():
            self._migrate_streams(ghost)
        return adopted

    # -- region-aware degradation (cross-group adoption) -------------------

    def note_group_lost(self, group) -> None:
        """Another federation group is DEAD (its region severed, its
        HA pair gone).  Mark it lost -- placement audit now routes its
        streams here when the rendezvous says so -- and warm the lost
        group's journal mirror so that, one replay_timeout later,
        _adopt_group_ready can rebuild OUR share of its streams with
        warm-restore hints.  Composes journal failover + warm
        checkpoints + federation: the journal names each stream's
        keeper, the keeper holds its KV snapshot, and the rendezvous
        decides which survivor adopts it."""
        group = str(group)
        if (self.federation_group is None
                or group == self.federation_group
                or group in self._lost_groups):
            return
        if group not in self.federation.groups:
            _LOGGER.warning("%s: note_group_lost(%s): unknown group",
                            self.name, group)
            return
        self._lost_groups.add(group)
        self.share["federation_lost"] = ",".join(sorted(self._lost_groups))
        _LOGGER.warning("%s: federation group %s marked lost",
                        self.name, group)
        if self.journal_policy is None:
            # no journal machinery: placement still remaps NEW streams,
            # but the lost group's live streams cannot be adopted
            self._update_share()
            return
        if group not in self._foreign_journals:
            # constructing the retained-backend journal SUBSCRIBES to
            # the lost group's journal root now, so its mirror has
            # warmed by the time adoption fires (sqlite backends read
            # the shared path directly and need no warm-up)
            root = f"{self.process.namespace}/gateway/{group}/journal"
            self._foreign_journals[group] = GatewayJournal(
                self.journal_policy, self.process, root)
        self.post_message_later(
            "_adopt_group_ready", [group],
            max(self.journal_policy.replay_timeout_s, 0.05))
        self._update_share()

    def note_group_healed(self, group) -> None:
        """The lost group is back: stop treating it as dead for
        placement.  Streams the survivors already adopted STAY adopted
        (their records were purged from the healed group's journal at
        adoption, so it cannot re-pin them); only un-adopted streams
        and new admissions flow back."""
        group = str(group)
        if group not in self._lost_groups:
            return
        self._lost_groups.discard(group)
        self.share["federation_lost"] = ",".join(sorted(self._lost_groups))
        journal = self._foreign_journals.pop(group, None)
        if journal is not None:
            journal.stop()
        _LOGGER.warning("%s: federation group %s healed",
                        self.name, group)
        self._update_share()

    def adopt_group_now(self, group) -> int:
        """Synchronous cross-group adoption (deterministic tests: the
        caller drained the broker, so the foreign mirror is warm)."""
        return self._adopt_group_ready(group)

    def _adopt_group_ready(self, group) -> int:
        """Mailbox continuation of note_group_lost: replay the lost
        group's journal and adopt exactly OUR rendezvous share of its
        live streams -- every survivor runs this same filter, so each
        stream is adopted exactly once, by the group the region-aware
        placement law names.  Adopted records are purged from the
        foreign journal so a healed group cannot re-pin them."""
        group = str(group)
        if group not in self._lost_groups:
            return 0                  # healed before adoption fired
        journal = self._foreign_journals.get(group)
        if journal is None or self.federation is None:
            return 0
        records, _buckets, dropped = journal.replay()
        if dropped:
            self.telemetry.journal_dropped_stale.inc(dropped)
        mine = []
        for record in records:
            stream_id = str(record.get("stream_id", ""))
            if not stream_id or stream_id in self.streams:
                continue
            parameters = record.get("parameters") or {}
            region = (str(parameters["region"])
                      if isinstance(parameters, dict)
                      and parameters.get("region") is not None else None)
            try:
                owner = self.federation.owner_of(
                    stream_id, region=region, lost=self._lost_groups)
            except ValueError:
                continue
            if owner == self.federation_group:
                mine.append(record)
        if not mine:
            return 0
        if not any(not replica.dead
                   for replica in list(self.replicas.values())):
            # the pool is empty (the outage took our replicas too):
            # retry like the cold-start path; record expiry bounds it
            self.post_message_later(
                "_adopt_group_ready", [group],
                max(self.journal_policy.replay_timeout_s, 0.05))
            return 0
        adopted = self._adopt_records(mine)
        if adopted:
            self.telemetry.region_migrations.inc(adopted)
            self._update_share()
            self._journal_tick()     # the new pins ride OUR journal...
            journal.write({}, [str(record.get("stream_id"))
                               for record in mine])
            _LOGGER.warning(
                "%s: adopted %d stream(s) from lost group %s",
                self.name, adopted, group)
        return adopted

    def _adopt_buckets(self, levels: dict) -> None:
        """Restore admission-bucket token levels: a rate-limited client
        must not refill its budget by crashing the gateway."""
        for key, tokens in (levels or {}).items():
            bucket = self.policy.buckets.get(parse_int(key, -1))
            if bucket is None:
                continue
            bucket.tokens = min(bucket.burst,
                                max(0.0, parse_float(tokens, 0.0)))
            bucket.updated = None

    def discover(self, service_filter: ServiceFilter = None,
                 **filter_kwargs) -> None:
        """Watch the registrar (via the process's shared ServicesCache)
        for pipeline services matching `service_filter`; matches become
        replicas, removals trigger failover.  Each discovered replica's
        EC share is mirrored through an ECConsumer -- its `inflight` /
        `queue_depth` keys are the load gauges routing reads, and the
        mirror's age gates trust (policy `stale_after`)."""
        from ..runtime.share import services_cache_create_singleton
        if service_filter is None:
            filter_kwargs.setdefault(
                "protocol", SERVICE_PROTOCOL_PIPELINE)
            service_filter = ServiceFilter(**filter_kwargs)
        if self._services_cache is None:
            self._services_cache = services_cache_create_singleton(
                self.process)

        def handler(command, fields):
            if command == "add":
                self._replica_discovered(fields)
            elif command == "remove":
                self.post_message("_replica_lost", [fields.topic_path,
                                                    "discovery_remove"])

        self._discovery_handler = handler
        self._services_cache.add_handler(handler, service_filter)

    def _replica_discovered(self, fields) -> None:
        if fields.topic_path in self.replicas:
            return
        from ..runtime.share import ECConsumer
        cache: dict = {}
        consumer = ECConsumer(self.process, cache, fields.topic_path)
        replica = _Replica(fields.topic_path, fields.name,
                          consumer=consumer, cache=cache)
        # liveness watch on the replica's PROCESS state topic: the LWT
        # "(absent)" reaches us directly, registrar or no registrar.
        # Discovery-remove alone has a hole the chaos harness exposed:
        # a replica that dies DURING a registrar failover never
        # re-registered with the new primary, so no remove ever fires
        # -- its pinned streams would hang until stale_after.  The
        # retained "(absent)" closes it (a late subscriber still sees
        # the death).
        self.process.add_message_handler(
            self._replica_state_handler,
            self._replica_state_topic(fields.topic_path))
        self._add_replica(replica)

    @staticmethod
    def _replica_state_topic(topic_path: str) -> str:
        """{ns}/{host}/{pid}/{service_id} -> the owning process's
        liveness topic {ns}/{host}/{pid}/0/state."""
        return f"{topic_path.rsplit('/', 1)[0]}/0/state"

    def _replica_state_handler(self, topic: str, payload: str) -> None:
        try:
            command, _ = parse(payload)
        except ValueError:
            return
        if command != "absent":
            return
        process_root = topic.rsplit("/0/state", 1)[0]
        for topic_path, replica in list(self.replicas.items()):
            if (replica.consumer is not None
                    and topic_path.rsplit("/", 1)[0] == process_root):
                self.post_message("_replica_lost",
                                  [topic_path, "process_absent"])

    def _add_replica(self, replica: _Replica) -> None:
        self.replicas[replica.topic_path] = replica
        # PR 3 reuse: a replica's dead-letter topic is the release path
        # for frames it dropped/errored -- the gateway frees the slot
        # instead of waiting out a deadline
        self.process.add_message_handler(
            self._dead_letter_handler,
            f"{replica.topic_path}/dead_letter")
        if self.autoscaler is not None:
            # closes a pending discovered spawn's time-to-healthy clock
            self.autoscaler.note_replica_added(replica)
        self._update_share()
        _LOGGER.info("%s: replica %s (%s) joined", self.name,
                     replica.name, replica.topic_path)

    def _replica_lost(self, topic_path, reason) -> None:
        replica = self.replicas.get(str(topic_path))
        if replica is not None:
            self._replica_dead(replica, str(reason))

    def _replica_dead(self, replica: _Replica, reason: str) -> None:
        """Replica death: fence it (destroy its streams so a zombie
        stops computing), then migrate every pinned stream to another
        replica and replay the un-acknowledged frames from the stream
        cursor.  Frames the zombie already answered are deduped by the
        per-stream `delivered` set, so clients observe exactly-once.

        Only ever runs as a mailbox continuation (_replica_lost): an
        injected replica_kill marks the replica dead inline but DEFERS
        this cleanup, so it never reenters a dispatch or drain loop
        mid-iteration.  Removal from self.replicas is the
        exactly-once latch (replica.dead alone is set early by the
        fault path)."""
        if self.replicas.pop(replica.topic_path, None) is None:
            return  # already failed over (e.g. kill then discovery remove)
        replica.dead = True
        self._detach_replica(replica)
        self.telemetry.replica_deaths.inc()
        _LOGGER.warning("%s: replica %s died (%s); failing over %d "
                        "streams", self.name, replica.name, reason,
                        len(replica.streams))
        self._migrate_streams(replica)
        self._recover_prefill_frames(replica.topic_path)
        self._update_share()
        # frames that parked while the replica was dying (dispatch saw
        # replica.dead before this cleanup ran) have no response left to
        # trigger a drain -- kick it now that streams are re-pinned
        self._drain_parked()

    def drain_replica(self, topic_path: str,
                      reason: str = "scale_down"):
        """Graceful retirement (the autoscaler's low-watermark path):
        leave the pool, stop attracting placements, and re-pin every
        pinned stream through the SAME zero-loss migration the death
        path uses -- destroy on the old replica, replay un-acked frames
        from the stream cursor on the new one, duplicates deduped.  The
        replica object is returned so the caller can retire the backing
        process after its in-flight responses settle; returns None when
        the topic is not in the pool."""
        replica = self.replicas.pop(str(topic_path), None)
        if replica is None:
            return None
        replica.draining = True
        self._detach_replica(replica)
        _LOGGER.info("%s: draining replica %s (%s); migrating %d "
                     "streams", self.name, replica.name, reason,
                     len(replica.streams))
        self._migrate_streams(replica)
        self._recover_prefill_frames(replica.topic_path,
                                     redispatch=False)
        self._update_share()
        self._drain_parked()
        return replica

    def _detach_replica(self, replica: _Replica) -> None:
        self.process.remove_message_handler(
            self._dead_letter_handler,
            f"{replica.topic_path}/dead_letter")
        if replica.consumer is not None:
            self.process.remove_message_handler(
                self._replica_state_handler,
                self._replica_state_topic(replica.topic_path))
            replica.consumer.terminate()

    def _migrate_streams(self, replica: _Replica) -> None:
        """Re-pin every stream pinned to `replica` and replay its
        un-acknowledged frames -- the zero-loss path shared by failover
        (replica death) and drain (scale-down).  The replica must
        already be out of self.replicas so placement cannot choose it.

        Warm failover (decode/checkpoint.py): when a checkpoint keeper
        is known, each replayed frame carries a RESTORE hint so the
        new replica adopts the stream's checkpointed decode state
        instead of re-prefilling.  Recovery-storm pacing: past the
        first `recovery_rate`-sized wave, a stream's replay defers to
        a scheduled `_paced_replay` at 1/recovery_rate spacing -- the
        survivors' LIVE decode slots keep their cadence while the
        re-admission wave (and its cold re-prefill fallbacks, bounded
        per tick by the replicas' chunked prefill) trickles in."""
        for stream_id in list(replica.streams):
            self._send_destroy(replica, stream_id)
        replay_start = time.perf_counter()
        replayed_frames = 0
        now = time.monotonic()
        # pacing protects survivors from a CRASH recovery storm; a
        # graceful drain migrates at full speed (nothing crashed, the
        # drained replica's work is finishing, survivors were sized
        # for the load) -- mirroring _restore_hint's drain bypass
        rate = (self.checkpoint.recovery_rate
                if self.checkpoint is not None
                and not replica.draining else 0.0)
        immediate = max(int(rate), 1)
        migrated = 0
        paced_streams = 0
        paced_frames = 0
        for stream_id in list(replica.streams):
            replica.streams.discard(stream_id)
            stream = self.streams.get(stream_id)
            if stream is None:
                continue
            # placement preference order, but failover NEVER fails a
            # stream while ANY live replica exists: a survivor that is
            # momentarily saturated (or stale) still gets the stream
            # pinned -- its frames park and drain as slots free, which
            # is exactly what the bounded queue is for.  Only an empty
            # pool hard-fails
            target = self._place(now) or self._any_replica()
            if target is None:
                self._fail_stream(stream, "no_replica_for_failover")
                continue
            self.telemetry.failovers.inc()
            stream.replica = target
            target.streams.add(stream_id)
            self._mark_journal(stream)   # the pin moved
            first = (min(stream.inflight) if stream.inflight
                     else stream.cursor)
            self._send_create(target, stream, first_frame_id=first)
            hint = self._restore_hint(stream, replica)
            # replay in frame order; capacity overflow parks (original
            # seq keeps the parked entries draining in order).  Frames
            # that were still PARKED at death are already queued -- they
            # drain to the new replica through the re-pin above
            parked_ids = {item[3] for item in list(self._parked)
                          if item[2] == stream_id}
            already_paced = stream_id in self._paced_frames
            replay_ids = []
            for frame_id in sorted(stream.inflight):
                if frame_id in parked_ids:
                    continue
                entry = stream.inflight[frame_id]
                if len(entry) > 3:
                    # mid-prefill-hop on a LIVE prefill replica: its
                    # response re-dispatches through _prefill_done to
                    # the NEW pin -- replaying here would double-send
                    continue
                replay_ids.append(frame_id)
            if already_paced:
                # a SECOND failover while this stream's replay wave is
                # still scheduled: MERGE the new replay ids (frames
                # dispatched after the first failover) into the
                # pending wave -- _paced_replay reads stream.replica
                # at fire time, so everything lands on the new pin;
                # replaying here too would double-dispatch
                pending = self._paced_frames[stream_id]
                pending["ids"] = sorted(set(pending["ids"])
                                        | set(replay_ids))
                pending["hint"] = hint
                continue
            if not replay_ids and hint is not None:
                # nothing in flight to carry the hint (adopted-journal
                # streams rebuild with EMPTY inflight): arm the
                # one-shot stream hint instead, so the next dispatched
                # frame -- the client's resubmission against the
                # restored dedupe floor -- warm-restores on the new
                # replica (see _send_frame)
                stream.restore_hint = hint
            migrated += 1
            if rate > 0 and migrated > immediate and replay_ids:
                self._paced_frames[stream_id] = {"ids": replay_ids,
                                                 "hint": hint}
                self.telemetry.recovery_paced.inc()
                self.telemetry.recovery_paced_pending.set(
                    len(self._paced_frames))
                paced_streams += 1
                paced_frames += len(replay_ids)
                self.post_message_later(
                    "_paced_replay", [stream_id],
                    (migrated - immediate) / rate)
                continue
            replayed_frames += len(replay_ids)
            self._replay_frames(stream, replay_ids, hint)
        if migrated:
            # failover replay wave on the merged fleet timeline: how
            # long re-pinning + replaying this replica's streams took
            # (paced streams were re-pinned here but replay in their
            # own scheduled paced_replay: waves)
            self.telemetry.record_replay(
                time.perf_counter() - replay_start,
                streams=migrated - paced_streams,
                frames=replayed_frames,
                paced_streams=paced_streams,
                paced_frames=paced_frames)

    def _restore_hint(self, stream: _GatewayStream,
                      dead: _Replica) -> dict | None:
        """The warm-failover hint a replayed frame carries: the keeper
        name the new DECODE replica restores the stream's checkpointed
        slots from.  None (cold replay) when no keeper is known, when
        the dead replica was a prefill-pool member (it held no decode
        state), or on a graceful drain's own migration (the drained
        replica finished its work; there is nothing to restore)."""
        keeper = stream.keeper or (
            self.checkpoint.keeper if self.checkpoint is not None
            else None)
        if not keeper or dead.draining:
            return None
        if dead.pool_role() == "prefill":
            return None
        return {"keeper": keeper}

    def _replay_frames(self, stream: _GatewayStream, frame_ids,
                       hint: dict | None) -> None:
        target = stream.replica
        for frame_id in frame_ids:
            entry = stream.inflight.get(frame_id)
            if entry is None or stream.is_delivered(frame_id):
                continue
            if (target is not None
                    and target.has_capacity(self.policy)
                    and stream.parked == 0):
                data = None
                if hint is not None:
                    data = dict(entry[0])
                    restore = dict(hint)
                    trace = stream.traces.get(frame_id)
                    if trace is not None:
                        # the restore HINT carries the trace context
                        # too: the survivor's warm restore parents
                        # under the frame's gateway root even though
                        # the hint was frozen at failover time
                        restore["trace_context"] = make_trace_context(
                            trace)
                    data["restore"] = restore
                self._send_frame(target, stream, frame_id, entry,
                                 data=data)
            else:
                # parked frames replay the ORIGINAL data when they
                # drain (the keeper snapshot may expire while parked):
                # degraded to a re-prefill, never lost
                self._park(stream, frame_id, entry[2])

    def _paced_replay(self, stream_id) -> None:
        """Scheduled continuation of a paced failover wave: dispatch
        one migrated stream's replayed frames now.  Reads the CURRENT
        pin, so a second failover (or drain) between scheduling and
        firing lands the frames on the right replica; the restore hint
        was frozen by _restore_hint at failover time, so its
        drain/prefill-pool guards still hold."""
        pending = self._paced_frames.pop(str(stream_id), None)
        self.telemetry.recovery_paced_pending.set(
            len(self._paced_frames))
        stream = self.streams.get(str(stream_id))
        if not pending or not pending["ids"] or stream is None:
            return
        if stream.replica is None:
            return
        paced_start = time.perf_counter()
        self._replay_frames(stream, pending["ids"], pending["hint"])
        self.telemetry.record_replay(
            time.perf_counter() - paced_start, streams=1,
            frames=len(pending["ids"]), paced=True)

    # -- placement ---------------------------------------------------------

    def _place(self, now: float,
               prefix_hint: str | None = None) -> _Replica | None:
        """Power-of-two-choices over the placeable DECODE pool: sample
        two, route to the lower load score.  Deterministic under the
        `router_seed` RNG.  Streams only ever pin to decode-role
        replicas -- a prefill replica holds no slot state to pin to.

        With a prefix policy armed and a `prefix_hint` (chain-head
        digest) on the stream, replicas already holding that head JOIN
        the sampled pair -- affinity must not depend on the RNG
        happening to draw the holder -- and the comparison subtracts
        `affinity_weight` from a holder's load score, so a warm
        replica wins ties and modest load gaps but a SATURATED holder
        still loses (placeable() filtered it out entirely, or its raw
        load dwarfs the discount): affinity degrades to plain
        balancing, never to a hot spot."""
        candidates = [replica for replica in list(self.replicas.values())
                      if replica.placeable(now, self.policy)
                      and replica.pool_role() != "prefill"]
        if not candidates:
            return None
        affinity = self.prefix is not None and bool(prefix_hint)
        if len(candidates) == 1:
            chosen = candidates[0]
        elif affinity:
            pool = self._rng.sample(candidates, 2)
            pool += [replica for replica in candidates
                     if replica not in pool
                     and prefix_hint in replica.prefix_heads()]
            weight = self.prefix.affinity_weight

            def adjusted(replica: _Replica) -> float:
                discount = (weight if prefix_hint
                            in replica.prefix_heads() else 0.0)
                return replica.score() - discount

            chosen = min(pool, key=adjusted)
        else:
            first, second = self._rng.sample(candidates, 2)
            chosen = first if first.score() <= second.score() else second
        if affinity:
            if prefix_hint in chosen.prefix_heads():
                self.telemetry.affinity_hits.inc()
            else:
                self.telemetry.affinity_misses.inc()
        return chosen

    def _place_prefill(self, now: float) -> _Replica | None:
        """Least-loaded prefill replica with dispatch capacity, or None
        (pool empty/saturated -- the frame goes straight to its decode
        replica and prefills locally; disaggregation degrades to
        colocation, never to a stall)."""
        candidates = [replica for replica in list(self.replicas.values())
                      if replica.pool_role() == "prefill"
                      and not replica.dead and not replica.draining
                      and replica.fresh(now, self.policy.stale_after_s)
                      and replica.has_capacity(self.policy)]
        if not candidates:
            return None
        return min(candidates, key=lambda replica: replica.score())

    def _any_replica(self) -> _Replica | None:
        """Least-loaded LIVE decode replica ignoring saturation/
        staleness: the failover fallback (availability beats load
        hygiene when the alternative is destroying a stream)."""
        candidates = [replica for replica in list(self.replicas.values())
                      if not replica.dead
                      and replica.pool_role() != "prefill"]
        if not candidates:
            return None
        return min(candidates, key=lambda replica: replica.score())

    # -- client surface (pipeline-protocol parity) -------------------------

    def submit_stream(self, stream_id, parameters=None, queue_response=None,
                      throttle=None,
                      grace_time: float = DEFAULT_GRACE_TIME) -> None:
        """Thread-safe local entry: posts through the gateway mailbox
        (decisions surface on `queue_response` and the counters)."""
        self.post_message("create_stream", [
            stream_id, parameters or {}, grace_time, None, queue_response,
            throttle])

    def submit_frame(self, stream_id, frame_data,
                     frame_id=None) -> None:
        stream_dict = {"stream_id": stream_id}
        if frame_id is not None:
            stream_dict["frame_id"] = frame_id
        self.post_message("process_frame", [stream_dict, frame_data])

    def create_stream(self, stream_id, parameters=None,
                      grace_time=DEFAULT_GRACE_TIME, topic_response=None,
                      queue_response=None, throttle=None) -> None:
        stream_id = str(stream_id)
        admit_start = time.perf_counter()
        try:
            if isinstance(parameters, str):   # wire call: JSON-encoded
                parameters = json.loads(parameters) if parameters else {}
            if isinstance(grace_time, str):
                grace_time = float(grace_time)
        except ValueError as error:
            _LOGGER.warning("%s: bad create_stream arguments: %s",
                            self.name, error)
            return
        parameters = dict(parameters or {})
        priority = parse_int(parameters.get("priority", 0), 0)
        slo_ms = parse_float(parameters.get("slo_ms", 0.0), 0.0)
        if stream_id in self.streams:
            self._reject_stream(stream_id, "duplicate_stream_id",
                                topic_response, queue_response)
            return
        region = (str(parameters["region"])
                  if parameters.get("region") is not None else None)
        if self.federation_group is not None:
            # federated tier: region-aware placement audit (client
            # region affinity first, rendezvous over the SURVIVING
            # groups as fallback) -- a stream that hashes to ANOTHER
            # live group sheds wrong_group before the token bucket (a
            # misrouted client must not burn this group's admission
            # budget)
            if (self.federation.owner_of(stream_id, region=region,
                                         lost=self._lost_groups)
                    != self.federation_group):
                self._reject_stream(stream_id, "wrong_group",
                                    topic_response, queue_response)
                return
            if region is not None:
                # degradation evidence: did the declared region
                # affinity land in-region, or did a region loss push
                # the stream cross-region?
                if self.federation.region_of(
                        self.federation_group) == region:
                    self.telemetry.region_affinity_hits.inc()
                else:
                    self.telemetry.region_affinity_misses.inc()
        now = time.monotonic()
        tenant = str(parameters.get("tenant", "") or "")
        bucket = self.policy.bucket_for(priority)
        if bucket is not None:
            taken = bucket.try_take(now)
            self._buckets_dirty = self.journal is not None
            if not taken:
                self._reject_stream(stream_id, "rate_limited",
                                    topic_response, queue_response)
                return
        tenant_bucket = self.policy.tenant_bucket_for(tenant)
        if tenant_bucket is not None:
            # multi-tenant isolation: each tenant burns its OWN budget
            # -- one tenant's storm exhausts its bucket and sheds
            # rate_limited_tenant, with zero draw on any other
            # tenant's tokens (the isolation proof rides this)
            taken = tenant_bucket.try_take(now)
            self._buckets_dirty = self.journal is not None
            if not taken:
                self._reject_stream(stream_id, "rate_limited_tenant",
                                    topic_response, queue_response)
                return
        # prefix-affinity: the client's chain-head digest (computed
        # with decode/prefix.py prefix_head over the shared preamble)
        # rides the create parameters; replicas mirroring that head
        # win placement ties (see _place)
        prefix_hint = (str(parameters.get("prefix_hint") or "")
                       if self.prefix is not None else "")
        replica = self._place(now, prefix_hint=prefix_hint or None)
        if replica is None:
            self._reject_stream(stream_id, "no_replica",
                                topic_response, queue_response)
            return
        if (self.policy.frame_deadline_s > 0
                and "frame_deadline" not in parameters):
            # PR 3 machinery: the REPLICA releases wedged frames by
            # dead-letter, which frees the gateway slot (see
            # _dead_letter_handler) -- no second deadline layer here
            parameters["frame_deadline"] = self.policy.frame_deadline_s
        if self.disagg is not None and "adopt_timeout" not in parameters:
            # the disagg policy's fetch bound reaches the DECODE
            # replica as a stream parameter (same mechanism as
            # frame_deadline): LMGenerate reads it per stream, so one
            # gateway knob governs the whole fleet's adopt fallback
            parameters["adopt_timeout"] = self.disagg.adopt_timeout_s
        if (self.prefix is not None and self.checkpoint is not None
                and self.checkpoint.keeper
                and "prefix_keeper" not in parameters):
            # prefix + checkpoint together turn the keeper into a
            # cross-replica prefix store: the replica pre-warms cold
            # prompts from it and exports finished chains back
            # (elements/ml.py _prewarm_prefix / _export_prefix)
            parameters["prefix_keeper"] = self.checkpoint.keeper
        stream = _GatewayStream(
            stream_id, priority, slo_ms, parameters, grace_time, replica,
            queue_response=queue_response, topic_response=topic_response,
            throttle=throttle)
        stream.tenant = tenant
        if self.checkpoint is not None and self.checkpoint.keeper:
            stream.keeper = self.checkpoint.keeper
        stream.lease = Lease(
            self.process.event, grace_time, stream_id,
            lease_expired_handler=self._stream_lease_expired,
            jitter=self._lease_jitter(stream_id))
        self.streams[stream_id] = stream
        replica.streams.add(stream_id)
        self.telemetry.admitted.inc()
        # decomposition: admission processing (bucket take + placement)
        # is the stream's one-time `admit` share
        self.telemetry.record_stage(
            stream_id, "admit", time.perf_counter() - admit_start)
        self._mark_journal(stream)
        self._send_create(replica, stream)
        if self._throttle_on:
            # admitted INTO an active overload: this source starts
            # capped like everyone else, not at full rate
            stream.throttled = True
            self.telemetry.throttled.inc()
            self._send_throttle(stream, self.policy.throttle_rate)
        self._update_share()

    def _lease_jitter(self, stream_id: str) -> float:
        from ..runtime.lease import jitter_fraction
        seed = self.faults.seed if self.faults is not None else 0
        return jitter_fraction(seed, stream_id, salt="gw-lease")

    def _stream_lease_expired(self, stream_id) -> None:
        _LOGGER.info("%s: stream %s lease expired", self.name, stream_id)
        self.destroy_stream(stream_id)

    def _reject_stream(self, stream_id, reason, topic_response,
                       queue_response) -> None:
        """Typed shed: the caller learns WHY, immediately -- never
        silent queue growth (Clockwork-style admission)."""
        self.telemetry.shed_streams.inc()
        self.telemetry.record_shed_stream(stream_id, reason)
        _LOGGER.info("%s: stream %s shed (%s)", self.name, stream_id,
                     reason)
        if topic_response:
            self.process.publish(
                topic_response,
                generate("overloaded", [stream_id, "", reason]))
        if queue_response is not None:
            queue_response.put(
                (stream_id, None, {"reason": reason}, "overloaded"))

    def process_frame(self, stream_dict, frame_data=None) -> None:
        try:
            if isinstance(stream_dict, str):
                stream_dict = json.loads(stream_dict)
            if isinstance(frame_data, str):
                frame_data = decode_frame_data(frame_data)
        except (ValueError, KeyError) as error:
            _LOGGER.warning("%s: undecodable frame dropped: %s",
                            self.name, error)
            return
        stream_id = str(stream_dict.get("stream_id", ""))
        stream = self.streams.get(stream_id)
        if stream is None:
            _LOGGER.debug("%s: frame for unknown stream %s dropped",
                          self.name, stream_id)
            return
        if stream.lease is not None:
            stream.lease.extend()
        frame_id = stream_dict.get("frame_id")
        frame_id = (stream.cursor if frame_id is None else int(frame_id))
        if frame_id >= stream.cursor:
            stream.cursor = frame_id + 1
        if stream.is_delivered(frame_id) or frame_id in stream.inflight:
            self.telemetry.duplicates.inc()
            return
        # SLO-aware shed: when the estimated queue wait already blows
        # the stream's declared SLO, rejecting NOW beats serving late
        if stream.slo_ms > 0 and self._parked:
            rate = self._completion_rate()
            if rate is not None:
                est_wait_ms = len(self._parked) / rate * 1000.0
                if est_wait_ms > stream.slo_ms:
                    self._shed_frame(stream, frame_id, "slo")
                    return
        seq = self._seq = self._seq + 1
        entry = [frame_data or {}, time.monotonic(), seq]
        stream.inflight[frame_id] = entry
        # root-span ownership: the gateway mints the frame's fleet-wide
        # trace here, at admission -- every replica that later serves
        # this frame CONTINUES the same trace (context rides the wire
        # in _send_frame).  None with telemetry off: zero trace bytes
        trace = self.telemetry.frame_begin(stream_id, frame_id)
        if trace is not None:
            stream.traces[frame_id] = trace
        self._mark_journal(stream)
        replica = stream.replica
        dispatchable = (replica is not None
                        and replica.has_capacity(self.policy)
                        and stream.parked == 0)
        if dispatchable and self.disagg is not None:
            # disaggregated hop 1: the least-loaded prefill replica
            # computes the prompt and returns a KV handoff; hop 2
            # (_prefill_done) forwards it to the pinned decode replica.
            # No prefill capacity -> straight to decode (local prefill)
            prefill = self._place_prefill(time.monotonic())
            if prefill is not None:
                if prefill.topic_path not in stream.prefill_created:
                    # the stream pins to its DECODE replica; a prefill
                    # replica only needs enough stream state to run
                    # prompt frames, created on first use
                    stream.prefill_created.add(prefill.topic_path)
                    self._send_create(prefill, stream)
                entry.append(("prefill", prefill.topic_path))
                self.telemetry.prefill_routed.inc()
                self._send_frame(prefill, stream, frame_id, entry)
                return
        if dispatchable:
            self._send_frame(replica, stream, frame_id, entry)
        else:
            self._park(stream, frame_id, seq)

    def destroy_stream(self, stream_id) -> None:
        stream_id = str(stream_id)
        stream = self.streams.pop(stream_id, None)
        if stream is None:
            return
        if stream.lease is not None:
            stream.lease.terminate()
            stream.lease = None
        parked_ids = {item[3] for item in list(self._parked)
                      if item[2] == stream_id}
        # paced failover replays that never fired behave like parked
        # entries: in inflight, but no replica slot was ever taken.
        # Dropping the cohort entry here is what keeps the later
        # scheduled _paced_replay a no-op (its pop finds nothing) --
        # a destroyed stream must never leak a replay dispatch
        paced = self._paced_frames.pop(stream_id, None)
        if paced is not None:
            parked_ids |= set(paced["ids"])
            self.telemetry.recovery_paced_pending.set(
                len(self._paced_frames))
        if stream.parked:
            self._parked = [item for item in list(self._parked)
                            if item[2] != stream_id]
            stream.parked = 0
            self._note_queue_depth()
        replica = stream.replica
        # frames mid-prefill-hop hold a PREFILL replica's slot, not the
        # pinned decode replica's -- release each where it was sent
        staged = 0
        for frame_id, entry in stream.inflight.items():
            if frame_id in parked_ids or len(entry) <= 3:
                continue
            staged += 1
            prefill = self.replicas.get(entry[3][1])
            if prefill is not None:
                prefill.outstanding = max(0, prefill.outstanding - 1)
                prefill.note_load(time.monotonic(), self.policy)
        if replica is not None:
            replica.streams.discard(stream_id)
            # only DISPATCHED frames hold replica slots: parked entries
            # never incremented outstanding
            replica.outstanding = max(
                0, replica.outstanding - (sum(
                    1 for frame_id in stream.inflight
                    if frame_id not in parked_ids) - staged))
            replica.note_load(time.monotonic(), self.policy)
            self._send_destroy(replica, stream_id)
        for topic_path in stream.prefill_created:
            prefill = self.replicas.get(topic_path)
            if prefill is not None:
                self._send_destroy(prefill, stream_id)
        for trace in stream.traces.values():
            # frames still open at destroy: finish their root spans so
            # the admission wait they DID accrue still exports
            self.telemetry.frame_done(trace, status="destroyed")
        stream.traces.clear()
        stream.dispatch_s.clear()
        self.telemetry.forget_stream(stream_id)
        stream.inflight.clear()
        self._journal_forget(stream_id)
        self._update_share()
        self._drain_parked()

    # -- replica dispatch --------------------------------------------------

    def _send_create(self, replica: _Replica, stream: _GatewayStream,
                     first_frame_id: int = 0) -> None:
        if replica.pipeline is not None:
            replica.pipeline.post_message("create_stream", [
                stream.stream_id, dict(stream.parameters),
                stream.grace_time, self.topic_in,
                _LocalResponder(self), None, first_frame_id])
        else:
            # positional wire call: queue_response/graph_path ride as
            # None placeholders (the codec renders them as empty lists;
            # the pipeline coerces falsy back to None) so
            # first_frame_id -- the failover cursor -- arrives intact
            self.process.publish(
                f"{replica.topic_path}/in",
                generate("create_stream", [
                    stream.stream_id,
                    json.dumps(stream.parameters).encode("ascii"),
                    stream.grace_time, self.topic_in, None, None,
                    first_frame_id]))

    def _send_destroy(self, replica: _Replica, stream_id: str) -> None:
        if replica.pipeline is not None:
            replica.pipeline.post_message("destroy_stream", [stream_id])
        elif replica.topic_path:
            self.process.publish(
                f"{replica.topic_path}/in",
                generate("destroy_stream", [stream_id]))

    def _send_frame(self, replica: _Replica, stream: _GatewayStream,
                    frame_id: int, entry: list, data=None) -> None:
        """Route one frame to `replica`, consulting the seeded
        `replica_kill` fault point first (one consult per ROUTED frame:
        frame=k kills the replica on its k-th routed frame).  `data`
        overrides the wire payload (the disagg decode hop sends the
        original frame data MERGED with the prefill handoff; entry[0]
        stays the original so failover replay restarts from scratch)."""
        if (self.faults is not None and not replica.dead
                and self.faults.replica_kill(replica.name)):
            _LOGGER.warning(
                "%s: injected replica_kill fired on %s (frame %s/%s)",
                self.name, replica.name, stream.stream_id, frame_id)
            # fence NOW (no further dispatch picks this replica) but
            # defer the failover to its own mailbox turn: running it
            # inline would reenter _drain_parked / the replay loop
            # mid-iteration (stale snapshot removes, double dispatch).
            # The un-dispatched frame stays in stream.inflight; the
            # deferred replay re-routes it with everything else
            replica.dead = True
            self.post_message("_replica_lost", [
                replica.topic_path, "injected replica_kill"])
            return
        if (stream.restore_hint is not None and data is None
                and replica.pool_role() != "prefill"):
            # one-shot warm-restore for an ADOPTED stream: its journal
            # rebuild had no inflight frames to replay, so the FIRST
            # frame dispatched after adoption (the client's
            # resubmission) carries the restore hint -- the decode
            # replica adopts the checkpointed KV and re-decodes only
            # the post-snapshot tail instead of cold re-prefilling
            data = dict(entry[0])
            restore = dict(stream.restore_hint)
            adopt_trace = stream.traces.get(frame_id)
            if adopt_trace is not None:
                restore["trace_context"] = make_trace_context(
                    adopt_trace)
            data["restore"] = restore
            stream.restore_hint = None
        route_start = time.perf_counter()
        replica.outstanding += 1
        replica.routed += 1
        replica.note_load(time.monotonic(), self.policy)
        self.telemetry.routed.inc()
        self.telemetry.record_replica_routed(replica.name)
        payload = entry[0] if data is None else data
        trace = stream.traces.get(frame_id)
        if trace is not None:
            if frame_id not in stream.dispatch_s:
                # FIRST dispatch closes the admit-wait span (submit ->
                # dispatch, parked wait included); re-dispatches (disagg
                # hop 2, failover replay) extend the same trace without
                # a second admission
                wait_s = self.telemetry.record_admit_wait(trace)
                self.telemetry.record_stage(stream.stream_id, "queue",
                                            wait_s)
            stream.dispatch_s[frame_id] = route_start
            self.telemetry.record_route(trace, route_start,
                                        replica.name,
                                        pool=replica.pool_role())
            self.telemetry.record_stage(
                stream.stream_id, "route",
                time.perf_counter() - route_start)
            # propagation: the trace context rides the frame data (a
            # COPY -- entry[0] stays pristine for replay byte-equality)
            # so the replica continues the gateway's trace
            payload = attach_trace_context(payload,
                                           make_trace_context(trace))
        if replica.pipeline is not None:
            replica.pipeline.post_message("process_frame", [
                {"stream_id": stream.stream_id, "frame_id": frame_id},
                payload])
        else:
            self.process.publish(
                f"{replica.topic_path}/in",
                generate("process_frame", [
                    {"stream_id": stream.stream_id, "frame_id": frame_id},
                    encode_frame_data(payload).encode("ascii")]))

    # -- parked queue / backpressure ---------------------------------------

    def _park(self, stream: _GatewayStream, frame_id: int,
              seq: int) -> None:
        policy = self.policy
        if policy.queue_capacity <= 0:
            self._shed_frame(stream, frame_id, "queue_disabled")
            return
        if len(self._parked) >= policy.queue_capacity:
            # full: the LOWEST-priority (then newest) parked entry goes
            # first; if the incoming frame IS lowest, shed it directly
            worst = max(self._parked)
            incoming = (stream.priority, seq, stream.stream_id, frame_id)
            if incoming[:2] >= worst[:2]:
                self._shed_frame(stream, frame_id, "queue_full")
                return
            self._parked.remove(worst)
            victim = self.streams.get(worst[2])
            if victim is not None:
                victim.parked = max(0, victim.parked - 1)
                self._shed_frame(victim, worst[3], "queue_full")
        self._parked.append(
            (stream.priority, seq, stream.stream_id, frame_id))
        stream.parked += 1
        self._note_queue_depth()
        self._update_backpressure()

    def _shed_frame(self, stream: _GatewayStream, frame_id: int,
                    reason: str) -> None:
        stream.inflight.pop(frame_id, None)
        stream.dispatch_s.pop(frame_id, None)
        trace = stream.traces.pop(frame_id, None)
        if trace is not None:
            self.telemetry.record_shed_span(trace, reason)
            self.telemetry.frame_done(trace, status="shed")
        else:
            # pre-admission sheds (SLO estimate) fire before the frame
            # trace exists: a global gateway-lane instant instead
            self.telemetry.record_shed_stream(stream.stream_id, reason)
        self.telemetry.shed_frames.inc()
        if stream.topic_response:
            self.process.publish(
                stream.topic_response,
                generate("overloaded",
                         [stream.stream_id, frame_id, reason]))
        if stream.queue_response is not None:
            stream.queue_response.put(
                (stream.stream_id, frame_id, {"reason": reason}, "shed"))

    def _drain_parked(self) -> None:
        """Dispatch parked frames whose pinned replica has capacity,
        highest-priority-oldest first.  Per-stream order is preserved:
        entries carry monotonically increasing seqs and a stream's
        frames never skip the queue while older siblings wait.

        Always falls through to the watermark check, even when the
        queue is already empty: destroy_stream/_fail_stream can empty
        the queue without any dispatch, and a latched throttle-on with
        capped sources would otherwise never observe the low-water
        crossing that lifts the caps."""
        progress = bool(self._parked)
        while progress and self._parked:
            progress = False
            for item in sorted(self._parked):
                if item not in self._parked:
                    continue  # removed by an earlier pass over the snapshot
                priority, seq, stream_id, frame_id = item
                stream = self.streams.get(stream_id)
                if stream is None:
                    self._parked.remove(item)
                    progress = True
                    continue
                entry = stream.inflight.get(frame_id)
                if entry is None:
                    self._parked.remove(item)
                    stream.parked = max(0, stream.parked - 1)
                    progress = True
                    continue
                # only the stream's OLDEST parked frame may dispatch
                oldest = min(
                    (other for other in list(self._parked)
                     if other[2] == stream_id),
                    default=item)
                if oldest != item:
                    continue
                replica = stream.replica
                if replica is None or not replica.has_capacity(
                        self.policy):
                    continue
                self._parked.remove(item)
                stream.parked = max(0, stream.parked - 1)
                self._send_frame(replica, stream, frame_id, entry)
                progress = True
        self._note_queue_depth()
        self._update_backpressure()

    def _note_queue_depth(self) -> None:
        self.telemetry.parked.set(len(self._parked))
        if self.telemetry.enabled:
            depths: dict[int, int] = {}
            for priority, _, _, _ in list(self._parked):
                depths[priority] = depths.get(priority, 0) + 1
            # zero-fill priorities reported before: a drained priority
            # must read 0, not its last nonzero value, in the snapshot
            for priority in self._depth_priorities - set(depths):
                depths[priority] = 0
            self._depth_priorities |= set(depths)
            self.telemetry.record_queue_depths(depths)

    def _update_backpressure(self) -> None:
        """Throttle hysteresis over queue occupancy: past the
        high-water mark every active stream's source is asked to slow
        to `throttle_rate`; once the queue drains below the low-water
        mark the cap is lifted (rate 0)."""
        policy = self.policy
        capacity = policy.queue_capacity
        if capacity <= 0:
            return
        occupancy = len(self._parked) / capacity
        if not self._throttle_on and occupancy >= policy.throttle_high:
            self._throttle_on = True
            self._signal_throttle(policy.throttle_rate)
        elif self._throttle_on and occupancy <= policy.throttle_low:
            self._throttle_on = False
            self._signal_throttle(0.0)

    def _signal_throttle(self, rate: float) -> None:
        self.telemetry.record_throttle_span(rate)
        counter = (self.telemetry.throttled if rate > 0
                   else self.telemetry.unthrottled)
        for stream in list(self.streams.values()):
            throttling = rate > 0
            if stream.throttled == throttling:
                continue
            stream.throttled = throttling
            counter.inc()
            self._send_throttle(stream, rate)

    def _send_throttle(self, stream: _GatewayStream, rate: float) -> None:
        if stream.throttle is not None:
            try:
                stream.throttle(stream.stream_id, rate)
            except Exception:   # a client callback must not kill us
                _LOGGER.exception("%s: throttle callback failed",
                                  self.name)
        # the wire form: sources subscribed to the gateway /out (or
        # a fronted pipeline's own throttle command) slow down
        self.publish_out("throttle", [stream.stream_id, rate])

    # -- responses ---------------------------------------------------------

    def process_frame_response(self, stream_dict, frame_data=None) -> None:
        """A replica answered (success via the local responder or the
        wire; error/drop via the stream's topic_response notice)."""
        try:
            if isinstance(stream_dict, str):
                stream_dict = json.loads(stream_dict)
        except ValueError as error:
            _LOGGER.warning("%s: undecodable frame response dropped: %s",
                            self.name, error)
            return
        stream_id = str(stream_dict.get("stream_id", ""))
        stream = self.streams.get(stream_id)
        if stream is None:
            return
        frame_id = int(stream_dict.get("frame_id", 0))
        event = stream_dict.get("event")
        if isinstance(frame_data, str):
            try:
                frame_data = decode_frame_data(frame_data)
            except (ValueError, KeyError):
                event = event or "error"
                frame_data = {}
        self._frame_done(stream, frame_id, frame_data or {}, event)

    def _dead_letter_handler(self, topic: str, payload: str) -> None:
        """A replica dead-lettered a frame (PR 3): release the slot as
        an error.  Runs on the process message pump; route through the
        mailbox to keep actor ordering."""
        try:
            command, parameters = parse(payload)
        except ValueError:
            return
        if command != "dead_letter" or not parameters:
            return
        meta = parameters[0] if isinstance(parameters[0], dict) else {}
        from ..runtime import ActorTopic
        # a dead-letter frees a replica slot: preempt queued submissions
        self.post_message("_release_dead_letter", [
            meta.get("stream_id", ""), meta.get("frame_id", -1),
            meta.get("reason", "dead_letter")],
            actor_topic=ActorTopic.CONTROL)

    def _release_dead_letter(self, stream_id, frame_id, reason) -> None:
        stream = self.streams.get(str(stream_id))
        if stream is None:
            return
        try:
            frame_id = int(frame_id)
        except (TypeError, ValueError):
            return
        self._frame_done(stream, frame_id, {"reason": str(reason)},
                         event="error")

    def _frame_done(self, stream: _GatewayStream, frame_id: int,
                    outputs: dict, event=None) -> None:
        staged = stream.inflight.get(frame_id)
        if (staged is not None and len(staged) > 3
                and not stream.is_delivered(frame_id)):
            # disaggregated hop 1 answered: forward to the decode pool
            # instead of completing the frame
            self._prefill_done(stream, frame_id, staged, outputs, event)
            return
        entry = stream.inflight.pop(frame_id, None)
        if entry is None or stream.is_delivered(frame_id):
            self.telemetry.duplicates.inc()
            return
        trace = stream.traces.pop(frame_id, None)
        dispatched_s = stream.dispatch_s.pop(frame_id, None)
        if dispatched_s is not None:
            # decomposition: pinned-replica service time (dispatch ->
            # response) is the stream's `decode` share -- the prefill
            # hop's share was credited by _prefill_done
            self.telemetry.record_stage(
                stream.stream_id, "decode",
                time.perf_counter() - dispatched_s)
        emit_start = (time.perf_counter() if trace is not None else 0.0)
        stream.delivered.add(frame_id)
        # collapse the contiguous delivered prefix into the floor: the
        # dedupe state a long-lived stream keeps is one int + the
        # sparse out-of-order tail, and the floor is what the crash
        # journal persists as the exactly-once high-water mark
        while stream.delivered_floor + 1 in stream.delivered:
            stream.delivered_floor += 1
            stream.delivered.discard(stream.delivered_floor)
        if len(stream.delivered) > 8192:
            # bounded backstop for pathologically sparse delivery: ids
            # far below the cursor can no longer recur
            floor = stream.cursor - 4096
            stream.delivered = {fid for fid in stream.delivered
                                if fid >= floor}
        self._mark_journal(stream)
        replica = stream.replica
        if replica is not None:
            replica.outstanding = max(0, replica.outstanding - 1)
            replica.note_load(time.monotonic(), self.policy)
        now = time.monotonic()
        if event:
            self.telemetry.released.inc()
            status = "error" if event == "error" else "dropped"
        else:
            self.telemetry.completed.inc()
            self.telemetry.latency.record(now - entry[1])
            if stream.slo_ms > 0:
                # per-priority (and per-tenant) SLO attainment:
                # completed frames judged against the stream's
                # declared end-to-end budget
                self.telemetry.record_slo(
                    stream.priority,
                    (now - entry[1]) * 1000.0 <= stream.slo_ms,
                    tenant=stream.tenant or None)
            self._completions.append(now)
            if len(self._completions) > _RATE_WINDOW:
                del self._completions[:len(self._completions)
                                      - _RATE_WINDOW]
            status = "ok"
        if stream.queue_response is not None:
            stream.queue_response.put(
                (stream.stream_id, frame_id, outputs, status))
        elif stream.topic_response:
            reply = {"stream_id": stream.stream_id, "frame_id": frame_id}
            if event:
                reply["event"] = event
                self.process.publish(
                    stream.topic_response,
                    generate("process_frame_response", [reply]))
            else:
                self.process.publish(
                    stream.topic_response,
                    generate("process_frame_response", [
                        reply,
                        encode_frame_data(outputs).encode("ascii")]))
        if trace is not None:
            self.telemetry.record_stage(
                stream.stream_id, "emit",
                time.perf_counter() - emit_start)
            self.telemetry.frame_done(trace, status=status)
        self._drain_parked()

    def _prefill_done(self, stream: _GatewayStream, frame_id: int,
                      entry: list, outputs, event=None) -> None:
        """Hop 2 of the disaggregated path: the prefill replica
        answered -- release its slot and forward the frame to the
        pinned decode replica with the KV handoff merged into the
        payload.  A prefill error/drop (or a response without a
        handoff) degrades to the direct dispatch: the decode replica
        prefills locally, the stream never notices."""
        stage_topic = entry[3][1]
        del entry[3:]               # back to the plain replay shape
        dispatched_s = stream.dispatch_s.get(frame_id)
        if dispatched_s is not None:
            # decomposition: the disagg hop-1 share (dispatch ->
            # prefill response); hop 2's dispatch re-stamps below
            self.telemetry.record_stage(
                stream.stream_id, "prefill",
                time.perf_counter() - dispatched_s)
        prefill = self.replicas.get(stage_topic)
        if prefill is not None:
            prefill.outstanding = max(0, prefill.outstanding - 1)
            prefill.note_load(time.monotonic(), self.policy)
        handoff = None
        if not event and isinstance(outputs, dict):
            handoff = outputs.get("handoff")
        if handoff is not None:
            self.telemetry.kv_migrations.inc()
        else:
            self.telemetry.prefill_fallbacks.inc()
        replica = stream.replica
        if (replica is not None and replica.has_capacity(self.policy)
                and stream.parked == 0):
            data = entry[0]
            if handoff is not None:
                data = dict(entry[0])
                data["handoff"] = handoff
            self._send_frame(replica, stream, frame_id, entry,
                             data=data)
        else:
            # parks replay the ORIGINAL frame data when they drain (the
            # handoff's transfer keys may expire while parked); the
            # decode replica prefills locally -- degraded, never lost
            self._park(stream, frame_id, entry[2])

    def _recover_prefill_frames(self, topic_path: str,
                                redispatch: bool = True) -> None:
        """A prefill replica left the pool with frames mid-hop: those
        frames belong to streams pinned to DECODE replicas, so stream
        migration never sees them.  On replica DEATH (redispatch=True)
        each is sent directly to its pinned decode replica (local
        re-prefill) -- the disagg analogue of failover replay, zero
        frames lost.  On a graceful DRAIN the frames are left in
        flight: the draining replica keeps serving through its linger
        window and its handoff responses forward normally; a
        re-dispatch here would race them -- the stale prefill response
        would arrive against a de-staged entry and be DELIVERED to the
        client as the frame's final output."""
        for stream in list(self.streams.values()):
            # a restarted prefill process must get a fresh create
            stream.prefill_created.discard(topic_path)
            if not redispatch:
                continue
            for frame_id, entry in list(stream.inflight.items()):
                if len(entry) <= 3 or entry[3][1] != topic_path:
                    continue
                del entry[3:]
                self.telemetry.prefill_fallbacks.inc()
                replica = stream.replica
                if (replica is not None
                        and replica.has_capacity(self.policy)
                        and stream.parked == 0):
                    self._send_frame(replica, stream, frame_id, entry)
                else:
                    self._park(stream, frame_id, entry[2])

    def _completion_rate(self) -> float | None:
        """Completions/sec over the recent window (None until warm):
        the denominator of the SLO queue-wait estimate."""
        if len(self._completions) < _RATE_WARMUP:
            return None
        window = self._completions[-1] - self._completions[0]
        if window <= 0:
            return None
        return (len(self._completions) - 1) / window

    def _fail_stream(self, stream: _GatewayStream, reason: str) -> None:
        _LOGGER.error("%s: stream %s failed (%s); releasing %d in-flight"
                      " frames", self.name, stream.stream_id, reason,
                      len(stream.inflight))
        for frame_id in sorted(stream.inflight):
            self.telemetry.released.inc()
            if stream.queue_response is not None:
                stream.queue_response.put(
                    (stream.stream_id, frame_id, {"reason": reason},
                     "error"))
            elif stream.topic_response:
                self.process.publish(
                    stream.topic_response,
                    generate("process_frame_response", [
                        {"stream_id": stream.stream_id,
                         "frame_id": frame_id, "event": "error"}]))
        for trace in stream.traces.values():
            self.telemetry.frame_done(trace, status="error")
        stream.traces.clear()
        stream.dispatch_s.clear()
        self.telemetry.forget_stream(stream.stream_id)
        stream.inflight.clear()
        if self._paced_frames.pop(stream.stream_id, None) is not None:
            self.telemetry.recovery_paced_pending.set(
                len(self._paced_frames))
        if stream.parked:
            self._parked = [item for item in list(self._parked)
                            if item[2] != stream.stream_id]
            stream.parked = 0
            self._note_queue_depth()
        if stream.lease is not None:
            stream.lease.terminate()
            stream.lease = None
        self.streams.pop(stream.stream_id, None)
        self._journal_forget(stream.stream_id)
        self._update_share()

    # -- live reconfiguration (the autopilot's apply surface) --------------
    #
    # Every setter mutates the RUNNING configuration in place -- no
    # restart, no stream disruption, no recompile.  serve/autopilot.py
    # write-ahead journals each delta before calling these, so a crash
    # mid-apply replays into the identical state.

    def set_bucket_rate(self, priority, rate, burst=None) -> None:
        """Live-retune (or create) one admission token bucket.  The
        current token level is preserved (clamped to a shrunk burst):
        a rate change must not refund or confiscate in-flight budget."""
        from .policy import TokenBucket
        priority = int(priority)
        rate = max(float(rate), 1e-9)
        bucket = self.policy.buckets.get(priority)
        if bucket is None:
            self.policy.buckets[priority] = TokenBucket(
                rate, float(burst) if burst else max(rate, 1.0))
        else:
            bucket.rate = rate
            if burst:
                bucket.burst = float(burst)
                bucket.tokens = min(bucket.tokens, bucket.burst)
        if self.journal is not None and self.role != "standby":
            self._buckets_dirty = True

    def set_autoscale_floors(self, min_replicas=None,
                             max_replicas=None) -> None:
        """Live-move the autoscaler's floor/ceiling; the next scaler
        tick acts on the new bounds.  The min <= max invariant is kept
        by widening toward whichever side the caller moved."""
        if self.autoscaler is None:
            return
        floors = self.autoscaler.policy
        if max_replicas is not None:
            floors.max_replicas = max(int(max_replicas), 1)
        if min_replicas is not None:
            floors.min_replicas = max(int(min_replicas), 1)
        if floors.min_replicas > floors.max_replicas:
            if min_replicas is not None and max_replicas is None:
                floors.max_replicas = floors.min_replicas
            else:
                floors.min_replicas = floors.max_replicas

    def set_replica_parameter(self, element_name, name, value) -> int:
        """Broadcast one element-parameter change to every live
        replica: direct-attached pipelines take the in-process call,
        wire replicas get `(set_element_parameter ...)` on their `in`
        topic.  Parameters like micro_batch / checkpoint_every are
        re-read per batch flush / checkpoint tick, so the new value
        takes effect on the next frame without a restart."""
        updated = 0
        for replica in list(self.replicas.values()):
            if replica.dead or replica.draining:
                continue
            if replica.pipeline is not None:
                try:
                    replica.pipeline.set_element_parameter(
                        element_name, name, value)
                    updated += 1
                except Exception as error:
                    _LOGGER.warning(
                        "%s: set %s.%s on %s failed: %s", self.name,
                        element_name, name, replica.name, error)
            else:
                self.process.publish(
                    f"{replica.topic_path}/in",
                    generate("set_element_parameter",
                             [str(element_name), str(name),
                              str(value)]))
                updated += 1
        return updated

    # -- observability -----------------------------------------------------

    def _autopilot_collect(self) -> None:
        """Mailbox continuation of the autopilot cadence timer."""
        if self.autopilot is not None:
            self.autopilot.collect()

    def _autopilot_decide(self, round_id) -> None:
        """Mailbox continuation closing one autopilot harvest round
        (posted early when every respondent answered, else by the
        wait lease)."""
        if self.autopilot is not None:
            self.autopilot.decide(round_id)

    def publish_trace(self, topic_response) -> None:
        """Wire query (`aiko trace collect`): publish this gateway's
        self-describing Perfetto document, so a collector harvests the
        fleet's per-process artifacts without filesystem access.  The
        reply shape lives in observe/collector.py (shared with
        Pipeline)."""
        from ..observe import publish_trace_document
        publish_trace_document(self.process, self.telemetry,
                               self.topic_path, topic_response)

    def pool_snapshot(self) -> dict:
        """Per-replica pool view (replica topic, state, load gauges,
        warm/cold) -- rendered by `aiko system status` and the
        dashboard's `pool:` row; rides the periodic telemetry summary
        into the EC share so remote observers see it."""
        pool = {}
        draining = (self.autoscaler.draining.values()
                    if self.autoscaler is not None else ())
        for replica in list(self.replicas.values()) + list(draining):
            pool[replica.name] = {
                "topic": replica.topic_path,
                "state": "draining" if replica.draining else "live",
                "outstanding": replica.outstanding,
                "inflight": replica.reported_inflight(),
                "queue_depth": replica.reported_queue_depth(),
                "streams": len(replica.streams),
                "warm": replica.warm,
                "role": replica.pool_role(),
            }
        return pool

    def _update_share(self) -> None:
        self.telemetry.replicas.set(len(self.replicas))
        self.telemetry.pool_size.set(len(self.replicas))
        if self.ec_producer is not None:
            # staged: a stream-churn storm (create/destroy per frame at
            # O(10k) streams) folds its share refreshes into one delta
            # per drained mailbox burst, and unchanged scalars
            # (replica_count, role) drop out of the payload entirely
            self.ec_producer.stage("replica_count", len(self.replicas))
            self.ec_producer.stage("stream_count", len(self.streams))
            self.ec_producer.stage("role", self.role)

    def stop(self) -> None:
        if not hasattr(self, "election"):
            # construction raised before wiring completed (a rejected
            # policy spec): process teardown finds nothing to stop --
            # every constructor raise precedes the election attribute
            return
        if self.autopilot is not None:
            self.autopilot.shutdown()
            self.autopilot = None
        if self.autoscaler is not None:
            self.autoscaler.stop()
            self.autoscaler = None
        self.telemetry.stop()
        for stream_id in list(self.streams):
            self.destroy_stream(stream_id)
        for journal in list(self._foreign_journals.values()):
            journal.stop()
        self._foreign_journals.clear()
        if self.journal is not None:
            # a CLEAN stop clears the journal (every stream destroyed
            # above was forgotten): a later restart must not re-pin
            # streams this incarnation deliberately tore down
            self._journal_tick()
            self._stop_journal_tick()
            self.journal.stop()
            self.journal = None
        if self.election is not None:
            # clean handover LAST: the retained "(primary absent)" lets
            # a standby promote without waiting on our LWT, and it must
            # not fire until teardown has settled the journal -- a
            # standby racing our destroy loop could otherwise adopt
            # records we are mid-way through forgetting
            self.election.stop()
            self.election = None
        for replica in list(self.replicas.values()):
            self._detach_replica(replica)
        self.replicas.clear()
        if (self._services_cache is not None
                and self._discovery_handler is not None):
            self._services_cache.remove_handler(self._discovery_handler)
            self._discovery_handler = None
        super().stop()
