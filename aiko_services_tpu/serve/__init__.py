# serve/: the serving tier -- a Gateway actor fronting a pool of
# pipeline replicas with admission control (per-priority token buckets,
# SLO-aware shedding), least-loaded routing (power-of-two-choices over
# registrar-discovered replicas' EC load gauges), bounded backpressure
# with `(throttle ...)` signals to DataSources, and mid-stream failover
# that replays un-acknowledged frames on replica death.  See README
# "Serving gateway".

from .policy import AdmissionPolicy, TokenBucket          # noqa: F401
from .gateway import Gateway, SERVICE_PROTOCOL_GATEWAY    # noqa: F401
