# serve/: the serving tier -- a Gateway actor fronting a pool of
# pipeline replicas with admission control (per-priority token buckets,
# SLO-aware shedding), least-loaded routing (power-of-two-choices over
# registrar-discovered replicas' EC load gauges), bounded backpressure
# with `(throttle ...)` signals to DataSources, mid-stream failover
# that replays un-acknowledged frames on replica death, an elastic
# replica fleet (autoscale.py): watermark-driven scale up/down over the
# lifecycle layer with warm-start replicas (persistent compile cache +
# live sibling weight hand-off), and crash consistency (journal.py): a
# write-ahead journal of routing state plus hot-standby election so a
# gateway crash re-pins every stream exactly-once, and prefill/decode
# disaggregation (disagg.py): the gateway splits the pool by replica
# role, routes prompts through a prefill pool, and forwards the KV
# handoff to the stream's pinned decode replica.  See README "Serving
# gateway", "Elastic scaling", "Crash recovery", and "Disaggregated
# serving".

from .policy import AdmissionPolicy, TokenBucket          # noqa: F401
from .journal import (                                    # noqa: F401
    GatewayJournal, JournalPolicy)
from .disagg import (                                     # noqa: F401
    DISAGG_GRAMMAR, DisaggPolicy)
from .federation import (                                 # noqa: F401
    FEDERATION_GRAMMAR, FederationPolicy, FederationRouter,
    assign_group)
from .gateway import Gateway, SERVICE_PROTOCOL_GATEWAY    # noqa: F401
from .autoscale import (                                  # noqa: F401
    AutoScaler, InProcessReplicaFactory, ProcessReplicaFactory,
    ScalePolicy)
from .autopilot import (                                  # noqa: F401
    AUTOPILOT_GRAMMAR, AutoPilot, AutopilotPolicy,
    harvest_documents, tune_documents)
