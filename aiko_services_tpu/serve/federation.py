# Federated gateway tier: multiple HA gateway groups sharing one
# replica fleet, with streams assigned to groups by CONSISTENT HASH of
# the stream id.
#
# Why: one gateway actor is a single event loop -- at O(10k) concurrent
# streams its mailbox becomes the serving tier's ceiling.  Federation
# splits the stream space across G independent gateway groups (each
# optionally an HA pair via the existing `ha=<group>` RetainedElection,
# serve/gateway.py) that all front the SAME replica pool.  Because the
# assignment is a pure function of (stream id, group set), every
# client, every gateway, and every test computes the same placement
# with no coordination, and a group's crash-failover composes
# unchanged: journals are already namespaced per group
# ("{ns}/gateway/{group}/journal"), so the group's standby adopts
# exactly its own streams.
#
# Assignment is rendezvous (highest-random-weight) hashing over a
# stable digest -- adding or removing one group moves only ~1/G of the
# streams, and the hash is identical across processes and Python runs
# (hashlib, never the salted builtin hash()).
#
# Regions: real fleets have geography.  A group entry may carry a
# region label (`us:a` = group "a" in region "us"; unlabeled entries
# live in the anonymous region "").  Placement is then region-aware in
# two layers: a client-declared region affinity narrows the rendezvous
# domain to that region's groups when any survive, and the rendezvous
# hash is the fallback -- so losing one region's groups remaps ONLY the
# streams that lived there (the rendezvous property), and every other
# stream keeps its pin.
#
# Grammar (gateway parameter `federation`, the shared directive style):
#
#   policy    := directive (";" directive)*
#   directive := "groups=" entry ("," entry)*  the full group set (the
#                                              hash domain; identical
#                                              on every member)
#              | "group=" entry                THIS gateway's own group
#                                              (defaults to its ha
#                                              group, else its name)
#   entry     := [region ":"] name             region label optional,
#                                              "" region when absent
#
# Examples: "groups=g0,g1,g2,g3;group=g1"
#           "groups=us:a,us:b,eu:c;group=eu:c"
#
# A federated gateway REJECTS streams that hash to another group with
# the typed shed reason "wrong_group" -- a misconfigured client fails
# fast instead of splitting a stream's frames across groups.
# Validation is at parse time through the shared directive core
# (analyze/grammar.py): `aiko lint` checks it offline as AIKO410 with
# the same messages Gateway construction raises.

from __future__ import annotations

import hashlib

from ..analyze.grammar import DirectiveGrammar, Field, GrammarError

__all__ = ["FEDERATION_GRAMMAR", "FederationPolicy", "FederationRouter",
           "assign_group"]

FEDERATION_GRAMMAR = DirectiveGrammar(
    "federation policy",
    options={
        "groups": Field("str"),
        "group": Field("str"),
    })


def assign_group(stream_id, groups) -> str:
    """The federated tier's ONE placement rule: rendezvous hashing of
    `stream_id` over `groups`.  Pure and process-stable (md5, not the
    salted builtin hash), so clients and gateways agree with no
    coordination; ties break to the lexicographically first group."""
    stream_id = str(stream_id)
    best = None
    best_score = -1
    for group in sorted(groups):
        digest = hashlib.md5(
            f"{group}\x00{stream_id}".encode("utf-8")).digest()
        score = int.from_bytes(digest[:8], "big")
        if score > best_score:
            best, best_score = group, score
    if best is None:
        raise ValueError("assign_group needs a non-empty group set")
    return best


def split_region(entry: str) -> tuple[str, str]:
    """`us:a` -> ("us", "a"); an unlabeled `a` -> ("", "a")."""
    entry = str(entry).strip()
    if ":" in entry:
        region, _, name = entry.partition(":")
        return region.strip(), name.strip()
    return "", entry


class FederationPolicy:
    """Parsed federation spec: the full group set (with optional
    per-group region labels) plus this gateway's own group (None =
    derive from ha group / gateway name)."""

    __slots__ = ("groups", "group", "regions", "spec")

    def __init__(self):
        self.groups: tuple[str, ...] = ()
        self.group: str | None = None
        self.regions: dict[str, str] = {}
        self.spec = ""

    @classmethod
    def parse(cls, spec) -> "FederationPolicy":
        policy = cls()
        if spec is None or spec == "":
            return policy
        if isinstance(spec, FederationPolicy):
            return spec
        parsed = FEDERATION_GRAMMAR.parse(spec)
        if not isinstance(spec, dict):
            policy.spec = str(spec)
        raw = parsed.options.get("groups", "")
        if isinstance(raw, (list, tuple)):
            entries = [str(entry).strip() for entry in raw]
        else:
            entries = [entry.strip() for entry in str(raw).split(",")]
        entries = [entry for entry in entries if entry]
        if not entries:
            raise GrammarError(
                "federation policy: groups= needs at least one group "
                "name (e.g. groups=g0,g1 or groups=us:a,eu:b)")
        names = []
        regions: dict[str, str] = {}
        for entry in entries:
            region, name = split_region(entry)
            if not name:
                raise GrammarError(
                    f"federation policy: empty group name in "
                    f"groups entry {entry!r}")
            names.append(name)
            regions[name] = region
        if len(set(names)) != len(names):
            raise GrammarError(
                f"federation policy: duplicate group names in "
                f"groups={','.join(names)}")
        policy.groups = tuple(names)
        policy.regions = regions
        own = parsed.options.get("group")
        if own is not None:
            own_region, own = split_region(own)
            if own not in policy.groups:
                raise GrammarError(
                    f"federation policy: group={own!r} is not in "
                    f"groups={','.join(policy.groups)}")
            if own_region and regions.get(own, "") != own_region:
                raise GrammarError(
                    f"federation policy: group={own_region}:{own} "
                    f"disagrees with groups= (region "
                    f"{regions.get(own, '')!r} there)")
            policy.group = own
        return policy

    def region_of(self, group: str) -> str:
        return self.regions.get(group, "")

    def region_groups(self, region: str) -> tuple[str, ...]:
        """Every group living in `region` (hash-domain order)."""
        return tuple(group for group in self.groups
                     if self.regions.get(group, "") == region)

    def owner_of(self, stream_id, region=None, lost=()) -> str:
        """Region-aware placement: the client's declared region
        affinity narrows the rendezvous domain to that region's
        surviving groups when any exist; otherwise rendezvous over all
        survivors.  `lost` excludes dead groups, so a region outage
        remaps only that region's streams onto the survivors while
        every other stream keeps its original owner."""
        survivors = [group for group in self.groups if group not in lost]
        if not survivors:
            raise ValueError(
                "federation policy: every group is lost -- no owner "
                f"for stream {stream_id!r}")
        if region is not None:
            local = [group for group in survivors
                     if self.regions.get(group, "") == str(region)]
            if local:
                return assign_group(stream_id, local)
        return assign_group(stream_id, survivors)

    def __repr__(self):
        labeled = [(f"{self.regions[group]}:{group}"
                    if self.regions.get(group) else group)
                   for group in self.groups]
        return (f"FederationPolicy(groups={labeled}, "
                f"group={self.group})")


class FederationRouter:
    """Client-side stream placement over a federated tier: holds one
    gateway handle (or submit surface) per group and forwards each
    stream's calls to the group its id hashes to -- the same
    region-aware owner_of the gateways enforce, so a routed stream is
    never shed wrong_group.  Handles are anything with submit_stream /
    submit_frame / destroy-by-post (the Gateway local surface); tests
    and the bench use in-process Gateway objects directly.

    With a `policy` (or a `regions` map) the router is region-aware:
    `submit_stream(..., region="us")` records the affinity and injects
    it into the stream parameters so the owning gateway audits the
    same placement; `fail_group` / `heal_group` mark groups lost so
    subsequent placement (and the re-submission of adopted streams)
    lands on the survivors -- and each surviving in-process gateway is
    told via `note_group_lost` so it warms the lost group's journal
    mirror and adopts its share of the streams."""

    def __init__(self, gateways: dict, policy=None, regions=None):
        if not gateways:
            raise ValueError("FederationRouter needs at least one group")
        self.gateways = dict(gateways)
        self.groups = tuple(sorted(self.gateways))
        if policy is not None and not isinstance(policy, FederationPolicy):
            policy = FederationPolicy.parse(policy)
        if policy is None:
            policy = FederationPolicy()
            policy.groups = self.groups
            policy.regions = {group: "" for group in self.groups}
        if regions:
            policy.regions = dict(policy.regions)
            policy.regions.update(
                {str(group): str(region)
                 for group, region in dict(regions).items()})
        self.policy = policy
        self._lost: set[str] = set()
        self._stream_regions: dict[str, str] = {}

    @property
    def lost_groups(self) -> frozenset:
        return frozenset(self._lost)

    def fail_group(self, group: str) -> None:
        """Mark `group` dead for placement and tell every surviving
        in-process gateway so it adopts its rendezvous share of the
        lost group's journaled streams (warm-KV restore hints ride the
        migration, decode/checkpoint.py)."""
        group = str(group)
        if group not in self.gateways:
            raise ValueError(f"fail_group: unknown group {group!r}")
        if group in self._lost:
            return
        self._lost.add(group)
        for name, gateway in self.gateways.items():
            if name in self._lost:
                continue
            post = getattr(gateway, "post_message", None)
            if post is not None:
                post("note_group_lost", [group])

    def heal_group(self, group: str) -> None:
        group = str(group)
        if group not in self._lost:
            return
        self._lost.discard(group)
        for name, gateway in self.gateways.items():
            if name == group or name in self._lost:
                continue
            post = getattr(gateway, "post_message", None)
            if post is not None:
                post("note_group_healed", [group])

    def group_for(self, stream_id, region=None) -> str:
        if region is None:
            region = self._stream_regions.get(str(stream_id))
        return self.policy.owner_of(stream_id, region=region,
                                    lost=self._lost)

    def gateway_for(self, stream_id):
        return self.gateways[self.group_for(stream_id)]

    def submit_stream(self, stream_id, region=None, **kwargs) -> str:
        """Create the stream on its owner group (region affinity
        first, rendezvous fallback); returns the group name (callers
        correlate responses per group)."""
        stream_id = str(stream_id)
        if region is not None:
            self._stream_regions[stream_id] = str(region)
            parameters = dict(kwargs.get("parameters") or {})
            parameters.setdefault("region", str(region))
            kwargs["parameters"] = parameters
        group = self.group_for(stream_id, region=region)
        self.gateways[group].submit_stream(stream_id, **kwargs)
        return group

    def submit_frame(self, stream_id, frame_data, frame_id=None) -> None:
        self.gateway_for(stream_id).submit_frame(
            stream_id, frame_data, frame_id=frame_id)

    def destroy_stream(self, stream_id) -> None:
        stream_id = str(stream_id)
        gateway = self.gateway_for(stream_id)
        self._stream_regions.pop(stream_id, None)
        gateway.post_message("destroy_stream", [stream_id])
