# Federated gateway tier: multiple HA gateway groups sharing one
# replica fleet, with streams assigned to groups by CONSISTENT HASH of
# the stream id.
#
# Why: one gateway actor is a single event loop -- at O(10k) concurrent
# streams its mailbox becomes the serving tier's ceiling.  Federation
# splits the stream space across G independent gateway groups (each
# optionally an HA pair via the existing `ha=<group>` RetainedElection,
# serve/gateway.py) that all front the SAME replica pool.  Because the
# assignment is a pure function of (stream id, group set), every
# client, every gateway, and every test computes the same placement
# with no coordination, and a group's crash-failover composes
# unchanged: journals are already namespaced per group
# ("{ns}/gateway/{group}/journal"), so the group's standby adopts
# exactly its own streams.
#
# Assignment is rendezvous (highest-random-weight) hashing over a
# stable digest -- adding or removing one group moves only ~1/G of the
# streams, and the hash is identical across processes and Python runs
# (hashlib, never the salted builtin hash()).
#
# Grammar (gateway parameter `federation`, the shared directive style):
#
#   policy    := directive (";" directive)*
#   directive := "groups=" name ("," name)*   the full group set (the
#                                             hash domain; identical
#                                             on every member)
#              | "group=" name                THIS gateway's own group
#                                             (defaults to its ha
#                                             group, else its name)
#
# Example: "groups=g0,g1,g2,g3;group=g1"
#
# A federated gateway REJECTS streams that hash to another group with
# the typed shed reason "wrong_group" -- a misconfigured client fails
# fast instead of splitting a stream's frames across groups.
# Validation is at parse time through the shared directive core
# (analyze/grammar.py): `aiko lint` checks it offline as AIKO410 with
# the same messages Gateway construction raises.

from __future__ import annotations

import hashlib

from ..analyze.grammar import DirectiveGrammar, Field, GrammarError

__all__ = ["FEDERATION_GRAMMAR", "FederationPolicy", "FederationRouter",
           "assign_group"]

FEDERATION_GRAMMAR = DirectiveGrammar(
    "federation policy",
    options={
        "groups": Field("str"),
        "group": Field("str"),
    })


def assign_group(stream_id, groups) -> str:
    """The federated tier's ONE placement rule: rendezvous hashing of
    `stream_id` over `groups`.  Pure and process-stable (md5, not the
    salted builtin hash), so clients and gateways agree with no
    coordination; ties break to the lexicographically first group."""
    stream_id = str(stream_id)
    best = None
    best_score = -1
    for group in sorted(groups):
        digest = hashlib.md5(
            f"{group}\x00{stream_id}".encode("utf-8")).digest()
        score = int.from_bytes(digest[:8], "big")
        if score > best_score:
            best, best_score = group, score
    if best is None:
        raise ValueError("assign_group needs a non-empty group set")
    return best


class FederationPolicy:
    """Parsed federation spec: the full group set plus this gateway's
    own group (None = derive from ha group / gateway name)."""

    __slots__ = ("groups", "group", "spec")

    def __init__(self):
        self.groups: tuple[str, ...] = ()
        self.group: str | None = None
        self.spec = ""

    @classmethod
    def parse(cls, spec) -> "FederationPolicy":
        policy = cls()
        if spec is None or spec == "":
            return policy
        if isinstance(spec, FederationPolicy):
            return spec
        parsed = FEDERATION_GRAMMAR.parse(spec)
        if not isinstance(spec, dict):
            policy.spec = str(spec)
        raw = parsed.options.get("groups", "")
        if isinstance(raw, (list, tuple)):
            names = [str(name).strip() for name in raw]
        else:
            names = [name.strip() for name in str(raw).split(",")]
        names = [name for name in names if name]
        if not names:
            raise GrammarError(
                "federation policy: groups= needs at least one group "
                "name (e.g. groups=g0,g1)")
        if len(set(names)) != len(names):
            raise GrammarError(
                f"federation policy: duplicate group names in "
                f"groups={','.join(names)}")
        policy.groups = tuple(names)
        own = parsed.options.get("group")
        if own is not None:
            own = str(own).strip()
            if own not in policy.groups:
                raise GrammarError(
                    f"federation policy: group={own!r} is not in "
                    f"groups={','.join(policy.groups)}")
            policy.group = own
        return policy

    def owner_of(self, stream_id) -> str:
        return assign_group(stream_id, self.groups)

    def __repr__(self):
        return (f"FederationPolicy(groups={list(self.groups)}, "
                f"group={self.group})")


class FederationRouter:
    """Client-side stream placement over a federated tier: holds one
    gateway handle (or submit surface) per group and forwards each
    stream's calls to the group its id hashes to -- the same
    assign_group the gateways enforce, so a routed stream is never
    shed wrong_group.  Handles are anything with submit_stream /
    submit_frame / destroy-by-post (the Gateway local surface); tests
    and the bench use in-process Gateway objects directly."""

    def __init__(self, gateways: dict):
        if not gateways:
            raise ValueError("FederationRouter needs at least one group")
        self.gateways = dict(gateways)
        self.groups = tuple(sorted(self.gateways))

    def group_for(self, stream_id) -> str:
        return assign_group(stream_id, self.groups)

    def gateway_for(self, stream_id):
        return self.gateways[self.group_for(stream_id)]

    def submit_stream(self, stream_id, **kwargs) -> str:
        """Create the stream on its consistent-hash group; returns the
        group name (callers correlate responses per group)."""
        group = self.group_for(stream_id)
        self.gateways[group].submit_stream(stream_id, **kwargs)
        return group

    def submit_frame(self, stream_id, frame_data, frame_id=None) -> None:
        self.gateway_for(stream_id).submit_frame(
            stream_id, frame_data, frame_id=frame_id)

    def destroy_stream(self, stream_id) -> None:
        self.gateway_for(stream_id).post_message(
            "destroy_stream", [stream_id])
