# Elastic replica fleet: load-driven autoscaling with warm-start
# replicas.
#
# The gateway (serve/gateway.py) routes over a replica pool but, until
# this controller, the pool was FIXED: a 2x load swing either shed
# traffic forever or wasted idle chips.  The AutoScaler closes the loop
# the lifecycle layer (runtime/lifecycle.py, runtime/process_manager.py)
# was built for:
#
#   signal    the per-replica load gauges the gateway already mirrors --
#             `outstanding` routed frames per replica plus the parked
#             queue depth -- folded into one fleet utilization number
#             (demand / (live replicas x max_inflight))
#   decide    high watermark -> spawn a replica; low watermark -> drain
#             one; a cooldown between decisions stops flapping, and a
#             min/max band bounds the pool
#   spawn     a ReplicaFactory brings the replica up OFF the gateway's
#             event loop; warm start = a live sibling streams its
#             initialized params over the transfer plane
#             (Pipeline.export_weights / import_weights) AND the
#             persistent compile cache (runtime/compile_cache.py) turns
#             every fleet-known shape's XLA compile into a deserialize,
#             so time-to-healthy is hand-off + deserialize, not the
#             2-40 s-per-shape compile storm BENCH_NOTES documents
#   drain     scale-down re-pins the victim's streams and replays
#             cursors through the gateway's zero-loss failover path
#             (Gateway.drain_replica -> _migrate_streams): bit-identical
#             to an unscaled run, never a dropped frame
#
# The policy parses through the shared directive-grammar core
# (analyze/grammar.py), so a typo'd spec fails construction with the
# same AIKO406/AIKO404 codes `aiko lint` reports offline.

from __future__ import annotations

import threading
import time

from ..analyze.grammar import DirectiveGrammar, Field
from ..runtime.lease import Lease
from ..utils import get_logger

__all__ = ["AUTOSCALE_GRAMMAR", "AutoScaler", "InProcessReplicaFactory",
           "ProcessReplicaFactory", "ScalePolicy"]

_LOGGER = get_logger("autoscale")

DEFAULT_MIN_REPLICAS = 1
DEFAULT_MAX_REPLICAS = 2
DEFAULT_HIGH_WATER = 0.75
DEFAULT_LOW_WATER = 0.2
DEFAULT_COOLDOWN_S = 5.0
DEFAULT_DRAIN_TIMEOUT_S = 2.0
DEFAULT_INTERVAL_S = 0.5
DEFAULT_SPAWN_TIMEOUT_S = 300.0

# Grammar (gateway parameter `autoscale`, same directive style as the
# admission policy and fault spec):
#
#   policy    := directive (";" directive)*
#   directive := "min_replicas=" int    pool floor (repaired even inside
#                                       the cooldown window)
#              | "max_replicas=" int    pool ceiling
#              | "high_water=" float    fleet utilization that triggers
#                                       a spawn (demand / capacity)
#              | "low_water=" float     utilization that triggers a
#                                       drain-then-retire
#              | "cooldown=" float      seconds between scale decisions
#              | "drain_timeout=" float seconds a drained replica's
#                                       backing process lingers (its
#                                       in-flight responses settle;
#                                       replay covers the rest)
#              | "interval=" float      controller evaluation period
#              | "spawn_timeout=" float seconds before a spawn that
#                                       never became healthy is written
#                                       off (frees its pool slot)
#              | "warm_start=" flag     hand off sibling weights to new
#                                       replicas (default on)
#
# Example: "min_replicas=1;max_replicas=4;high_water=0.8;cooldown=10"
AUTOSCALE_GRAMMAR = DirectiveGrammar(
    "autoscale policy",
    options={
        "min_replicas": Field("int", minimum=0),
        "max_replicas": Field("int", minimum=1),
        "high_water": Field("float", minimum=0.0),
        "low_water": Field("float", minimum=0.0),
        "cooldown": Field("float", minimum=0.0),
        "drain_timeout": Field("float", minimum=0.0),
        "interval": Field("float", minimum=0.01),
        "spawn_timeout": Field("float", minimum=0.0),
        "warm_start": Field("flag"),
    })


class ScalePolicy:
    __slots__ = ("min_replicas", "max_replicas", "high_water",
                 "low_water", "cooldown_s", "drain_timeout_s",
                 "interval_s", "spawn_timeout_s", "warm_start", "spec")

    def __init__(self):
        self.min_replicas = DEFAULT_MIN_REPLICAS
        self.max_replicas = DEFAULT_MAX_REPLICAS
        self.high_water = DEFAULT_HIGH_WATER
        self.low_water = DEFAULT_LOW_WATER
        self.cooldown_s = DEFAULT_COOLDOWN_S
        self.drain_timeout_s = DEFAULT_DRAIN_TIMEOUT_S
        self.interval_s = DEFAULT_INTERVAL_S
        self.spawn_timeout_s = DEFAULT_SPAWN_TIMEOUT_S
        self.warm_start = True
        self.spec = ""

    @classmethod
    def parse(cls, spec) -> "ScalePolicy":
        """Parse a spec (directive string, dict of the same keys, or
        None for all defaults); cross-field constraints fail here so
        construction and offline lint stay one check."""
        policy = cls()
        if spec is None or spec == "":
            return policy
        if isinstance(spec, ScalePolicy):
            return spec
        parsed = AUTOSCALE_GRAMMAR.parse(spec)
        if not isinstance(spec, dict):
            policy.spec = str(spec)
        attributes = {
            "min_replicas": "min_replicas",
            "max_replicas": "max_replicas",
            "high_water": "high_water",
            "low_water": "low_water",
            "cooldown": "cooldown_s",
            "drain_timeout": "drain_timeout_s",
            "interval": "interval_s",
            "spawn_timeout": "spawn_timeout_s",
            "warm_start": "warm_start",
        }
        for key, value in parsed.options.items():
            setattr(policy, attributes[key], value)
        if policy.min_replicas > policy.max_replicas:
            raise ValueError(
                f"min_replicas {policy.min_replicas} must not exceed "
                f"max_replicas {policy.max_replicas}")
        if policy.low_water >= policy.high_water:
            raise ValueError(
                f"low_water {policy.low_water} must be below "
                f"high_water {policy.high_water} (equal watermarks "
                f"oscillate)")
        return policy

    def __repr__(self):
        return (f"ScalePolicy(replicas=[{self.min_replicas}, "
                f"{self.max_replicas}], water=[{self.low_water}, "
                f"{self.high_water}], cooldown={self.cooldown_s})")


class AutoScaler:
    """The gateway-owned controller: a periodic tick on the gateway's
    event loop (the same single-threaded scheduler that runs its
    mailbox, so every read of gateway state here is race-free) compares
    fleet utilization against the watermarks and drives the factory."""

    def __init__(self, gateway, policy=None, factory=None):
        try:
            self.policy = ScalePolicy.parse(policy)
        except ValueError as error:
            code = ("AIKO404" if getattr(error, "kind", "") == "unknown"
                    else "AIKO406")
            raise ValueError(
                f"{code}: autoscale policy rejected: {error}") from None
        self.gateway = gateway
        self.factory = factory
        self.pending = 0                  # spawns decided, not yet healthy
        self.spawns: list[dict] = []      # completed bring-up records
        self.draining: dict = {}          # topic_path -> retiring replica
        self._draining_handles: dict = {} # topic_path -> factory handle
        self._pending_spawns: dict = {}   # name -> decision record
        self._handles: dict = {}          # topic_path -> factory handle
        self._retiring: list[Lease] = []
        self._last_scale = 0.0
        self._below_low_since: float | None = None
        self._sequence = 0
        self._stopped = False
        # disaggregated fleets (gateway `disagg` policy + a factory
        # DICT {role: factory}): the two pools scale INDEPENDENTLY --
        # prefill on queue pressure, decode on slot occupancy -- each
        # with its own watermark state and per-pool floor
        self.disagg = getattr(gateway, "disagg", None)
        self._pool_state = {
            role: {"last_scale": 0.0, "below_low_since": None}
            for role in ("prefill", "decode")}
        self._pending_roles = {"prefill": 0, "decode": 0}
        self._handle_roles: dict = {}     # topic_path -> pool role
        self._last_prefill_fallbacks = 0
        gateway.process.event.add_timer_handler(
            self._tick, self.policy.interval_s)

    def _factory_for(self, role: str | None):
        if isinstance(self.factory, dict):
            return self.factory.get(role or "decode")
        # a single factory serves the one-pool (non-disagg) fleet and
        # the decode pool; it cannot spawn prefill replicas
        return self.factory if role in (None, "decode") else None

    # -- the control loop --------------------------------------------------

    def utilization(self) -> float | None:
        """Fleet demand / fleet capacity over LIVE (non-draining)
        replicas: routed frames in flight plus the gateway's parked
        queue, against pool_size x max_inflight.  None when there is
        neither capacity nor demand (an empty idle pool makes no
        decision); an empty pool WITH demand reads as infinite."""
        live = self._live()
        demand = (sum(replica.outstanding for replica in live)
                  + len(self.gateway._parked))
        capacity = len(live) * self.gateway.policy.max_inflight
        if capacity <= 0:
            return None if demand == 0 else float("inf")
        return demand / capacity

    def pool_utilization(self, role: str) -> float | None:
        """One disagg pool's scale signal.  The DECODE pool reads slot
        occupancy (routed frames + the parked queue over capacity),
        like the one-pool fleet.  The PREFILL pool reads QUEUE
        pressure: frames in flight at prefill replicas, frames queued
        inside them, and frames that fell back to local prefill since
        the last tick (demand the pool was too small to even see) --
        prefill work is one bounded kernel per frame, so waiting, not
        occupancy, is what blows TTFT."""
        live = self._live(role)
        if role == "prefill":
            fallbacks = self.gateway.telemetry.prefill_fallbacks.value
            delta = max(0, fallbacks - self._last_prefill_fallbacks)
            self._last_prefill_fallbacks = fallbacks
            demand = sum(replica.outstanding
                         + replica.reported_queue_depth()
                         for replica in live) + delta
        else:
            demand = (sum(replica.outstanding for replica in live)
                      + len(self.gateway._parked))
        capacity = len(live) * self.gateway.policy.max_inflight
        if capacity <= 0:
            return None if demand == 0 else float("inf")
        return demand / capacity

    def _live(self, role: str | None = None) -> list:
        return [replica for replica in self.gateway.replicas.values()
                if not replica.dead and not replica.draining
                and (role is None or replica.pool_role() == role)]

    def _tick(self) -> None:
        if self._stopped:
            return
        now = time.monotonic()
        if self.disagg is not None and isinstance(self.factory, dict):
            for role in ("decode", "prefill"):
                self._tick_pool(role, now)
            return
        live = self._live()
        size = len(live) + self.pending
        can_spawn = self.factory is not None
        if size < self.policy.min_replicas and can_spawn:
            # pool-floor repair ignores the cooldown: a death that drops
            # the fleet below min must heal now, not a cooldown later
            self._scale_up(now, live)
            return
        utilization = self.utilization()
        if utilization is None:
            return
        in_cooldown = now - self._last_scale < self.policy.cooldown_s
        if utilization > self.policy.low_water:
            self._below_low_since = None
        elif self._below_low_since is None:
            self._below_low_since = now
        if (utilization >= self.policy.high_water
                and size < self.policy.max_replicas
                and can_spawn
                and self.pending == 0 and not in_cooldown):
            self._scale_up(now, live)
        elif (self._below_low_since is not None
                # scale up fast, scale DOWN slow: one transiently idle
                # tick (a rejection storm between session retries reads
                # as zero demand) must not drain a replica the next
                # tick will need -- the low watermark has to hold for a
                # full cooldown window continuously
                and now - self._below_low_since >= self.policy.cooldown_s
                and len(live) > self.policy.min_replicas
                and self.pending == 0 and not in_cooldown):
            self._scale_down(now, live)
            self._below_low_since = None

    def _tick_pool(self, role: str, now: float) -> None:
        """One disagg pool's watermark pass: the same scale-up-fast /
        scale-down-slow state machine as the one-pool fleet, evaluated
        against THIS pool's signal, floor, and cooldown."""
        live = self._live(role)
        state = self._pool_state[role]
        pending = self._pending_roles[role]
        size = len(live) + pending
        floor = self.disagg.floor(role, self.policy.min_replicas)
        can_spawn = self._factory_for(role) is not None
        if size < floor and can_spawn:
            self._scale_up(now, live, role=role)
            return
        utilization = self.pool_utilization(role)
        if utilization is None:
            return
        in_cooldown = now - state["last_scale"] < self.policy.cooldown_s
        if utilization > self.policy.low_water:
            state["below_low_since"] = None
        elif state["below_low_since"] is None:
            state["below_low_since"] = now
        if (utilization >= self.policy.high_water
                and size < self.policy.max_replicas
                and can_spawn
                and pending == 0 and not in_cooldown):
            self._scale_up(now, live, role=role)
        elif (state["below_low_since"] is not None
                and now - state["below_low_since"]
                >= self.policy.cooldown_s
                and len(live) > floor
                and pending == 0 and not in_cooldown):
            self._scale_down(now, live, role=role)
            state["below_low_since"] = None

    # -- scale up ----------------------------------------------------------

    def _scale_up(self, now: float, live: list,
                  role: str | None = None) -> None:
        self._last_scale = now
        if role is not None:
            self._pool_state[role]["last_scale"] = now
        self._sequence += 1
        pool_tag = f"-{role}" if role is not None else ""
        name = f"{self.gateway.name}{pool_tag}-r{self._sequence}"
        warm_source = None
        if self.policy.warm_start:
            # warm-start from a SAME-POOL sibling: a prefill replica's
            # params are the right hand-off for a prefill spawn
            source = next((replica for replica in live
                           if replica.pipeline is not None), None)
            if source is not None:
                # hand the factory the SIBLING, not the exported tree:
                # export_weights copies every state leaf to host, and
                # this tick runs on the gateway's event loop at peak
                # overload -- the copy belongs on the spawn thread
                warm_source = source.pipeline
        warm = warm_source is not None
        self.pending += 1
        if role is not None:
            self._pending_roles[role] += 1
        self.gateway.telemetry.scale_ups.inc()
        record = self._pending_spawns[name] = {
            "decided": now, "warm": warm, "role": role}
        if self.policy.spawn_timeout_s > 0:
            # a spawn that never becomes healthy (child crashed during
            # bring-up, bad definition) must not hold its pool slot
            # forever -- `pending` gates every future scale decision
            record["lease"] = Lease(
                self.gateway.process.event, self.policy.spawn_timeout_s,
                name, lease_expired_handler=self._spawn_expired)
        _LOGGER.info("%s: scale UP -> spawning %s (%s%s)",
                     self.gateway.name, name,
                     "warm" if warm else "cold",
                     f", pool {role}" if role is not None else "")

        def ready(handle, info=None):
            # factory thread -> gateway CONTROL mailbox (see
            # Gateway._autoscale_ready)
            self.gateway.post_message("_autoscale_ready",
                                      [handle, info or {"name": name}])

        try:
            self._factory_for(role).spawn(name, warm_source=warm_source,
                                          ready=ready)
        except Exception as error:
            self._close_pending(name)
            _LOGGER.exception("%s: spawn %s failed to launch: %s",
                              self.gateway.name, name, error)

    def _close_pending(self, name: str):
        """Pop a pending-spawn record, stop its timeout lease, and free
        its pool slot; None when the name is not pending."""
        record = self._pending_spawns.pop(name, None)
        if record is None:
            return None
        lease = record.pop("lease", None)
        if lease is not None:
            lease.terminate()
        self.pending = max(0, self.pending - 1)
        role = record.get("role")
        if role is not None:
            self._pending_roles[role] = max(
                0, self._pending_roles[role] - 1)
        return record

    def _spawn_expired(self, name) -> None:
        if self._close_pending(str(name)) is not None:
            _LOGGER.error("%s: spawn %s never became healthy within "
                          "%.0f s; writing it off", self.gateway.name,
                          name, self.policy.spawn_timeout_s)

    def spawn_finished(self, handle, info: dict) -> None:
        """Mailbox continuation: the factory's bring-up finished (or
        failed).  In-process handles attach here; discovered (OS
        process) replicas attach through gateway.discover() and close
        their clock in note_replica_added instead."""
        name = str(info.get("name", ""))
        if info.get("error") or handle is None:
            self._close_pending(name)
            _LOGGER.error("%s: spawn %s failed: %s", self.gateway.name,
                          name, info.get("error", "no handle"))
            return
        record = self._pending_spawns.get(name)
        if record is None:
            # already written off (spawn_timeout lapsed and the slot
            # was re-planned): attaching this late arrival would push
            # the pool past max_replicas -- retire it instead
            _LOGGER.warning("%s: spawn %s finished after being written "
                            "off; retiring it", self.gateway.name, name)
            try:
                if self.factory is not None:
                    self._retire_handle(handle)
            except Exception:
                _LOGGER.exception("%s: late-spawn retire failed",
                                  self.gateway.name)
            return
        record.update({key: value for key, value in info.items()
                       if key != "name"})
        if "imported_elements" in info:
            # the factory resolves the hand-off now: a failed export
            # downgrades the spawn to cold, truthfully
            record["warm"] = bool(info["imported_elements"])
        pipeline = getattr(handle, "pipeline", None)
        if pipeline is None:
            # a handle the gateway cannot attach: close the books so
            # `pending` cannot wedge every future scale-up
            self._close_pending(name)
            _LOGGER.error("%s: spawn %s returned a handle without a "
                          ".pipeline; dropped", self.gateway.name, name)
            return
        self._handles[pipeline.topic_path] = handle
        self._handle_roles[pipeline.topic_path] = record.get("role")
        self.gateway.attach_replica(
            pipeline, warm=bool(record and record.get("warm")),
            role=record.get("role"))
        if name in self._pending_spawns:
            # attach ran note_replica_added synchronously; the record
            # still pending means the pipeline's name does not match
            # the spawn name (a callable definition ignoring `name`) --
            # close the books rather than wedging the controller
            self._close_pending(name)
            _LOGGER.warning("%s: spawn %s attached as %r (name "
                            "mismatch); bring-up stats dropped",
                            self.gateway.name, name, pipeline.name)

    def note_replica_added(self, replica) -> None:
        """Called from Gateway._add_replica for EVERY join: when the
        name matches a pending spawn, the time-to-healthy clock stops
        here -- the replica is attached and placeable."""
        record = self._close_pending(replica.name)
        if record is None:
            return
        replica.warm = bool(record.get("warm"))
        if replica.topic_path not in self._handles:
            # discovered (OS process) replica: the factory retires it
            # by NAME through the lifecycle layer
            self._handles[replica.topic_path] = replica.name
            self._handle_roles[replica.topic_path] = record.get("role")
        elapsed_ms = (time.monotonic() - record["decided"]) * 1000.0
        self.gateway.telemetry.record_spawn(elapsed_ms, replica.warm)
        entry = {"name": replica.name, "warm": replica.warm,
                 "time_to_healthy_ms": round(elapsed_ms, 2)}
        for key in ("cache_hits", "cache_misses", "imported_elements"):
            if key in record:
                entry[key] = record[key]
        self.spawns.append(entry)
        _LOGGER.info("%s: replica %s healthy in %.0f ms (%s)",
                     self.gateway.name, replica.name, elapsed_ms,
                     "warm" if replica.warm else "cold")

    def _retire_handle(self, handle, role: str | None = None) -> None:
        """Retire a handle through the owning factory; with a factory
        dict and no known role, every factory is offered the handle
        (retire is a tolerant no-op on a handle it never spawned)."""
        factory = self._factory_for(role)
        if factory is not None:
            factory.retire(handle)
            return
        if isinstance(self.factory, dict):
            for candidate in self.factory.values():
                candidate.retire(handle)

    # -- scale down --------------------------------------------------------

    def _scale_down(self, now: float, live: list,
                    role: str | None = None) -> None:
        if self.factory is not None:
            # only retire replicas this controller OWNS: draining a
            # discovered/manually-attached replica would leave its
            # process running detached forever (it never rejoins -- the
            # registrar entry predates the drain, so discovery fires no
            # new "add").  With no factory at all the pool is operator-
            # managed and a pure drain is exactly what was asked for
            candidates = [replica for replica in live
                          if replica.topic_path in self._handles]
        else:
            candidates = live
        if not candidates:
            return
        victim = min(candidates,
                     key=lambda replica: (replica.outstanding,
                                          len(replica.streams),
                                          replica.topic_path))
        self._last_scale = now
        if role is not None:
            self._pool_state[role]["last_scale"] = now
        replica = self.gateway.drain_replica(victim.topic_path,
                                             "low watermark")
        if replica is None:
            return
        self.gateway.telemetry.scale_downs.inc()
        handle = self._handles.pop(replica.topic_path, None)
        if handle is None:
            # not factory-owned (manually attached / discovered without
            # a spawn record): draining it out of the pool is all the
            # controller may do
            return
        # visible in pool_snapshot as state "draining" until retirement
        self.draining[replica.topic_path] = replica
        self._draining_handles[replica.topic_path] = handle
        if self.policy.drain_timeout_s <= 0:
            self._retire(replica.topic_path, handle, None)
            return
        # linger: responses already computed on the victim settle (and
        # dedupe against the replay) before the process goes away
        lease = Lease(
            self.gateway.process.event, self.policy.drain_timeout_s,
            replica.topic_path,
            lease_expired_handler=lambda _uuid: self._retire(
                replica.topic_path, handle, lease))
        self._retiring.append(lease)

    def _retire(self, topic_path, handle, lease) -> None:
        self.draining.pop(topic_path, None)
        self._draining_handles.pop(topic_path, None)
        if lease is not None and lease in self._retiring:
            self._retiring.remove(lease)  # fired: stop tracking it
        try:
            self._retire_handle(handle,
                                self._handle_roles.pop(topic_path, None))
        except Exception:
            _LOGGER.exception("%s: replica retire failed",
                              self.gateway.name)

    def stop(self) -> None:
        self._stopped = True
        self.gateway.process.event.remove_timer_handler(self._tick)
        for record in list(self._pending_spawns.values()):
            lease = record.pop("lease", None)
            if lease is not None:
                lease.terminate()
        self._pending_spawns.clear()
        for lease in list(self._retiring):
            lease.terminate()
        self._retiring.clear()
        # drains caught mid-linger: their backing processes still
        # belong to the factory -- retire NOW or nobody ever will
        for topic_path, handle in list(self._draining_handles.items()):
            self._retire(topic_path, handle, None)
        self.draining.clear()
        # factory-owned LIVE replicas die with their controller too: a
        # stopped gateway must not strand the fleet it spawned
        if self.factory is not None:
            for topic_path, handle in list(self._handles.items()):
                try:
                    self._retire_handle(
                        handle, self._handle_roles.get(topic_path))
                except Exception:
                    _LOGGER.exception("%s: replica retire failed",
                                      self.gateway.name)
        self._handles.clear()
        self._handle_roles.clear()


class _SpawnHandle:
    __slots__ = ("name", "process", "pipeline")

    def __init__(self, name, process, pipeline):
        self.name = name
        self.process = process
        self.pipeline = pipeline


def _resolve_exports(warm_source):
    """Factory-side half of the hand-off, run on the SPAWN thread
    (export_weights copies every state leaf to host -- never on the
    gateway's event loop): a live sibling Pipeline, an already-exported
    descriptor tree, or None."""
    if warm_source is None:
        return None
    if isinstance(warm_source, dict):
        return warm_source
    return warm_source.export_weights()


class InProcessReplicaFactory:
    """Replicas as in-process Pipelines, each on its own virtual
    Process (threaded, shared loopback broker) -- the bench/test
    topology, and the warm-start proof surface: the spawn thread
    enables the persistent compile cache, imports the sibling's weights
    over the transfer plane, and probes one warmup frame so "healthy"
    means "served a frame", with the compile-cache hit/miss delta for
    the whole bring-up recorded into the spawn info."""

    def __init__(self, definition, transport: str = "loopback",
                 warmup=None, compile_cache: str | None = None,
                 probe_timeout: float = 120.0):
        # definition: dict template (name overridden per spawn) or a
        # callable name -> definition dict
        self._definition = definition
        self.transport = transport
        self.warmup = warmup            # frame_data dict for the probe
        self.compile_cache = compile_cache
        self.probe_timeout = probe_timeout

    def definition_for(self, name: str) -> dict:
        if callable(self._definition):
            return self._definition(name)
        definition = dict(self._definition)
        definition["name"] = name
        return definition

    def spawn(self, name: str, warm_source=None, ready=None):
        thread = threading.Thread(
            target=self._bring_up, args=(name, warm_source, ready),
            name=f"autoscale-spawn-{name}", daemon=True)
        thread.start()
        return thread

    def _bring_up(self, name, warm_source, ready) -> None:
        process = None
        try:
            from ..pipeline import create_pipeline
            from ..runtime import Process
            from ..runtime.compile_cache import (
                enable_compile_cache, thread_cache_delta,
                thread_cache_snapshot)
            if self.compile_cache:
                enable_compile_cache(self.compile_cache)
            try:
                warm_exports = _resolve_exports(warm_source)
            except Exception:
                _LOGGER.exception("replica %s: sibling weight export "
                                  "failed; bringing up cold", name)
                warm_exports = None
            # compile accounting is scoped to THIS bring-up's threads
            # (the spawn thread and the new replica's event loop):
            # sibling replicas in the same OS process may compile
            # concurrently, and their traffic must not pollute the
            # warm-start proof
            before = thread_cache_snapshot()
            process = Process(transport_kind=self.transport)
            pipeline = create_pipeline(process,
                                       self.definition_for(name))
            imported = []
            if warm_exports:
                try:
                    imported = pipeline.import_weights(warm_exports)
                except Exception:
                    # a failed hand-off (expired transfer keys, drained
                    # sibling) downgrades to a COLD start, like the
                    # OS-process path -- a scale-up at peak overload
                    # must still add capacity
                    _LOGGER.exception("replica %s: weight import "
                                      "failed; continuing cold", name)
                    imported = []
            loop_thread = process.run(in_thread=True)
            if self.warmup is not None:
                self._probe(pipeline)
            delta = thread_cache_delta(
                before, thread_cache_snapshot(),
                {threading.get_ident(),
                 getattr(loop_thread, "ident", None)})
            info = {
                "name": name,
                "cache_hits": delta["hits"],
                "cache_misses": delta["misses"],
                "imported_elements": imported,
            }
            ready(_SpawnHandle(name, process, pipeline), info)
        except Exception as error:
            _LOGGER.exception("replica %s bring-up failed", name)
            if process is not None:
                try:  # never leak a half-built replica's event loop
                    process.terminate()
                except Exception:
                    pass
            if ready is not None:
                ready(None, {"name": name, "error": str(error)})

    def _probe(self, pipeline) -> None:
        """One warmup frame through a private stream: forces setup +
        compile (persistent-cache hits for fleet-known shapes) so the
        replica joins the pool serving-ready, and time-to-healthy
        measures first-frame readiness, not object construction."""
        import queue as queue_module
        responses = queue_module.Queue()
        stream_id = f"_warmup_{pipeline.name}"
        stream = pipeline.create_stream(stream_id,
                                        queue_response=responses,
                                        grace_time=self.probe_timeout)
        pipeline.create_frame(stream, dict(self.warmup))
        responses.get(timeout=self.probe_timeout)
        pipeline.destroy_stream(stream_id)

    def retire(self, handle) -> None:
        if isinstance(handle, _SpawnHandle):
            handle.process.terminate()


class ProcessReplicaFactory:
    """OS-process replicas driven through LifeCycleManager /
    ProcessManager: spawn() creates a lifecycle client running
    `python -m aiko_services_tpu pipeline <definition> --name <name>`
    with an env OVERLAY (merged over os.environ by ProcessManager) that
    pins JAX_PLATFORMS, the persistent compile-cache directory
    (AIKO_COMPILE_CACHE), and -- when a sibling exported weights -- an
    AIKO_WARM_WEIGHTS descriptor file the child imports over the
    transfer plane before serving.  The gateway attaches the replica
    when registrar discovery sees it (gateway.discover), which closes
    the autoscaler's time-to-healthy clock; retire() runs the lifecycle
    layer's graceful delete (terminate, deletion lease, SIGKILL
    escalation)."""

    def __init__(self, lifecycle_manager, definition_path: str,
                 transport: str | None = None, env: dict | None = None,
                 compile_cache: str | None = None):
        self.lifecycle_manager = lifecycle_manager
        self.definition_path = str(definition_path)
        self.transport = transport
        self.env = dict(env or {})
        self.compile_cache = compile_cache
        self._clients: dict = {}      # name -> lifecycle client id

    def spawn(self, name: str, warm_source=None, ready=None):
        # launched off-thread: the sibling weight export (device-to-
        # host copy of the whole parameter set) must not run on the
        # gateway's event loop, which is where the autoscaler tick
        # calls spawn()
        thread = threading.Thread(
            target=self._launch, args=(name, warm_source),
            name=f"autoscale-launch-{name}", daemon=True)
        thread.start()
        return thread

    def _launch(self, name: str, warm_source) -> None:
        import json
        import sys
        import tempfile
        env = dict(self.env)
        if self.compile_cache:
            env["AIKO_COMPILE_CACHE"] = str(self.compile_cache)
        try:
            warm_exports = _resolve_exports(warm_source)
        except Exception:
            _LOGGER.exception("replica %s: sibling weight export "
                              "failed; spawning cold", name)
            warm_exports = None
        if warm_exports:
            handoff = tempfile.NamedTemporaryFile(
                "w", prefix=f"aiko_warm_{name}_", suffix=".json",
                delete=False)
            json.dump(warm_exports, handoff)
            handoff.close()
            # the CHILD unlinks the file after a successful import
            # (cli.py); it only lives this long so a crashed child can
            # be respawned against the same descriptors
            env["AIKO_WARM_WEIGHTS"] = handoff.name
        arguments = ["-m", "aiko_services_tpu", "pipeline",
                     self.definition_path, "--name", name]
        if self.transport:
            arguments += ["--transport", self.transport]
        self._clients[name] = self.lifecycle_manager.create_client(
            sys.executable, arguments, use_interpreter=False, env=env)
        # no ready() here: the replica becomes healthy when registrar
        # discovery attaches it (AutoScaler.note_replica_added)

    def retire(self, handle) -> None:
        name = getattr(handle, "name", handle)
        client_id = self._clients.pop(str(name), None)
        if client_id is not None:
            self.lifecycle_manager.delete_client(client_id)
