# Admission policy for the serving gateway: WHO gets in, HOW MUCH each
# replica carries, and WHEN the tier sheds instead of queueing.
#
# Grammar (gateway parameter `policy`, same directive style as the
# fault-harness spec so operators learn one shape):
#
#   policy    := directive (";" directive)*
#   directive := "max_inflight=" int     frames in flight per replica
#              | "queue=" int            bounded parked-frame queue length
#              | "hysteresis=" float     seconds a saturated replica must
#                                        stay below half cap to rejoin
#                                        stream placement
#              | "stale_after=" float    seconds without an EC share
#                                        update before a discovered
#                                        replica's load view is distrusted
#              | "throttle_high=" float  queue fraction that triggers
#                                        `(throttle ...)` to sources
#              | "throttle_low=" float   queue fraction that lifts it
#              | "throttle_rate=" float  frames/sec cap sent to throttled
#                                        sources
#              | "frame_deadline=" float seconds, injected into replica
#                                        streams (PR 3 machinery: a
#                                        wedged replica releases frames
#                                        by dead-letter instead of
#                                        leaking gateway slots)
#              | "bucket:" prio "=" rate "/" burst
#                                        per-priority token bucket for
#                                        STREAM admission (priority 0 is
#                                        most important; priorities
#                                        without a bucket admit freely)
#              | "bucket:tenant:" name "=" rate "/" burst
#                                        per-TENANT token bucket --
#                                        streams declaring parameter
#                                        `tenant=<name>` draw from
#                                        their tenant's bucket IN
#                                        ADDITION to their priority
#                                        bucket, so one tenant's storm
#                                        exhausts its own tokens, never
#                                        another tenant's admission
#
# Example: "max_inflight=8;queue=64;hysteresis=0.5;bucket:2=10/4"
#          "bucket:tenant:gold=100/20;bucket:tenant:free=10/4"
#
# Validation is at parse time, like the pipeline-definition and fault
# grammars: a typo'd policy must fail the gateway's construction, not
# silently admit everything.

from __future__ import annotations

from ..analyze.grammar import DirectiveGrammar, Field

__all__ = ["AdmissionPolicy", "POLICY_GRAMMAR", "TokenBucket"]

DEFAULT_MAX_INFLIGHT = 8
DEFAULT_QUEUE_CAPACITY = 64
DEFAULT_HYSTERESIS_S = 0.5
DEFAULT_STALE_AFTER_S = 15.0
DEFAULT_THROTTLE_HIGH = 0.5
DEFAULT_THROTTLE_LOW = 0.125
DEFAULT_THROTTLE_RATE = 5.0


def _parse_bucket(tail, value):
    """`bucket:P=rate/burst` -> (priority, rate, burst);
    `bucket:tenant:NAME=rate/burst` -> (("tenant", name), rate, burst).
    Dict-shaped specs may carry (rate, burst) tuples."""
    tail = str(tail)
    if tail.startswith("tenant:"):
        tenant = tail[len("tenant:"):].strip()
        if not tenant:
            raise ValueError(
                "bucket:tenant:<name>= needs a non-empty tenant name")
        key = ("tenant", tenant)
    else:
        key = int(tail)
    if isinstance(value, (tuple, list)):
        rate, burst = value
    else:
        rate, _, burst = str(value).partition("/")
    return key, float(rate), float(burst or rate)


# The grammar above as a declarative table over the shared
# directive-grammar core (analyze/grammar.py): Gateway construction and
# `aiko lint` (AIKO403) validate through the SAME definition.  Range
# handling keeps the historical clamping semantics (max_inflight
# clamps up to 1, queue down to 0) -- the grammar rejects unknown
# directives and untypeable values, the policy clamps domains.
POLICY_GRAMMAR = DirectiveGrammar(
    "gateway policy",
    options={
        "max_inflight": Field("int"),
        "queue": Field("int"),
        "hysteresis": Field("float"),
        "stale_after": Field("float"),
        "throttle_high": Field("float"),
        "throttle_low": Field("float"),
        "throttle_rate": Field("float"),
        "frame_deadline": Field("float"),
    },
    prefixes={"bucket": _parse_bucket})


class TokenBucket:
    """Classic token bucket with caller-supplied time: `now` is always
    passed in (monotonic seconds) so tests drive it deterministically
    and the gateway pays no clock read when no bucket is configured."""

    __slots__ = ("rate", "burst", "tokens", "updated")

    def __init__(self, rate: float, burst: float):
        if rate <= 0 or burst <= 0:
            raise ValueError(
                f"token bucket needs rate > 0 and burst > 0, got "
                f"{rate}/{burst}")
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self.updated: float | None = None

    def try_take(self, now: float) -> bool:
        if self.updated is not None:
            self.tokens = min(
                self.burst, self.tokens + (now - self.updated) * self.rate)
        self.updated = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


class AdmissionPolicy:
    __slots__ = ("max_inflight", "queue_capacity", "hysteresis_s",
                 "stale_after_s", "throttle_high", "throttle_low",
                 "throttle_rate", "frame_deadline_s", "buckets",
                 "tenant_buckets", "spec")

    def __init__(self):
        self.max_inflight = DEFAULT_MAX_INFLIGHT
        self.queue_capacity = DEFAULT_QUEUE_CAPACITY
        self.hysteresis_s = DEFAULT_HYSTERESIS_S
        self.stale_after_s = DEFAULT_STALE_AFTER_S
        self.throttle_high = DEFAULT_THROTTLE_HIGH
        self.throttle_low = DEFAULT_THROTTLE_LOW
        self.throttle_rate = DEFAULT_THROTTLE_RATE
        self.frame_deadline_s = 0.0
        self.buckets: dict[int, TokenBucket] = {}
        self.tenant_buckets: dict[str, TokenBucket] = {}
        self.spec = ""

    @classmethod
    def parse(cls, spec) -> "AdmissionPolicy":
        """Parse a policy spec (str in the grammar above, a dict of the
        same keys, or None for all defaults)."""
        policy = cls()
        if spec is None or spec == "":
            return policy
        if isinstance(spec, AdmissionPolicy):
            return spec
        parsed = POLICY_GRAMMAR.parse(spec)
        if not isinstance(spec, dict):
            policy.spec = str(spec)
        clamps = {
            "max_inflight": lambda v: max(1, v),
            "queue": lambda v: max(0, v),
            "hysteresis": lambda v: max(0.0, v),
            "stale_after": lambda v: max(0.0, v),
            "frame_deadline": lambda v: max(0.0, v),
        }
        attributes = {
            "max_inflight": "max_inflight",
            "queue": "queue_capacity",
            "hysteresis": "hysteresis_s",
            "stale_after": "stale_after_s",
            "throttle_high": "throttle_high",
            "throttle_low": "throttle_low",
            "throttle_rate": "throttle_rate",
            "frame_deadline": "frame_deadline_s",
        }
        for key, value in parsed.options.items():
            clamp = clamps.get(key)
            setattr(policy, attributes[key],
                    clamp(value) if clamp else value)
        for _, _, (key, rate, burst) in parsed.prefixed:
            if isinstance(key, tuple):
                policy.tenant_buckets[key[1]] = TokenBucket(rate, burst)
            else:
                policy.buckets[key] = TokenBucket(rate, burst)
        if policy.throttle_low > policy.throttle_high:
            raise ValueError(
                f"throttle_low {policy.throttle_low} must not exceed "
                f"throttle_high {policy.throttle_high}")
        return policy

    def bucket_for(self, priority: int) -> TokenBucket | None:
        return self.buckets.get(int(priority))

    def tenant_bucket_for(self, tenant) -> TokenBucket | None:
        """The per-tenant admission bucket, or None when the tenant is
        unnamed or unbucketed (unbucketed tenants admit freely -- the
        grammar only constrains tenants it names)."""
        if not tenant:
            return None
        return self.tenant_buckets.get(str(tenant))

    def __repr__(self):
        return (f"AdmissionPolicy(max_inflight={self.max_inflight}, "
                f"queue={self.queue_capacity}, "
                f"hysteresis={self.hysteresis_s}, "
                f"buckets={sorted(self.buckets)}, "
                f"tenants={sorted(self.tenant_buckets)})")
