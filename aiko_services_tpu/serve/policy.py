# Admission policy for the serving gateway: WHO gets in, HOW MUCH each
# replica carries, and WHEN the tier sheds instead of queueing.
#
# Grammar (gateway parameter `policy`, same directive style as the
# fault-harness spec so operators learn one shape):
#
#   policy    := directive (";" directive)*
#   directive := "max_inflight=" int     frames in flight per replica
#              | "queue=" int            bounded parked-frame queue length
#              | "hysteresis=" float     seconds a saturated replica must
#                                        stay below half cap to rejoin
#                                        stream placement
#              | "stale_after=" float    seconds without an EC share
#                                        update before a discovered
#                                        replica's load view is distrusted
#              | "throttle_high=" float  queue fraction that triggers
#                                        `(throttle ...)` to sources
#              | "throttle_low=" float   queue fraction that lifts it
#              | "throttle_rate=" float  frames/sec cap sent to throttled
#                                        sources
#              | "frame_deadline=" float seconds, injected into replica
#                                        streams (PR 3 machinery: a
#                                        wedged replica releases frames
#                                        by dead-letter instead of
#                                        leaking gateway slots)
#              | "bucket:" prio "=" rate "/" burst
#                                        per-priority token bucket for
#                                        STREAM admission (priority 0 is
#                                        most important; priorities
#                                        without a bucket admit freely)
#
# Example: "max_inflight=8;queue=64;hysteresis=0.5;bucket:2=10/4"
#
# Validation is at parse time, like the pipeline-definition and fault
# grammars: a typo'd policy must fail the gateway's construction, not
# silently admit everything.

from __future__ import annotations

__all__ = ["AdmissionPolicy", "TokenBucket"]

DEFAULT_MAX_INFLIGHT = 8
DEFAULT_QUEUE_CAPACITY = 64
DEFAULT_HYSTERESIS_S = 0.5
DEFAULT_STALE_AFTER_S = 15.0
DEFAULT_THROTTLE_HIGH = 0.5
DEFAULT_THROTTLE_LOW = 0.125
DEFAULT_THROTTLE_RATE = 5.0


class TokenBucket:
    """Classic token bucket with caller-supplied time: `now` is always
    passed in (monotonic seconds) so tests drive it deterministically
    and the gateway pays no clock read when no bucket is configured."""

    __slots__ = ("rate", "burst", "tokens", "updated")

    def __init__(self, rate: float, burst: float):
        if rate <= 0 or burst <= 0:
            raise ValueError(
                f"token bucket needs rate > 0 and burst > 0, got "
                f"{rate}/{burst}")
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self.updated: float | None = None

    def try_take(self, now: float) -> bool:
        if self.updated is not None:
            self.tokens = min(
                self.burst, self.tokens + (now - self.updated) * self.rate)
        self.updated = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


class AdmissionPolicy:
    __slots__ = ("max_inflight", "queue_capacity", "hysteresis_s",
                 "stale_after_s", "throttle_high", "throttle_low",
                 "throttle_rate", "frame_deadline_s", "buckets", "spec")

    def __init__(self):
        self.max_inflight = DEFAULT_MAX_INFLIGHT
        self.queue_capacity = DEFAULT_QUEUE_CAPACITY
        self.hysteresis_s = DEFAULT_HYSTERESIS_S
        self.stale_after_s = DEFAULT_STALE_AFTER_S
        self.throttle_high = DEFAULT_THROTTLE_HIGH
        self.throttle_low = DEFAULT_THROTTLE_LOW
        self.throttle_rate = DEFAULT_THROTTLE_RATE
        self.frame_deadline_s = 0.0
        self.buckets: dict[int, TokenBucket] = {}
        self.spec = ""

    @classmethod
    def parse(cls, spec) -> "AdmissionPolicy":
        """Parse a policy spec (str in the grammar above, a dict of the
        same keys, or None for all defaults)."""
        policy = cls()
        if spec is None or spec == "":
            return policy
        if isinstance(spec, AdmissionPolicy):
            return spec
        if isinstance(spec, dict):
            items = list(spec.items())
        else:
            items = []
            for part in str(spec).split(";"):
                part = part.strip()
                if not part:
                    continue
                key, sep, value = part.partition("=")
                if not sep:
                    raise ValueError(
                        f"policy directive {part!r} is not key=value")
                items.append((key.strip(), value.strip()))
            policy.spec = str(spec)
        for key, value in items:
            if key.startswith("bucket:"):
                priority = int(key.split(":", 1)[1])
                if isinstance(value, (tuple, list)):
                    rate, burst = value
                else:
                    rate, _, burst = str(value).partition("/")
                policy.buckets[priority] = TokenBucket(
                    float(rate), float(burst or rate))
            elif key == "max_inflight":
                policy.max_inflight = max(1, int(value))
            elif key == "queue":
                policy.queue_capacity = max(0, int(value))
            elif key == "hysteresis":
                policy.hysteresis_s = max(0.0, float(value))
            elif key == "stale_after":
                policy.stale_after_s = max(0.0, float(value))
            elif key == "throttle_high":
                policy.throttle_high = float(value)
            elif key == "throttle_low":
                policy.throttle_low = float(value)
            elif key == "throttle_rate":
                policy.throttle_rate = float(value)
            elif key == "frame_deadline":
                policy.frame_deadline_s = max(0.0, float(value))
            else:
                raise ValueError(f"unknown policy directive: {key!r}")
        if policy.throttle_low > policy.throttle_high:
            raise ValueError(
                f"throttle_low {policy.throttle_low} must not exceed "
                f"throttle_high {policy.throttle_high}")
        return policy

    def bucket_for(self, priority: int) -> TokenBucket | None:
        return self.buckets.get(int(priority))

    def __repr__(self):
        return (f"AdmissionPolicy(max_inflight={self.max_inflight}, "
                f"queue={self.queue_capacity}, "
                f"hysteresis={self.hysteresis_s}, "
                f"buckets={sorted(self.buckets)})")
