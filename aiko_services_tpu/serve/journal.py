# Gateway crash journal: the write-ahead record that makes the serving
# tier's FRONT DOOR crash-consistent.
#
# PR 4 made replica death invisible (cursor replay + exactly-once
# dedupe), but the gateway itself held the entire routing truth --
# stream->replica pins, replay cursors, dedupe high-water marks,
# admission-bucket levels -- in process memory: one gateway crash
# stranded every active stream, which contradicts the north star of
# serving heavy traffic from millions of users.  This module journals
# that state so a RESTARTED gateway, or a hot STANDBY elected through
# the registrar's retained-topic election (runtime/registrar.py
# RetainedElection), rebuilds the table and resumes with the same
# exactly-once guarantee replica failover already provides:
#
#   what          stream pins, cursors, delivered floors (dedupe),
#                 bucket token levels -- METADATA only, never frame
#                 payloads (clients replay un-acked frame DATA; the
#                 journal guarantees the replay is deduped exactly-once).
#                 With warm KV failover (decode/checkpoint.py) a
#                 record also carries the stream's checkpoint KEEPER
#                 name, so a promoted standby's decode-replica
#                 failovers restore from the same keeper the dead
#                 primary's would have
#   when          stream admission / destruction is journaled at the
#                 NEXT tick boundary along with the hot per-frame state
#                 (cursor, floor), batched per `interval` tick -- one
#                 backend write per tick, not one per frame
#   where         the sqlite KV backend shared with runtime/storage.py
#                 (`backend=sqlite;path=...`), or a retained-topic
#                 mirror (`backend=retained`) when no disk is wanted:
#                 retained messages ARE the broker's journal, and a hot
#                 standby mirrors them continuously so takeover replay
#                 is a dict read, not an I/O wait
#   staleness     every record carries `expires_at` (the stream lease,
#                 refreshed on activity); replay DROPS expired entries
#                 instead of re-pinning dead streams to dead replicas,
#                 and a periodic compaction (`compact_every` ticks)
#                 purges them from the store
#
# Policy grammar (gateway parameter `journal`, rule code AIKO407,
# parsed through the shared directive core exactly like the admission /
# autoscale policies):
#
#   spec      := directive (";" directive)*
#   directive := "interval=" float        flush tick seconds (the crash
#                                         window: state younger than
#                                         one tick may replay from the
#                                         client instead of the journal)
#              | "backend=" sqlite|retained
#              | "path=" str              sqlite database file (required
#                                         for backend=sqlite)
#              | "compact_every=" int     ticks between expiry sweeps
#              | "search_timeout=" float  HA election search window
#              | "replay_timeout=" float  cold-start wait for retained
#                                         replay before adoption
#
# Example: "backend=sqlite;path=/var/aiko/gw.db;interval=0.05"

from __future__ import annotations

import json

from ..analyze.grammar import DirectiveGrammar, Field
from ..utils import epoch_now, get_logger

__all__ = ["GatewayJournal", "JournalPolicy", "JOURNAL_GRAMMAR"]

_LOGGER = get_logger("journal")

DEFAULT_INTERVAL_S = 0.05
DEFAULT_COMPACT_EVERY = 50
DEFAULT_SEARCH_TIMEOUT_S = 2.0
DEFAULT_REPLAY_TIMEOUT_S = 0.5

JOURNAL_GRAMMAR = DirectiveGrammar(
    "gateway journal",
    options={
        "interval": Field("float", minimum=0.0),
        "backend": Field("str", choices=("sqlite", "retained")),
        "path": Field("str"),
        "compact_every": Field("int", minimum=1),
        "search_timeout": Field("float", minimum=0.0),
        "replay_timeout": Field("float", minimum=0.0),
    })


class JournalPolicy:
    __slots__ = ("interval_s", "backend", "path", "compact_every",
                 "search_timeout_s", "replay_timeout_s", "spec")

    def __init__(self):
        self.interval_s = DEFAULT_INTERVAL_S
        self.backend = ""          # "" = auto: sqlite when path given
        self.path = ""
        self.compact_every = DEFAULT_COMPACT_EVERY
        self.search_timeout_s = DEFAULT_SEARCH_TIMEOUT_S
        self.replay_timeout_s = DEFAULT_REPLAY_TIMEOUT_S
        self.spec = ""

    @classmethod
    def parse(cls, spec) -> "JournalPolicy":
        """Parse a journal spec (grammar string, dict of the same keys,
        or None/True for all defaults).  Cross-field constraint --
        backend=sqlite without a path -- fails HERE and in offline lint
        (analyze/policies.py check_journal_policy) identically."""
        policy = cls()
        if spec is None or spec == "" or spec is True:
            return policy
        if isinstance(spec, JournalPolicy):
            return spec
        parsed = JOURNAL_GRAMMAR.parse(spec)
        if not isinstance(spec, dict):
            policy.spec = str(spec)
        attributes = {
            "interval": "interval_s",
            "backend": "backend",
            "path": "path",
            "compact_every": "compact_every",
            "search_timeout": "search_timeout_s",
            "replay_timeout": "replay_timeout_s",
        }
        for key, value in parsed.options.items():
            setattr(policy, attributes[key], value)
        if not policy.backend:
            policy.backend = "sqlite" if policy.path else "retained"
        if policy.backend == "sqlite" and not policy.path:
            raise ValueError(
                "journal backend=sqlite requires path=<database file>")
        return policy

    def __repr__(self):
        return (f"JournalPolicy(backend={self.backend!r}, "
                f"path={self.path!r}, interval={self.interval_s})")


def _delta_key(delta: dict) -> str:
    """Zero-padded sequence key: lexical order IS apply order, so both
    backends replay deltas exactly as they were applied."""
    return f"autopilot/{int(delta.get('seq', 0)):010d}"


class _SqliteBackend:
    """Journal over the sqlite KV core shared with the Storage actor
    (runtime/storage.py KeyValueStore): stream records under
    `stream/<id>`, bucket levels under `buckets`, one transaction per
    tick."""

    kind = "sqlite"

    def __init__(self, path: str):
        from ..runtime.storage import KeyValueStore
        self.store = KeyValueStore(path)

    def write_batch(self, records: dict, forgotten, buckets) -> None:
        items = {f"stream/{stream_id}": record
                 for stream_id, record in records.items()}
        if buckets is not None:
            items["buckets"] = buckets
        self.store.write_batch(
            items, [f"stream/{stream_id}" for stream_id in forgotten])

    def write_deltas(self, deltas) -> None:
        self.store.write_batch(
            {_delta_key(delta): delta for delta in deltas}, [])

    def replay_deltas(self) -> list:
        return [record for _, record
                in sorted(self.store.items("autopilot/"))]

    def purge_deltas(self, seqs) -> None:
        self.store.write_batch(
            {}, [f"autopilot/{int(seq):010d}" for seq in seqs])

    def replay(self) -> tuple:
        records = [record for _, record in self.store.items("stream/")]
        return records, (self.store.load("buckets") or {})

    def purge(self, stream_ids) -> None:
        self.store.write_batch(
            {}, [f"stream/{stream_id}" for stream_id in stream_ids])

    def entry_count(self) -> int:
        return self.store.count("stream/")

    def close(self) -> None:
        self.store.close()


class _RetainedBackend:
    """Journal as retained broker messages under `{root}/stream/<id>`
    (+ `{root}/buckets`): the broker IS the store, and every gateway in
    the HA group mirrors the topics continuously, so a hot standby's
    takeover replay reads a warm in-memory dict.  An empty retained
    payload clears an entry (MQTT semantics), exactly as the sqlite
    backend deletes the row."""

    kind = "retained"

    def __init__(self, process, root_topic: str):
        self.process = process
        self.root_topic = root_topic
        self._pattern = f"{root_topic}/#"
        self.mirror: dict[str, dict] = {}     # stream_id -> record
        self.bucket_mirror: dict = {}
        self.delta_mirror: dict[int, dict] = {}   # seq -> delta record
        process.add_message_handler(self._on_message, self._pattern)

    def _on_message(self, topic: str, payload: str) -> None:
        tail = topic[len(self.root_topic) + 1:]
        if tail == "buckets":
            try:
                self.bucket_mirror = json.loads(payload) if payload else {}
            except ValueError:
                _LOGGER.warning("undecodable journal buckets payload")
            return
        if tail.startswith("autopilot/"):
            try:
                seq = int(tail[len("autopilot/"):])
            except ValueError:
                return
            if not payload:
                self.delta_mirror.pop(seq, None)
                return
            try:
                self.delta_mirror[seq] = json.loads(payload)
            except ValueError:
                _LOGGER.warning("undecodable journal delta on %s", topic)
            return
        if not tail.startswith("stream/"):
            return
        stream_id = tail[len("stream/"):]
        if not payload:
            self.mirror.pop(stream_id, None)
            return
        try:
            self.mirror[stream_id] = json.loads(payload)
        except ValueError:
            _LOGGER.warning("undecodable journal record on %s", topic)

    def write_batch(self, records: dict, forgotten, buckets) -> None:
        publish = self.process.publish
        for stream_id, record in records.items():
            publish(f"{self.root_topic}/stream/{stream_id}",
                    json.dumps(record, separators=(",", ":")),
                    retain=True)
        for stream_id in forgotten:
            publish(f"{self.root_topic}/stream/{stream_id}", "",
                    retain=True)
        if buckets is not None:
            publish(f"{self.root_topic}/buckets",
                    json.dumps(buckets, separators=(",", ":")),
                    retain=True)

    def write_deltas(self, deltas) -> None:
        for delta in deltas:
            seq = int(delta.get("seq", 0))
            self.delta_mirror[seq] = delta
            self.process.publish(
                f"{self.root_topic}/{_delta_key(delta)}",
                json.dumps(delta, separators=(",", ":")), retain=True)

    def replay_deltas(self) -> list:
        return [self.delta_mirror[seq]
                for seq in sorted(self.delta_mirror)]

    def purge_deltas(self, seqs) -> None:
        for seq in seqs:
            self.delta_mirror.pop(int(seq), None)
            self.process.publish(
                f"{self.root_topic}/autopilot/{int(seq):010d}", "",
                retain=True)

    def replay(self) -> tuple:
        return list(self.mirror.values()), dict(self.bucket_mirror)

    def purge(self, stream_ids) -> None:
        for stream_id in stream_ids:
            self.mirror.pop(stream_id, None)
            self.process.publish(
                f"{self.root_topic}/stream/{stream_id}", "", retain=True)

    def entry_count(self) -> int:
        return len(self.mirror)

    def close(self) -> None:
        self.process.remove_message_handler(self._on_message,
                                            self._pattern)


class GatewayJournal:
    """Batched write-ahead journal of gateway routing state.  The
    gateway owns dirty-tracking and serialization (it owns the
    streams); this class owns the backend, the per-tick batch, expiry
    on replay, and periodic compaction."""

    def __init__(self, policy: JournalPolicy, process=None,
                 root_topic: str = ""):
        self.policy = policy
        if policy.backend == "sqlite":
            self.backend = _SqliteBackend(policy.path)
        else:
            if process is None or not root_topic:
                raise ValueError(
                    "journal backend=retained needs a process and a "
                    "root topic")
            self.backend = _RetainedBackend(process, root_topic)
        self.appends = 0          # records written across all ticks
        self.ticks = 0            # write() calls that reached the backend
        self.compactions = 0
        self.compacted_entries = 0
        self.delta_appends = 0    # autopilot deltas write-ahead logged
        self._ticks_since_compact = 0

    def write(self, records: dict, forgotten=(), buckets=None) -> int:
        """One journal tick: upsert `records` (stream_id -> record
        dict), delete `forgotten`, refresh `buckets` (None = clean).
        Returns the number of records written.  Empty ticks cost one
        truthiness check -- the idle gateway never touches the
        backend."""
        if not records and not forgotten and buckets is None:
            return 0
        self.backend.write_batch(records, forgotten, buckets)
        self.appends += len(records)
        self.ticks += 1
        self._ticks_since_compact += 1
        if self._ticks_since_compact >= self.policy.compact_every:
            self._ticks_since_compact = 0
            self.compact()
        return len(records)

    def replay(self) -> tuple:
        """(live_records, buckets, dropped_stale): every journaled
        stream whose lease has NOT expired, stale entries purged from
        the store and counted -- a cold start with an old journal must
        not re-pin dead streams to dead replicas."""
        records, buckets = self.backend.replay()
        now = epoch_now()
        live, stale = [], []
        for record in records:
            if float(record.get("expires_at", 0)) > now:
                live.append(record)
            else:
                stale.append(str(record.get("stream_id", "")))
        if stale:
            self.backend.purge(stale)
            _LOGGER.info("journal replay dropped %d expired stream(s)",
                         len(stale))
        return live, buckets, len(stale)

    def write_deltas(self, deltas) -> int:
        """WRITE-AHEAD log autopilot config deltas, synchronously and
        BEFORE they are applied: a crash between the log and the apply
        replays the logged value, a crash before the log never applied
        anything -- either way replay reconstructs the exact applied
        configuration.  Records carry absolute `value`s (never
        increments), so replaying them twice is idempotent."""
        deltas = [dict(delta) for delta in deltas]
        if not deltas:
            return 0
        self.backend.write_deltas(deltas)
        self.delta_appends += len(deltas)
        return len(deltas)

    def replay_deltas(self) -> list:
        """Every journaled autopilot delta in apply (seq) order."""
        return self.backend.replay_deltas()

    def purge_deltas(self, seqs) -> None:
        self.backend.purge_deltas(seqs)

    def compact(self) -> int:
        """Drop expired entries from the store (destroyed streams are
        deleted inline at their tick; this sweep catches streams whose
        lease lapsed without a clean destroy -- a crashed client)."""
        records, _ = self.backend.replay()
        now = epoch_now()
        stale = [str(record.get("stream_id", "")) for record in records
                 if float(record.get("expires_at", 0)) <= now]
        if stale:
            self.backend.purge(stale)
        self.compactions += 1
        self.compacted_entries += len(stale)
        return len(stale)

    def entry_count(self) -> int:
        return self.backend.entry_count()

    def stop(self) -> None:
        self.backend.close()
