# Online SLO autopilot: the guarded control loop that closes the
# observe -> tune gap (ROADMAP open item #3).
#
# `aiko tune --apply` rewrites a definition OFFLINE; the AutoPilot runs
# the SAME loader + cost model + recommender against the LIVE fleet and
# applies the result without a restart:
#
#   observe   harvest per-process trace documents over the existing
#             `(publish_trace ...)` wire path -- every live replica
#             plus the gateway itself -- and merge them with
#             merge_trace_documents into one clock-aligned artifact
#   decide    run tune/ on the merged document; convert the supported
#             recommendations (admission bucket rates, autoscale
#             min/max floors, micro_batch, checkpoint cadence) into
#             BOUNDED deltas: each knob moves at most `max_delta_frac`
#             of its current value per tick (ints always move >= 1),
#             so a bad recommendation can only nudge, never lurch
#   gate      a windowed SLO burn-rate signal (observe/metrics
#             SlidingWindow over the gateway's slo_ok/slo_miss
#             counters) arms the apply path: deltas land only while
#             burn over the window exceeds `burn_threshold`; once
#             attainment recovers the loop backs off to observe-only.
#             A fleet with NO declared SLOs has no burn signal at all
#             -- the gate stays open and the loop optimizes throughput
#   act       apply through live setter paths on the running gateway /
#             replicas (serve/gateway.py set_bucket_rate,
#             set_autoscale_floors, set_replica_parameter) -- never a
#             restart, never a recompile-forcing shape change (shape
#             knobs like decode_slots / kv_block_size are counted as
#             skipped, not applied)
#   account   every applied delta is WRITE-AHEAD journaled into the
#             gateway's serve/journal.py store before it is applied.
#             Records carry absolute values (never increments), so
#             replay is idempotent: a crash or HA promote mid-apply
#             replays the committed prefix and lands bit-identical to
#             an unkilled run; the chaos bench arm proves it
#
# Policy grammar (AIKO412, shared directive core):
#
#   interval=<s>;apply=on|off;margin=<frac>;max_delta_frac=<frac>;
#   burn_window=<s>;burn_threshold=<frac>;scope=local|fleet;
#   wait=<s>;slo=throughput|latency;p99_ms=<ms>
#
# `apply=off` (the default) is a first-class operating mode: the loop
# still harvests, tunes, journals nothing, and publishes convergence
# distance -- a dry-run audit of what it WOULD do.
#
# scope=fleet: each gateway group publishes its windowed burn on a
# retained control-plane topic; every group's autopilot sees the fleet
# view and adjusts only ITS OWN autoscale floors (raise when hot while
# a peer idles, donate when cool while a peer burns) -- floors
# rebalance between federated groups with no central coordinator.

from __future__ import annotations

import json

from ..analyze.grammar import DirectiveGrammar, Field
from ..observe.collector import (
    collect_traces, merge_trace_documents, unique_source_name)
from ..runtime.lease import Lease
from ..utils import generate, get_logger, monotonic, parse

__all__ = ["AUTOPILOT_GRAMMAR", "AutoPilot", "AutopilotPolicy",
           "harvest_documents", "tune_documents"]

_LOGGER = get_logger("autopilot")

DEFAULT_INTERVAL_S = 10.0
DEFAULT_MARGIN = 0.15
DEFAULT_MAX_DELTA_FRAC = 0.25
DEFAULT_BURN_WINDOW_S = 30.0
DEFAULT_BURN_THRESHOLD = 0.02
DEFAULT_WAIT_S = 0.5
# per-tick delta ledger entries kept for the bench timeline artifact
LEDGER_CAP = 256

AUTOPILOT_GRAMMAR = DirectiveGrammar(
    "gateway autopilot",
    options={
        "interval": Field("float", minimum=0.0),
        "apply": Field("flag"),
        "margin": Field("float", minimum=0.0),
        "max_delta_frac": Field("float", minimum=0.0, maximum=1.0),
        "burn_window": Field("float", minimum=0.0),
        "burn_threshold": Field("float", minimum=0.0, maximum=1.0),
        "scope": Field("str", choices=("local", "fleet")),
        "wait": Field("float", minimum=0.0),
        "slo": Field("str", choices=("throughput", "latency")),
        "p99_ms": Field("float", minimum=1e-3),
    })


class AutopilotPolicy:
    __slots__ = ("interval_s", "apply", "margin", "max_delta_frac",
                 "burn_window_s", "burn_threshold", "scope", "wait_s",
                 "objective", "p99_ms", "spec")

    def __init__(self):
        self.interval_s = DEFAULT_INTERVAL_S
        self.apply = False          # observe-only is the safe default
        self.margin = DEFAULT_MARGIN
        self.max_delta_frac = DEFAULT_MAX_DELTA_FRAC
        self.burn_window_s = DEFAULT_BURN_WINDOW_S
        self.burn_threshold = DEFAULT_BURN_THRESHOLD
        self.scope = "local"
        self.wait_s = DEFAULT_WAIT_S
        self.objective = "throughput"
        self.p99_ms = None
        self.spec = ""

    @classmethod
    def parse(cls, spec) -> "AutopilotPolicy":
        """Parse an autopilot spec (grammar string, dict of the same
        keys, or None/True for all defaults).  Cross-field constraints
        -- a zero burn window or a zero step bound -- fail HERE and in
        offline lint (analyze/policies.check_autopilot_policy)
        identically."""
        policy = cls()
        if spec is None or spec == "" or spec is True:
            return policy
        policy.spec = spec if isinstance(spec, str) else ""
        parsed = AUTOPILOT_GRAMMAR.parse(spec)
        attributes = {
            "interval": "interval_s",
            "apply": "apply",
            "margin": "margin",
            "max_delta_frac": "max_delta_frac",
            "burn_window": "burn_window_s",
            "burn_threshold": "burn_threshold",
            "scope": "scope",
            "wait": "wait_s",
            "slo": "objective",
            "p99_ms": "p99_ms",
        }
        for key, value in parsed.options.items():
            setattr(policy, attributes[key], value)
        if policy.burn_window_s <= 0:
            raise ValueError("autopilot burn_window must be > 0")
        if policy.max_delta_frac <= 0:
            raise ValueError(
                "autopilot max_delta_frac must be > 0 (a zero step "
                "bound can never move a knob)")
        return policy

    def slo_spec(self) -> str:
        spec = f"slo={self.objective}"
        if self.p99_ms is not None:
            spec += f";p99_ms={self.p99_ms:g}"
        return spec

    def __repr__(self):
        return (f"AutopilotPolicy(interval={self.interval_s}, "
                f"apply={self.apply}, margin={self.margin}, "
                f"max_delta_frac={self.max_delta_frac}, "
                f"scope={self.scope!r})")


# -- shared harvest + tune (autopilot loop, `aiko tune --live`) ------------

def tune_documents(named_documents: list, slo_spec=None,
                   label: str = "live", definition=None,
                   run: str | None = None, static_costs=None) -> dict:
    """[(source, chrome_trace_document), ...] -> tune report dict: the
    ONE merge -> load -> tune path shared by the autopilot's decide
    step and `aiko tune --live` (no artifact files involved; `label`
    stands in for the trace path in the report)."""
    from ..tune import load_trace, run_tune
    merged = merge_trace_documents(list(named_documents))
    loaded = load_trace(label, definition=definition, run=run,
                        document=merged)
    return run_tune(label, slo_spec=slo_spec, loaded=loaded,
                    static_costs=static_costs)


def harvest_documents(process, wait: float = 3.0,
                      protocols: tuple = ("pipeline", "gateway"),
                      targets=None) -> list:
    """Live wire harvest -> deterministically named+ordered
    [(source, document), ...] ready for tune_documents (topic paths
    sort stably; collisions get unique_source_name suffixes)."""
    collected = collect_traces(process, wait=wait, protocols=protocols,
                               targets=targets)
    seen: dict = {}
    return [(unique_source_name(seen, source), collected[source])
            for source in sorted(collected)]


# -- the control loop ------------------------------------------------------

class AutoPilot:
    """The gateway-owned observe -> decide -> act -> account loop.

    Two tick paths share one decide/apply core:

      timer path   `start()` arms a cadence timer; each firing posts
                   `_autopilot_collect` through the gateway mailbox,
                   which wire-harvests every live replica AND the
                   gateway itself (the gateway's own publish_trace
                   reply is processed by its mailbox after collect
                   returns -- the loop never blocks the mailbox), then
                   decides when all respondents answered or the wait
                   lease expires
      tick_now()   synchronous in-process harvest straight from the
                   attached replica pipelines' telemetry -- the
                   deterministic path bench.py and the tests drive
    """

    def __init__(self, gateway, policy: AutopilotPolicy):
        self.gateway = gateway
        self.policy = policy
        self.registry = gateway.telemetry.registry
        self._seq = 0              # last delta sequence number issued
        self._applied: dict = {}   # (target, knob) -> value in effect
        self._pending: dict = {}   # source -> document, current round
        self._round = 0
        self._decided_round = 0
        self._expected = 0
        self._lease = None
        self._timer_installed = False
        self._handler_installed = False
        self._fleet_handler_installed = False
        self._fleet_burns: dict = {}   # group -> {"burn", "floor"}
        self.ledger: list = []         # per-tick delta ledger (capped)
        self.last_report: dict | None = None
        self.convergence: float | None = None
        self.converged = False
        self._response_topic = (f"{gateway.process.topic_path_process}"
                                f"/autopilot/{gateway.name}")
        self._burn_root = f"{gateway.process.namespace}/autopilot/burn"
        gateway.telemetry.configure_slo_window(policy.burn_window_s)
        if policy.scope == "fleet":
            gateway.process.add_message_handler(
                self._on_fleet_burn, f"{self._burn_root}/#")
            self._fleet_handler_installed = True

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Arm the cadence timer (primary/single role only; the
        gateway re-arms on HA promote and disarms on demote)."""
        if self._timer_installed or self.policy.interval_s <= 0:
            return
        self.gateway.process.event.add_timer_handler(
            self._timer_fired, self.policy.interval_s)
        self._timer_installed = True

    def stop(self) -> None:
        """Disarm the cadence timer and any in-flight wait lease (a
        demoted standby must not keep tuning a fleet it no longer
        owns)."""
        if self._timer_installed:
            self.gateway.process.event.remove_timer_handler(
                self._timer_fired)
            self._timer_installed = False
        lease, self._lease = self._lease, None
        if lease is not None and not lease.expired:
            lease.terminate()

    def shutdown(self) -> None:
        self.stop()
        if self._handler_installed:
            self.gateway.process.remove_message_handler(
                self._on_trace, self._response_topic)
            self._handler_installed = False
        if self._fleet_handler_installed:
            self.gateway.process.remove_message_handler(
                self._on_fleet_burn, f"{self._burn_root}/#")
            self._fleet_handler_installed = False

    def _timer_fired(self) -> None:
        # timer thread -> gateway mailbox: the loop's work happens on
        # the gateway's own thread, serialized with stream traffic
        self.gateway.post_message("_autopilot_collect", [])

    # -- observe: wire harvest ---------------------------------------------

    def collect(self) -> None:
        """Start one harvest round (gateway mailbox).  Non-blocking:
        replies accumulate on the transport thread; decide runs when
        every expected respondent answered or the wait lease expires.
        The gateway queries ITSELF over the same wire path -- its own
        publish_trace reply is just another mailbox message."""
        if getattr(self.gateway, "role", "single") == "standby":
            return
        self._round += 1
        round_id = self._round
        self._pending = {}
        if not self._handler_installed:
            self.gateway.process.add_message_handler(
                self._on_trace, self._response_topic)
            self._handler_installed = True
        targets = [self.gateway.topic_path]
        for replica in self.gateway.replicas.values():
            if not replica.dead and not getattr(
                    replica, "draining", False):
                targets.append(replica.topic_path)
        self._expected = len(targets)
        self.registry.counter("autopilot.collections").inc()
        for topic in targets:
            self.gateway.process.publish(
                f"{topic}/in",
                generate("publish_trace", [self._response_topic]))
        lease = self._lease
        if lease is not None and not lease.expired:
            lease.terminate()
        self._lease = Lease(
            self.gateway.process.event, max(self.policy.wait_s, 0.05),
            f"autopilot-{round_id}",
            lease_expired_handler=lambda _uuid: self.gateway.post_message(
                "_autopilot_decide", [round_id]))

    def _on_trace(self, topic, payload) -> None:
        # transport thread: parse + stash; the decide hop back to the
        # gateway mailbox keeps every apply on the owning thread
        try:
            command, parameters = parse(payload)
        except ValueError:
            return
        if command != "trace" or len(parameters) < 2:
            return
        source, document = str(parameters[0]), parameters[1]
        if isinstance(document, (str, bytes)):
            try:
                document = json.loads(document)
            except ValueError:
                return
        if not isinstance(document, dict):
            return
        round_id = self._round
        self._pending[source] = document
        self.registry.counter("autopilot.responses").inc()
        if self._expected and len(self._pending) >= self._expected:
            # early decide: no reason to sit out the rest of the wait
            self.gateway.post_message("_autopilot_decide", [round_id])

    def decide(self, round_id) -> None:
        """Close one harvest round (gateway mailbox; at-most-once per
        round -- the early post and the lease expiry can both land)."""
        round_id = int(round_id)
        if round_id != self._round or self._decided_round >= round_id:
            return
        self._decided_round = round_id
        lease, self._lease = self._lease, None
        if lease is not None and not lease.expired:
            lease.terminate()
        documents = dict(self._pending)
        self._pending = {}
        if self._expected and len(documents) < self._expected:
            self.registry.counter("autopilot.timeouts").inc(
                self._expected - len(documents))
        self._run_decide(documents)

    def tick_now(self, now: float | None = None) -> dict | None:
        """One SYNCHRONOUS control-loop tick: harvest the in-process
        replica pipelines (and the gateway itself) directly, decide,
        apply.  Deterministic -- the bench convergence arm and the
        replay tests drive this instead of the wire timers."""
        from ..observe.trace import chrome_trace_document
        gateway = self.gateway
        telemetry = gateway.telemetry
        documents = {
            gateway.topic_path: chrome_trace_document(
                telemetry.chrome_events(),
                metadata=telemetry.trace_metadata())}
        for replica in gateway.replicas.values():
            pipeline = replica.pipeline
            if replica.dead or pipeline is None:
                continue
            replica_telemetry = getattr(pipeline, "telemetry", None)
            if replica_telemetry is None:
                continue
            documents[replica.topic_path] = chrome_trace_document(
                replica_telemetry.chrome_events(),
                metadata=replica_telemetry.trace_metadata())
        self.registry.counter("autopilot.collections").inc()
        return self._run_decide(documents, now=now)

    # -- decide + act + account --------------------------------------------

    def _run_decide(self, documents: dict,
                    now: float | None = None) -> dict | None:
        gateway = self.gateway
        telemetry = gateway.telemetry
        now = monotonic() if now is None else float(now)
        telemetry.sample_slo_window(now)
        burn = telemetry.windowed_burn()
        if burn is not None:
            self.registry.gauge("autopilot.burn_window").set(burn)
        if not documents:
            return None
        seen: dict = {}
        named = [(unique_source_name(seen, source), documents[source])
                 for source in sorted(documents)]
        try:
            report = tune_documents(
                named, slo_spec=self.policy.slo_spec(),
                label=f"autopilot:{gateway.name}")
        except Exception as error:
            # a malformed / definition-less harvest must never kill
            # the loop (the next round sees a richer fleet)
            self.registry.counter("autopilot.harvest_errors").inc()
            _LOGGER.warning("autopilot tune failed: %s", error)
            return None
        self.last_report = report
        planned, skipped, distance = self._plan(
            report.get("recommendations") or [])
        self.convergence = distance
        self.registry.gauge("autopilot.convergence").set(distance)
        self.converged = distance <= self.policy.margin
        if skipped:
            self.registry.counter("autopilot.deltas_skipped").inc(
                skipped)
        # the gate: act while the windowed burn exceeds the threshold;
        # back off once attainment recovers.  No burn signal at all
        # (no declared SLOs in the window) leaves the gate OPEN -- an
        # SLO-less fleet is tuned for throughput, not frozen
        gate_open = burn is None or burn >= self.policy.burn_threshold
        tick: dict = {"round": self._decided_round or self._round,
                      "sources": len(named),
                      "burn": (round(burn, 4)
                               if burn is not None else None),
                      "convergence": round(distance, 4),
                      "converged": self.converged,
                      "applied": [], "skipped": skipped,
                      "gated": False}
        if planned and self.policy.apply and gate_open:
            records = []
            for delta in planned:
                self._seq += 1
                record = dict(delta)
                record["seq"] = self._seq
                records.append(record)
            rebalance = self._fleet_delta(burn)
            if rebalance is not None:
                self._seq += 1
                rebalance["seq"] = self._seq
                records.append(rebalance)
                self.registry.counter("autopilot.rebalances").inc()
            # WRITE-AHEAD: journal first, apply second.  A crash
            # between the two replays the journaled record into the
            # exact state the apply would have produced
            if gateway.journal is not None and records:
                gateway.journal.write_deltas(records)
            for record in records:
                self._apply_delta(record)
                self.registry.counter("autopilot.deltas_applied").inc()
                if record.get("clamped"):
                    self.registry.counter(
                        "autopilot.deltas_clamped").inc()
            tick["applied"] = records
        elif planned and self.policy.apply and not gate_open:
            # attainment recovered: observe, don't touch
            self.registry.counter("autopilot.backoffs").inc()
            self.registry.counter("autopilot.deltas_skipped").inc(
                len(planned))
            tick["gated"] = True
            tick["skipped"] += len(planned)
        elif planned:
            # apply=off: the dry-run audit mode
            self.registry.counter("autopilot.deltas_skipped").inc(
                len(planned))
            tick["skipped"] += len(planned)
        if self.policy.scope == "fleet":
            self._publish_fleet_burn(burn)
        self.ledger.append(tick)
        del self.ledger[:-LEDGER_CAP]
        telemetry.autopilot_summary = self.summary()
        return report

    def _plan(self, recommendations: list):
        """Recommendation dicts -> (bounded delta plan, skipped count,
        convergence distance).  Only live-mutable knobs are planned;
        shape-changing knobs (decode_slots, kv_block_size,
        micro_batch_fused, frame_window, prefix/disagg policy) would
        force recompiles or restarts and are counted as skipped.
        Distance is the worst relative gap between what is in effect
        and what the recommender wants -- the number the bench
        convergence assertion reads."""
        gateway = self.gateway
        planned: list = []
        skipped = 0
        distance = 0.0

        def gap(current, proposed) -> float:
            if current is None:
                return 1.0
            scale = max(abs(float(proposed)), 1.0)
            return abs(float(proposed) - float(current)) / scale

        for recommendation in recommendations:
            target = str(recommendation.get("target", ""))
            knob = str(recommendation.get("knob", ""))
            proposed = recommendation.get("proposed")
            if target.startswith("element:") and knob == "micro_batch":
                current = self._applied.get((target, knob))
                if current is None and isinstance(
                        recommendation.get("current"), int):
                    current = recommendation["current"]
                value, clamped = self._clamp_step(current,
                                                  int(proposed))
                distance = max(distance, gap(current, proposed))
                if value is not None:
                    planned.append({"target": target,
                                    "knob": knob, "value": value,
                                    "before": current,
                                    "goal": int(proposed),
                                    "clamped": clamped})
            elif target == "gateway" and knob == "gateway_policy":
                delta = self._plan_bucket(recommendation)
                if delta is not None:
                    distance = max(distance,
                                   gap(delta["before"],
                                       delta["goal"]))
                    planned.append(delta)
            elif (target == "gateway" and knob == "autoscale_policy"
                    and gateway.autoscaler is not None):
                for delta in self._plan_floors(recommendation):
                    distance = max(distance,
                                   gap(delta["before"], delta["goal"]))
                    planned.append(delta)
            elif (target == "gateway" and knob == "replicas"
                    and gateway.autoscaler is not None):
                floors = gateway.autoscaler.policy
                current = self._applied.get(
                    ("gateway", "min_replicas"), floors.min_replicas)
                goal = min(int(proposed), floors.max_replicas)
                value, clamped = self._clamp_step(current, goal)
                distance = max(distance, gap(current, goal))
                if value is not None:
                    planned.append({"target": "gateway",
                                    "knob": "min_replicas",
                                    "value": value, "before": current,
                                    "goal": goal, "clamped": clamped})
            elif target.startswith("element:") and knob == "checkpoint":
                delta = self._plan_checkpoint(recommendation)
                if delta is not None:
                    distance = max(distance,
                                   gap(delta["before"], delta["goal"]))
                    planned.append(delta)
            else:
                skipped += 1
        return planned, skipped, distance

    def _plan_bucket(self, recommendation: dict) -> dict | None:
        """`gateway_policy` proposals arrive as a bucket spec
        fragment -- `bucket:<priority>=<rate>/<burst>` -- from
        tune/recommend.admission_recommendation."""
        proposed = str(recommendation.get("proposed", ""))
        head, _, value = proposed.partition("=")
        if not head.startswith("bucket:") or not value:
            return None
        try:
            priority = int(head.split(":", 1)[1])
            rate_text, _, burst_text = value.partition("/")
            rate = float(rate_text)
            burst = float(burst_text) if burst_text else None
        except ValueError:
            return None
        knob = f"bucket:{priority}"
        current = self._applied.get(("gateway", knob))
        if current is None:
            bucket = self.gateway.policy.buckets.get(priority)
            current = bucket.rate if bucket is not None else None
        value, clamped = self._clamp_step(current, rate)
        if value is None:
            return None
        delta = {"target": "gateway", "knob": knob, "value": value,
                 "before": current, "goal": rate, "clamped": clamped}
        if burst is not None:
            delta["burst"] = burst
        return delta

    def _plan_floors(self, recommendation: dict) -> list:
        """`autoscale_policy` proposals arrive as a policy spec
        fragment: `min_replicas=<n>;max_replicas=<m>`."""
        goals = {}
        for part in str(recommendation.get("proposed", "")).split(";"):
            key, _, value = part.partition("=")
            if key.strip() in ("min_replicas", "max_replicas"):
                try:
                    goals[key.strip()] = int(value)
                except ValueError:
                    pass
        floors = self.gateway.autoscaler.policy
        deltas = []
        for knob, live in (("min_replicas", floors.min_replicas),
                           ("max_replicas", floors.max_replicas)):
            goal = goals.get(knob)
            if goal is None:
                continue
            current = self._applied.get(("gateway", knob), live)
            value, clamped = self._clamp_step(current, goal)
            if value is not None:
                deltas.append({"target": "gateway", "knob": knob,
                               "value": value, "before": current,
                               "goal": goal, "clamped": clamped})
        # keep min <= max inside ONE tick: apply max raises before min
        # raises (the apply path clamps again, this just orders nicely)
        deltas.sort(key=lambda delta: delta["knob"] != "max_replicas")
        return deltas

    def _plan_checkpoint(self, recommendation: dict) -> dict | None:
        """`checkpoint` proposals arrive as a full checkpoint policy
        spec; the live-mutable part is the cadence
        (`checkpoint_every`), re-read by the engine's checkpointer on
        its next pump tick."""
        from ..decode.checkpoint import CheckpointPolicy
        target = str(recommendation.get("target", ""))
        try:
            goal = CheckpointPolicy.parse(
                str(recommendation.get("proposed", ""))).checkpoint_every
        except Exception:
            return None
        current = self._applied.get((target, "checkpoint_every"))
        if current is None:
            try:
                current = CheckpointPolicy.parse(
                    str(recommendation.get("current", ""))
                ).checkpoint_every
            except Exception:
                current = None
        value, clamped = self._clamp_step(current, int(goal))
        if value is None:
            return None
        return {"target": target, "knob": "checkpoint_every",
                "value": value, "before": current, "goal": int(goal),
                "clamped": clamped}

    def _clamp_step(self, current, proposed):
        """Bounded move from `current` toward `proposed`: at most
        max_delta_frac of the current value per tick (ints always get
        a step of at least 1, so small knobs are not frozen by the
        fraction).  Returns (value, clamped) -- value None when no
        move is needed, clamped True when the goal was not reached
        this tick."""
        if current is None:
            # nothing in effect yet (e.g. no admission bucket): the
            # proposal IS the bounded first step
            return proposed, False
        if isinstance(proposed, int):
            current = int(current)
            if proposed == current:
                return None, False
            limit = max(int(abs(current) * self.policy.max_delta_frac),
                        1)
            step = max(min(proposed - current, limit), -limit)
            value = current + step
            return value, value != proposed
        current = float(current)
        proposed = float(proposed)
        if proposed == current:
            return None, False
        limit = abs(current) * self.policy.max_delta_frac
        if limit <= 0.0:
            limit = abs(proposed)
        step = max(min(proposed - current, limit), -limit)
        value = current + step
        return value, abs(value - proposed) > 1e-9

    def _apply_delta(self, record: dict) -> None:
        """Apply ONE journaled delta record through the live setter
        paths.  Values are absolute, so applying the same record twice
        is a no-op -- the property journal replay (crash recovery, HA
        adoption) depends on."""
        gateway = self.gateway
        target = str(record.get("target", ""))
        knob = str(record.get("knob", ""))
        value = record.get("value")
        if target == "gateway":
            if knob.startswith("bucket:"):
                gateway.set_bucket_rate(int(knob.split(":", 1)[1]),
                                        float(value),
                                        burst=record.get("burst"))
            elif knob == "min_replicas":
                gateway.set_autoscale_floors(min_replicas=int(value))
            elif knob == "max_replicas":
                gateway.set_autoscale_floors(max_replicas=int(value))
        elif target.startswith("element:"):
            element = target.split(":", 1)[1]
            gateway.set_replica_parameter(element, knob, value)
        self._applied[(target, knob)] = value

    # -- journal adoption (crash recovery / HA promote) --------------------

    def adopt_journal(self) -> int:
        """Replay every journaled delta, in sequence order, through the
        SAME apply path a live tick uses.  Absolute values make this
        idempotent: a promoted standby adopting a journal mid-apply
        neither re-applies (double-steps) nor skips a delta -- it
        lands on exactly the configuration the primary had applied.
        Future ticks continue numbering above the adopted high water."""
        journal = self.gateway.journal
        if journal is None:
            return 0
        records = journal.replay_deltas()
        for record in records:
            try:
                self._apply_delta(record)
            except Exception as error:
                _LOGGER.warning("autopilot delta %s replay failed: %s",
                                record.get("seq"), error)
        if records:
            self._seq = max(self._seq,
                            max(int(record.get("seq", 0))
                                for record in records))
            self.registry.counter("autopilot.deltas_adopted").inc(
                len(records))
            self.gateway.telemetry.autopilot_summary = self.summary()
        return len(records)

    # -- fleet scope: burn-driven floor rebalancing ------------------------

    def _group(self) -> str:
        gateway = self.gateway
        return (getattr(gateway, "federation_group", "")
                or getattr(gateway, "ha_group", "") or gateway.name)

    def _publish_fleet_burn(self, burn) -> None:
        floors = (self.gateway.autoscaler.policy
                  if self.gateway.autoscaler is not None else None)
        payload = {"group": self._group(),
                   "burn": (round(burn, 4)
                            if burn is not None else None),
                   "floor": (floors.min_replicas
                             if floors is not None else None)}
        try:
            self.gateway.process.publish(
                f"{self._burn_root}/{self._group()}",
                json.dumps(payload, sort_keys=True), retain=True)
        except Exception as error:
            _LOGGER.warning("fleet burn publish failed: %s", error)

    def _on_fleet_burn(self, topic, payload) -> None:
        # transport thread: retained per-group burn beacons
        group = str(topic).rsplit("/", 1)[-1]
        if not payload:
            self._fleet_burns.pop(group, None)
            return
        try:
            record = json.loads(payload)
        except ValueError:
            return
        if isinstance(record, dict):
            self._fleet_burns[group] = record

    def _fleet_delta(self, burn) -> dict | None:
        """scope=fleet: adjust OUR OWN autoscale min floor from the
        fleet burn view -- raise while we burn and a peer group idles
        (capacity exists fleet-wide), donate (lower) while we idle and
        a peer burns.  Every group runs the same rule against the same
        retained beacons, so floors rebalance with no coordinator."""
        if (self.policy.scope != "fleet" or burn is None
                or self.gateway.autoscaler is None):
            return None
        my_group = self._group()
        peers = [record for group, record in
                 sorted(self._fleet_burns.items())
                 if group != my_group
                 and isinstance(record.get("burn"), (int, float))]
        if not peers:
            return None
        floors = self.gateway.autoscaler.policy
        current = self._applied.get(("gateway", "min_replicas"),
                                    floors.min_replicas)
        threshold = self.policy.burn_threshold
        hot = burn >= threshold
        peer_cool = any(record["burn"] < threshold / 2.0
                        for record in peers)
        peer_hot = any(record["burn"] >= threshold
                       for record in peers)
        if hot and peer_cool and current < floors.max_replicas:
            value = current + 1
        elif (not hot and burn < threshold / 2.0 and peer_hot
                and current > 1):
            value = current - 1
        else:
            return None
        return {"target": "gateway", "knob": "min_replicas",
                "value": value, "before": current, "goal": value,
                "clamped": False, "rebalance": True}

    # -- reporting ---------------------------------------------------------

    def summary(self) -> dict:
        """Compact scalars for the EC share (staged into the gateway
        telemetry summary under "autopilot") and the dashboard row."""
        counters = self.registry._counters

        def count(name: str) -> int:
            instrument = counters.get(name)
            return instrument.value if instrument is not None else 0

        summary = {
            "apply": self.policy.apply,
            "scope": self.policy.scope,
            "collections": count("autopilot.collections"),
            "deltas_applied": count("autopilot.deltas_applied"),
            "deltas_clamped": count("autopilot.deltas_clamped"),
            "deltas_skipped": count("autopilot.deltas_skipped"),
            "deltas_adopted": count("autopilot.deltas_adopted"),
            "backoffs": count("autopilot.backoffs"),
            "rebalances": count("autopilot.rebalances"),
        }
        if self.convergence is not None:
            summary["convergence"] = round(self.convergence, 4)
            summary["converged"] = self.converged
        burn = self.gateway.telemetry.windowed_burn()
        if burn is not None:
            summary["burn_window"] = round(burn, 4)
        return summary
