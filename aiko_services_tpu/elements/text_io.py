# Text I/O elements: the pipeline correctness suite.
#
# Capability parity with the reference text elements (reference:
# src/aiko_services/elements/media/text_io.py:64-179): TextReadFile,
# TextTransform (case operations), TextSample (drop-frame by rate -- the
# reference's documented local/remote drop-frame test vehicle,
# text_io.py:21-26), TextWriteFile, TextOutput.

from __future__ import annotations

from pathlib import Path

from ..pipeline import StreamEvent, PipelineElement
from .common_io import DataSource, DataTarget, Sample

__all__ = ["TextReadFile", "TextTransform", "TextSample", "TextWriteFile",
           "TextOutput", "TextSource"]


class TextReadFile(DataSource):
    def read_item(self, stream, item) -> dict:
        return {"text": Path(item).read_text()}


class TextSource(DataSource):
    """In-memory text source: data_sources is a list of LITERAL strings
    (no path/glob expansion -- prompts legitimately contain ? and *)."""

    expand_sources = False

    def read_item(self, stream, item) -> dict:
        return {"text": str(item)}


class TextTransform(PipelineElement):
    def process_frame(self, stream, text):
        transform = self.get_parameter("transform", "none", stream)
        if transform == "lower":
            text = text.lower()
        elif transform == "upper":
            text = text.upper()
        elif transform == "title":
            text = text.title()
        elif transform != "none":
            return StreamEvent.ERROR, {
                "diagnostic": f"unknown transform: {transform}"}
        return StreamEvent.OKAY, {"text": text}


class TextSample(Sample):
    """Pass every Nth frame, drop the rest (reference text_io.py:108-115)."""


class TextWriteFile(DataTarget):
    def process_frame(self, stream, text):
        path = self.next_target_path(stream)
        Path(path).write_text(text)
        return StreamEvent.OKAY, {"path": path}


class TextOutput(PipelineElement):
    """Collect text into stream variables (assertion point for tests,
    like the reference PE_Inspect idiom)."""

    def process_frame(self, stream, text):
        collected = stream.variables.setdefault("text_output", [])
        collected.append(text)
        return StreamEvent.OKAY, {"text": text}
