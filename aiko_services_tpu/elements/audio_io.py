# Audio I/O elements.
#
# Capability parity with the reference audio stack (reference:
# src/aiko_services/elements/media/audio_io.py -- AudioReadFile skeleton
# plus the disabled-in-docstring microphone/speaker/FFT/resampler suite
# :162-643, and PE_AudioFraming's LRU sliding window,
# examples/speech/speech_elements.py:54-83).  Microphone/speaker hardware
# elements are stubbed (no audio devices in a TPU pod); the framing,
# file-read, and synthesis elements are full implementations.

from __future__ import annotations

import numpy as np

from ..pipeline import PipelineElement, StreamEvent
from ..utils import get_logger, truthy as _truthy
from ..pipeline import AsyncHostElement
from .common_io import DataSource, DataTarget, Sample

__all__ = ["AudioReadFile", "AudioWriteFile", "ToneSource", "AudioFraming",
           "AudioSample", "AudioFFT", "AudioResample", "MicrophoneSource",
           "SpeakerSink", "synthesize_tone", "SAMPLE_RATE"]

_LOGGER = get_logger("audio_io")
SAMPLE_RATE = 16000  # reference audio_io.py:455-460: 16 kHz


def synthesize_tone(frequency: float, seconds: float,
                    sample_rate: int = SAMPLE_RATE) -> np.ndarray:
    t = np.arange(int(seconds * sample_rate)) / sample_rate
    return np.sin(2 * np.pi * frequency * t).astype(np.float32)


_DEVICE_TONE = None  # lazily-built module-level jit (stable identity)


def synthesize_tone_on_device(frequency: float, seconds: float,
                              sample_rate: int = SAMPLE_RATE):
    """Tone synthesized directly in HBM as ONE device program (a single
    dispatch -- eager op-by-op jnp would pay per-op dispatch latency,
    which dominates on tunneled/remote devices)."""
    global _DEVICE_TONE
    import functools

    import jax
    import jax.numpy as jnp

    if _DEVICE_TONE is None:
        @functools.partial(jax.jit,
                           static_argnames=("samples", "sample_rate"))
        def _tone(frequency, samples, sample_rate):
            t = jnp.arange(samples) / sample_rate
            return jnp.sin(2 * jnp.pi * frequency * t)

        _DEVICE_TONE = _tone
    return _DEVICE_TONE(jnp.float32(frequency),
                        int(seconds * sample_rate), sample_rate)


class AudioReadFile(DataSource):
    """data_sources of .wav paths -> {"audio": (samples,) f32 [-1, 1]}.
    Stdlib wave + numpy; 16-bit PCM mono/stereo (stereo is averaged)."""

    def read_item(self, stream, item) -> dict:
        import wave
        with wave.open(str(item), "rb") as handle:
            n_channels = handle.getnchannels()
            width = handle.getsampwidth()
            raw = handle.readframes(handle.getnframes())
        if width != 2:
            raise ValueError(f"{item}: only 16-bit PCM supported")
        audio = np.frombuffer(raw, np.int16).astype(np.float32) / 32768.0
        if n_channels > 1:
            audio = audio.reshape(-1, n_channels).mean(axis=1)
        return {"audio": audio}


class AudioWriteFile(DataTarget):
    """{"audio"} -> 16-bit PCM mono .wav at data_targets."""

    def process_frame(self, stream, audio):
        import wave
        array = np.asarray(audio, np.float32).reshape(-1)
        path = self.next_target_path(stream)
        with wave.open(path, "wb") as handle:
            handle.setnchannels(1)
            handle.setsampwidth(2)
            handle.setframerate(
                int(self.get_parameter("sample_rate", SAMPLE_RATE, stream)))
            handle.writeframes(
                (array.clip(-1, 1) * 32767).astype(np.int16).tobytes())
        return StreamEvent.OKAY, {"audio": audio}


class ToneSource(DataSource):
    """Synthetic audio source: items are [frequency_hz, seconds] pairs --
    the hermetic stand-in for PE_Microphone* (reference audio_io.py:196+,
    which needs pyaudio/sounddevice hardware).  on_device=true synthesizes
    the tone in HBM (no host->device hop on the frame path)."""

    def read_item(self, stream, item) -> dict:
        if self.get_parameter("on_device", False, stream):
            return {"audio": synthesize_tone_on_device(
                float(item[0]), float(item[1]))}
        return {"audio": synthesize_tone(float(item[0]), float(item[1]))}


class AudioFraming(PipelineElement):
    """Sliding-window concatenation of audio chunks (reference
    PE_AudioFraming, speech_elements.py:54-83: LRU of the last
    window_count chunks feeding Whisper a longer context)."""

    def process_frame(self, stream, audio):
        window_count = int(self.get_parameter("window_count", 4, stream))
        key = f"{self.definition.name}.window"
        window = stream.variables.setdefault(key, [])
        window.append(np.asarray(audio, np.float32).reshape(-1))
        while len(window) > window_count:
            window.pop(0)
        return StreamEvent.OKAY, {"audio": np.concatenate(window)}


class AudioSample(Sample):
    """Drop-frame sampler over audio (shared Sample base)."""


class AudioFFT(PipelineElement):
    """Magnitude spectrum of an audio frame on device (the reference's
    disabled PE_FFT seat, audio_io.py:196-640): audio (samples,) or
    (B, samples) -> {"spectrum": |rfft|, "frequencies": bin centers}.
    Runs as jnp.fft on the element's device -- XLA, not numpy."""

    def process_frame(self, stream, audio):
        import jax.numpy as jnp
        from ..ops.device import as_device_array
        sample_rate = int(self.get_parameter("sample_rate", SAMPLE_RATE,
                                             stream))
        waveform = as_device_array(audio, jnp.float32)
        spectrum = jnp.abs(jnp.fft.rfft(waveform, axis=-1))
        frequencies = np.fft.rfftfreq(waveform.shape[-1],
                                      1.0 / sample_rate)
        return StreamEvent.OKAY, {"spectrum": spectrum,
                                  "frequencies": frequencies}


class AudioResample(PipelineElement):
    """Sample-rate conversion (the reference's disabled PE_AudioResampler
    seat): linear interpolation via jnp.interp on device.  Parameters:
    rate_in (default SAMPLE_RATE), rate_out (required)."""

    def process_frame(self, stream, audio):
        import jax
        import jax.numpy as jnp
        rate_in = int(self.get_parameter("rate_in", SAMPLE_RATE, stream))
        rate_out = self.get_parameter("rate_out", None, stream)
        if rate_out is None:
            raise ValueError(
                f"{self.definition.name}: rate_out parameter is required")
        rate_out = int(rate_out)
        from ..ops.device import as_device_array
        waveform = as_device_array(audio, jnp.float32)
        if rate_in == rate_out:
            return StreamEvent.OKAY, {"audio": waveform,
                                      "sample_rate": rate_out}
        # resample along the LAST axis only; leading batch/channel axes
        # are preserved (never interpolate across row boundaries)
        samples = waveform.shape[-1]
        lead_shape = waveform.shape[:-1]
        rows = waveform.reshape(-1, samples)
        out_samples = int(round(samples * rate_out / rate_in))
        positions = (jnp.arange(out_samples, dtype=jnp.float32)
                     * (rate_in / rate_out))
        source = jnp.arange(samples, dtype=jnp.float32)
        resampled = jax.vmap(
            lambda row: jnp.interp(positions, source, row))(rows)
        resampled = resampled.reshape(*lead_shape, out_samples)
        return StreamEvent.OKAY, {"audio": resampled,
                                  "sample_rate": rate_out}


class MicrophoneSource(DataSource):
    """Live microphone chunks (the reference's PE_MicrophoneSD seat,
    audio_io.py:440-520: sounddevice, 16 kHz, 5 s chunks, with a mute
    protocol so a speaker can silence it during playback).

    Hardware-gated exactly like webcam/gstreamer: sounddevice missing or
    no capture device -> a clear start_stream error, not an import
    crash.  The "mute" share flag is live-updatable over EC (the
    reference's speaker publishes (update mute true) to the microphone
    service); muted chunks emit zeros so downstream framing stays
    continuous.
    """

    def start_stream(self, stream, stream_id):
        try:
            import sounddevice
        except ImportError:
            return StreamEvent.ERROR, {
                "diagnostic": "sounddevice is not installed "
                              "(pip install sounddevice)"}
        try:  # promised diagnostic: a clear error when no capture device
            if hasattr(sounddevice, "query_devices"):
                devices = sounddevice.query_devices()
                if not any(d.get("max_input_channels", 0) > 0
                           for d in devices):
                    return StreamEvent.ERROR, {
                        "diagnostic": "no audio capture device available"}
        except Exception as error:
            return StreamEvent.ERROR, {
                "diagnostic": f"audio device probe failed: {error}"}
        self.share.setdefault("mute", False)
        chunk_seconds = float(
            self.get_parameter("chunk_seconds", 5.0, stream))
        sample_rate = int(
            self.get_parameter("sample_rate", SAMPLE_RATE, stream))

        def frames(stream, frame_id):
            import sounddevice
            recording = sounddevice.rec(
                int(chunk_seconds * sample_rate), samplerate=sample_rate,
                channels=1, dtype="float32")
            sounddevice.wait()
            audio = recording.reshape(-1)
            if _truthy(self.get_parameter("mute", False, stream)):
                audio = np.zeros_like(audio)
            return StreamEvent.OKAY, {"audio": audio}

        self.create_frames(stream, frames)
        return StreamEvent.OKAY, None


class SpeakerSink(AsyncHostElement):
    """Audio playback (the reference's PE_Speaker seat, audio_io.py:
    560-640): plays {"audio"} frames and, while playing, MUTES a
    discovered microphone service so the pipeline does not hear itself
    (the reference's mute protocol -- (update mute true/false) on the
    microphone's /control topic via its EC share).

    Playback blocks for the clip's duration, so it runs as an ASYNC
    host element: the frame parks during play and the pipeline keeps
    flowing other frames."""

    _microphone_topic = None
    _discovery_warned = False

    def start_stream(self, stream, stream_id):
        # begin microphone discovery now so the cache is synced before
        # the first frame plays
        if self.get_parameter("microphone_service", None, stream):
            self._resolve_microphone(stream)
        return StreamEvent.OKAY, None

    def _resolve_microphone(self, stream):
        if self._microphone_topic is not None:
            return self._microphone_topic
        name = self.get_parameter("microphone_service", None, stream)
        if not name:
            return None
        from ..runtime import ServiceFilter
        from ..runtime.share import services_cache_create_singleton
        cache = services_cache_create_singleton(self.process)
        matches = list(cache.services.filter_services(
            ServiceFilter(name=str(name))))
        if matches:
            self._microphone_topic = matches[0].topic_path
        elif not self._discovery_warned:  # once, not per chunk
            self._discovery_warned = True
            _LOGGER.warning(
                "%s: microphone service '%s' not discovered yet; "
                "playing unmuted until it registers",
                self.definition.name, name)
        return self._microphone_topic

    def _set_mute(self, topic_path, muted: bool):
        from ..utils import generate
        self.process.publish(
            f"{topic_path}/control",
            generate("update", ["mute", "true" if muted else "false"]))

    def process_async(self, stream, audio):
        try:
            import sounddevice
        except ImportError as error:
            raise RuntimeError(
                "sounddevice is not installed "
                "(pip install sounddevice)") from error
        sample_rate = int(self.get_parameter(
            "sample_rate", SAMPLE_RATE, stream))
        microphone = self._resolve_microphone(stream)
        if microphone:
            self._set_mute(microphone, True)
        try:
            array = np.asarray(audio, np.float32).reshape(-1)
            sounddevice.play(array, samplerate=sample_rate)
            sounddevice.wait()
        finally:
            if microphone:
                self._set_mute(microphone, False)
        return {"audio": audio}
