# Classical vision elements: face cascade + ArUco fiducial detection.
#
# Capability parity with the reference example detectors (reference:
# src/aiko_services/examples/face/face.py:82 -- cv2 Haar cascade with the
# overlay contract; examples/aruco_marker/aruco.py:187 -- cv2 ArUco detect
# + overlay + pose).  These are host-side cv2 elements by nature (tiny
# integer workloads, not MXU shapes); they emit the SAME detections dict
# as the TPU Detector element ({boxes, scores, classes, valid}) so
# ImageOverlay and downstream consumers are interchangeable, plus the
# reference-shaped overlay fields.
#
# cv2 is import-gated exactly like the webcam/gstreamer elements: missing
# OpenCV turns the elements into a clear setup error, not an import crash.

from __future__ import annotations

import numpy as np

from ..pipeline import PipelineElement, StreamEvent
from ..utils import get_logger

__all__ = ["FaceDetect", "ArucoDetect"]

_LOGGER = get_logger("vision")


def _require_cv2():
    try:
        import cv2
        return cv2
    except ImportError as error:  # pragma: no cover - cv2 in test image
        raise RuntimeError(
            "OpenCV (cv2) is required for classical vision elements") \
            from error


def _to_gray_uint8(image) -> np.ndarray:
    """Accept CHW float [0,1], HWC float/uint8, or gray; return HxW u8."""
    array = np.asarray(image)
    if array.ndim == 4:
        array = array[0]
    if array.ndim == 3 and array.shape[0] in (1, 3):   # CHW -> HWC
        array = array.transpose(1, 2, 0)
    if array.dtype != np.uint8:
        array = (np.clip(array, 0.0, 1.0) * 255.0).astype(np.uint8)
    if array.ndim == 3:
        array = np.ascontiguousarray(array[..., :3].mean(axis=-1)
                                     .astype(np.uint8))
    return np.ascontiguousarray(array)


def _detections_dict(boxes_xyxy, scores, classes, max_detections: int):
    """Pack variable-count host detections into the Detector element's
    fixed-size contract (boxes (N,4) xyxy, scores, classes, valid)."""
    boxes = np.zeros((max_detections, 4), np.float32)
    out_scores = np.zeros((max_detections,), np.float32)
    out_classes = np.zeros((max_detections,), np.int32)
    valid = np.zeros((max_detections,), bool)
    count = min(len(boxes_xyxy), max_detections)
    for index in range(count):
        boxes[index] = boxes_xyxy[index]
        out_scores[index] = scores[index]
        out_classes[index] = classes[index]
        valid[index] = True
    return {"boxes": boxes, "scores": out_scores, "classes": out_classes,
            "valid": valid}


def _to_rgb_float(image) -> np.ndarray:
    """Accept CHW/HWC float [0,1] or uint8; return HxWx3 float [0,1]."""
    array = np.asarray(image)
    if array.ndim == 4:
        array = array[0]
    if array.ndim == 3 and array.shape[0] in (1, 3):   # CHW -> HWC
        array = array.transpose(1, 2, 0)
    if array.dtype == np.uint8:
        array = array.astype(np.float32) / 255.0
    if array.ndim == 2:
        array = np.stack([array] * 3, axis=-1)
    return np.clip(array[..., :3].astype(np.float32), 0.0, 1.0)


def _skin_mask(rgb: np.ndarray) -> np.ndarray:
    """Classical RGB skin-color rule (Kovac et al.): the segmentation
    stage of the built-in face detector."""
    r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
    peak = rgb.max(axis=-1)
    spread = peak - rgb.min(axis=-1)
    return ((r > 95 / 255) & (g > 40 / 255) & (b > 20 / 255)
            & (spread > 15 / 255) & (np.abs(r - g) > 15 / 255)
            & (r > g) & (r > b))


class FaceDetect(PipelineElement):
    """Face detector filling the reference's cascade seat (reference
    face.py:82: cv2 Haar cascade -> overlay contract; the cascade API was
    removed in OpenCV 5, so the default backend here is a self-contained
    classical pipeline -- skin-color segmentation + connected-component
    shape analysis -- with cv2's cascade used only when a "cascade"
    parameter names a model on an OpenCV that still ships it).  Emits the
    Detector element's detections dict + overlay {objects, rectangles}."""

    _cascade = None

    def _detect_classical(self, image, stream):
        try:
            from scipy import ndimage
        except ImportError as error:
            raise RuntimeError(
                "scipy is required for the built-in face detector "
                "(pip install aiko_services_tpu[media]); alternatively "
                "set the 'cascade' parameter on an OpenCV build that "
                "ships CascadeClassifier") from error
        rgb = _to_rgb_float(image)
        mask = _skin_mask(rgb)
        labels, count = ndimage.label(mask)
        height, width = mask.shape
        min_area = float(self.get_parameter(
            "min_area_fraction", 0.002, stream)) * height * width
        results = []
        for index, (slice_y, slice_x) in enumerate(
                ndimage.find_objects(labels)):
            h = slice_y.stop - slice_y.start
            w = slice_x.stop - slice_x.start
            # only THIS component's pixels (find_objects slices are
            # ordered by label id); a bbox may overlap other blobs
            region = labels[slice_y, slice_x] == index + 1
            area = int(region.sum())
            if area < min_area or h == 0 or w == 0:
                continue
            aspect = h / w
            fill = area / (h * w)
            # faces are roughly upright ellipses: aspect ~ 0.8-2.2,
            # solid fill (an ellipse fills pi/4 ~ 0.785 of its bbox)
            if not (0.6 <= aspect <= 2.5 and fill >= 0.5):
                continue
            results.append((slice_x.start, slice_y.start, w, h,
                            min(1.0, fill)))
        results.sort(key=lambda item: -(item[2] * item[3]))
        return results

    def _detect_cascade(self, image, stream, cascade_path):
        cv2 = _require_cv2()
        if self._cascade is None:
            if not hasattr(cv2, "CascadeClassifier"):
                raise RuntimeError(
                    "this OpenCV build has no CascadeClassifier "
                    "(removed in OpenCV 5); drop the 'cascade' "
                    "parameter to use the built-in detector")
            self._cascade = cv2.CascadeClassifier(str(cascade_path))
            if self._cascade.empty():
                raise RuntimeError(
                    f"cascade failed to load: {cascade_path}")
        scale = float(self.get_parameter("scale_factor", 1.1, stream))
        neighbors = int(self.get_parameter("min_neighbors", 5, stream))
        faces = self._cascade.detectMultiScale(
            _to_gray_uint8(image), scaleFactor=scale,
            minNeighbors=neighbors)
        return [(int(x), int(y), int(w), int(h), 1.0)
                for (x, y, w, h) in (faces if len(faces) else [])]

    def process_frame(self, stream, image):
        max_detections = int(
            self.get_parameter("max_detections", 32, stream))
        cascade_path = self.get_parameter("cascade", None, stream)
        if cascade_path:
            found = self._detect_cascade(image, stream, cascade_path)
        else:
            found = self._detect_classical(image, stream)
        boxes, scores, objects, rectangles = [], [], [], []
        for (x, y, w, h, confidence) in found:
            boxes.append([x, y, x + w, y + h])
            scores.append(confidence)
            objects.append({"name": "face",
                            "confidence": round(float(confidence), 3)})
            rectangles.append({"x": int(x), "y": int(y),
                               "w": int(w), "h": int(h)})
        detections = _detections_dict(
            boxes, scores, [0] * len(boxes), max_detections)
        return StreamEvent.OKAY, {
            "detections": detections,
            "overlay": {"objects": objects, "rectangles": rectangles}}


class ArucoDetect(PipelineElement):
    """ArUco fiducial detector (reference aruco.py:187): image ->
    marker ids + corners + detections/overlay contract; optional pose
    when camera parameters are supplied."""

    _detectors: dict | None = None

    def _get_detector(self, stream):
        cv2 = _require_cv2()
        name = str(self.get_parameter("dictionary", "DICT_4X4_50",
                                      stream))
        if self._detectors is None:
            self._detectors = {}
        detector = self._detectors.get(name)
        if detector is None:
            dictionary = cv2.aruco.getPredefinedDictionary(
                getattr(cv2.aruco, name))
            detector = cv2.aruco.ArucoDetector(
                dictionary, cv2.aruco.DetectorParameters())
            self._detectors[name] = detector
        return detector

    def process_frame(self, stream, image):
        gray = _to_gray_uint8(image)
        max_detections = int(
            self.get_parameter("max_detections", 32, stream))
        corners, ids, _ = self._get_detector(stream).detectMarkers(gray)
        boxes, classes, objects, rectangles = [], [], [], []
        marker_corners = []
        if ids is not None:
            for marker_id, quad in zip(ids.reshape(-1), corners):
                points = quad.reshape(-1, 2)
                x0, y0 = points.min(axis=0)
                x1, y1 = points.max(axis=0)
                boxes.append([x0, y0, x1, y1])
                classes.append(int(marker_id))
                objects.append({"name": f"aruco_{int(marker_id)}",
                                "confidence": 1.0})
                rectangles.append({"x": int(x0), "y": int(y0),
                                   "w": int(x1 - x0), "h": int(y1 - y0)})
                marker_corners.append(points.tolist())
        detections = _detections_dict(
            boxes, [1.0] * len(boxes), classes, max_detections)
        outputs = {
            "detections": detections,
            "markers": {"ids": [int(i) for i in (
                ids.reshape(-1) if ids is not None else [])],
                "corners": marker_corners},
            "overlay": {"objects": objects, "rectangles": rectangles},
        }
        return StreamEvent.OKAY, outputs
