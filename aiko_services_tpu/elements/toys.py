# Toy pipeline elements: arithmetic diamonds, inspection, metrics.
#
# Capability parity with the reference example elements (reference:
# src/aiko_services/examples/pipeline/elements.py:26-324): PE_0..PE_4-style
# arithmetic for fan-out/fan-in graphs, PE_Inspect (swag dump), PE_Metrics
# (per-element timing report), PE_RandomIntegers (seeded generator).

from __future__ import annotations

from ..pipeline import PipelineElement, StreamEvent
from ..utils import get_logger
from .common_io import DataSource

__all__ = ["PE_Number", "PE_Add", "PE_Busy", "PE_Multiply", "PE_Sum2",
           "PE_Inspect", "PE_Metrics", "PE_RandomIntegers",
           "PE_RandomTensor", "PE_Sum"]

_LOGGER = get_logger("toys")


class PE_Number(DataSource):
    """Emits frames {"number": n} from data_sources items."""

    def read_item(self, stream, item) -> dict:
        return {"number": int(item)}


class PE_Add(PipelineElement):
    def process_frame(self, stream, number):
        constant = int(self.get_parameter("constant", 1, stream))
        return StreamEvent.OKAY, {"number": int(number) + constant}


class PE_Multiply(PipelineElement):
    def process_frame(self, stream, number):
        constant = int(self.get_parameter("constant", 2, stream))
        return StreamEvent.OKAY, {"number": int(number) * constant}


class PE_Busy(PipelineElement):
    """PE_Multiply with a FIXED host cost per frame (`work_ms`): models
    a replica's service time, so capacity-sensitive benches and tests
    control the floor classification (compute vs queue wait) instead of
    the host machine.  Output stays deterministic (number x constant)
    for bit-identical two-arm comparisons.  Array inputs multiply
    elementwise (shape-preserving), so the element coalesces under
    micro-batching; scalar ints stay exact integers."""

    def process_frame(self, stream, number):
        import time
        time.sleep(  # the modelled service time  # aiko: allow
            float(self.get_parameter("work_ms", 2, stream)) / 1000.0)
        constant = int(self.get_parameter("constant", 3, stream))
        if hasattr(number, "shape"):
            return StreamEvent.OKAY, {"number": number * constant}
        return StreamEvent.OKAY, {"number": int(number) * constant}


class PE_Sum2(PipelineElement):
    """Fan-in join: sums two inputs (use with map_in for diamond graphs)."""

    def process_frame(self, stream, a, b):
        return StreamEvent.OKAY, {"number": int(a) + int(b)}


class PE_Inspect(PipelineElement):
    """Dump chosen swag keys to the log and a stream variable
    (reference elements.py:68-123)."""

    def process_frame(self, stream, **inputs):
        inspected = stream.variables.setdefault("inspected", [])
        inspected.append(dict(inputs))
        if self.get_parameter("log", False, stream):
            _LOGGER.info("%s inspect: %s", self.definition.name, inputs)
        return StreamEvent.OKAY, dict(inputs)


class PE_Metrics(PipelineElement):
    """Report per-element frame timings (reference elements.py:133-149).
    Reads frame metrics accumulated by the pipeline engine."""

    def process_frame(self, stream, **inputs):
        frame = stream.frames.get(max(stream.frames) if stream.frames
                                  else None)
        metrics = dict(frame.metrics) if frame else {}
        history = stream.variables.setdefault("metrics_history", [])
        history.append(metrics)
        if self.get_parameter("log", False, stream):
            _LOGGER.info("metrics: %s", metrics)
        return StreamEvent.OKAY, {}


class PE_RandomIntegers(DataSource):
    """Deterministic pseudo-random integer source: data_sources items are
    seeds; emits {"number": value}."""

    def read_item(self, stream, item) -> dict:
        seed = int(item)
        value = (seed * 1103515245 + 12345) % 2147483648
        return {"number": value % 100}


class PE_RandomTensor(DataSource):
    """Tensor source for data-plane load tests: data_sources items are
    element counts; emits {"values": float32 array} (deterministic)."""

    def read_item(self, stream, item) -> dict:
        import numpy as np
        count = int(item)
        rng = np.random.default_rng(count)
        return {"values": rng.standard_normal(count).astype(np.float32)}


class PE_Sum(PipelineElement):
    """Reduce a tensor input to its scalar sum."""

    def process_frame(self, stream, values):
        import numpy as np
        return StreamEvent.OKAY, {"number": float(np.sum(values))}
