# Robot actor seat: a simulated robot driven by S-expression actions.
#
# Capability parity with the reference's XGO robot-dog stack (reference:
# src/aiko_services/examples/xgo_robot/xgo_robot.py + robot_control.py,
# 807 LoC): an Actor that accepts "(action name args...)" commands --
# the contract the reference LLM element emits (elements_llm.py:137-179's
# S-expression-constrained system prompt) -- plus a pipeline element that
# parses LM output text into robot actions and forwards them to a
# discovered robot service.
#
# The reference drives real XGO hardware over serial; here the actuation
# backend is pluggable: SimulatedRobot integrates simple kinematics (the
# hermetic default, also the CI story the reference never had), and a
# hardware backend can subclass RobotActor and override _apply.

from __future__ import annotations

import math
import re
import time

from ..pipeline import PipelineElement, StreamEvent
from ..runtime.actor import Actor
from ..utils import get_logger

__all__ = ["RobotActor", "RobotControl", "RobotCameraSource",
           "parse_actions", "encode_camera_frame", "decode_camera_frame"]

_LOGGER = get_logger("robot")

# action name -> (parameter names, defaults); mirrors the reference robot
# vocabulary (xgo_robot.py action handlers: move/turn/stop/pose/speak)
ACTIONS = {
    "move": (("distance",), (0.1,)),       # meters, +forward
    "turn": (("degrees",), (15.0,)),       # +counter-clockwise
    "stop": ((), ()),
    "pose": (("name",), ("stand",)),
    "speak": (("text",), ("",)),
}


class RobotActor(Actor):
    """Discoverable robot service: "(action move 0.5)" etc. on its /in
    topic move the (simulated) robot; pose/odometry live in the EC share
    so dashboards and controllers mirror robot state like any service."""

    def __init__(self, process, name: str = "robot", protocol=None):
        super().__init__(process, name,
                         protocol=protocol or "robot:0")
        self.share.update({
            "x": 0.0, "y": 0.0, "heading": 0.0, "pose": "stand",
            "odometer": 0.0, "actions": 0, "last_action": "",
            "utterances": 0,
        })
        self.history: list[tuple] = []

    # -- the wire command ----------------------------------------------

    def action(self, name, *args):
        """(action <name> <args...>) -- validate name AND argument types
        against the action vocabulary before touching any state; invalid
        actions are logged, not fatal (the LM may hallucinate)."""
        name = str(name)
        if name not in ACTIONS:
            _LOGGER.warning("%s: unknown action: %s", self.name, name)
            return
        if name in ("move", "turn") and args:
            try:
                args = (float(args[0]),)
            except (TypeError, ValueError):
                _LOGGER.warning("%s: bad %s argument: %r", self.name,
                                name, args[0])
                return
        self.history.append((name, args, time.time()))
        self._apply(name, args)
        self._update_share("actions", int(self.share["actions"]) + 1)
        self._update_share(
            "last_action",
            f"{name} {' '.join(str(a) for a in args)}".strip())

    # -- simulated kinematics (override for hardware) ------------------

    def _apply(self, name: str, args: tuple):
        if name == "move":
            distance = float(args[0]) if args else ACTIONS["move"][1][0]
            heading = math.radians(float(self.share["heading"]))
            self._update_share(
                "x", round(float(self.share["x"])
                           + distance * math.cos(heading), 6))
            self._update_share(
                "y", round(float(self.share["y"])
                           + distance * math.sin(heading), 6))
            self._update_share(
                "odometer",
                round(float(self.share["odometer"]) + abs(distance), 6))
        elif name == "turn":
            degrees = float(args[0]) if args else ACTIONS["turn"][1][0]
            self._update_share(
                "heading",
                round((float(self.share["heading"]) + degrees) % 360.0,
                      6))
        elif name == "pose":
            self._update_share(
                "pose", str(args[0]) if args else "stand")
        elif name == "speak":
            self._update_share(
                "utterances", int(self.share["utterances"]) + 1)
        # "stop" only records history/last_action

    def _update_share(self, key, value):
        self.share[key] = value
        if self.ec_producer is not None:
            self.ec_producer.update(key, value)

    # -- camera over binary topics (reference xgo_robot.py ships camera
    # frames as zlib'd numpy on binary MQTT topics) --------------------

    def start_camera(self, period=1.0, height=64, width=64) -> None:
        """Wire-invocable "(start_camera 0.5)": publish camera frames to
        "{topic_path}/video" every `period` seconds as zlib-compressed
        .npy payloads (the reference's numpy+zlib binary-topic codec,
        audio_io.py PE_RemoteSend / xgo_robot.py camera loop).
        Consumers: RobotCameraSource feeds them into pipelines."""
        self.stop_camera()
        period = float(period)
        shape = (int(height), int(width))

        def tick():
            self.process.publish(f"{self.topic_path}/video",
                                 encode_camera_frame(self._capture(shape)))
            self._update_share("camera_frames",
                               int(self.share.get("camera_frames", 0)) + 1)

        self._camera_timer = tick
        self.process.event.add_timer_handler(tick, period, immediate=True)
        self._update_share("camera", f"on period={period}")

    def stop_camera(self) -> None:
        timer = getattr(self, "_camera_timer", None)
        if timer is not None:
            self.process.event.remove_timer_handler(timer)
            self._camera_timer = None
            self._update_share("camera", "off")

    def _capture(self, shape) -> "np.ndarray":
        """Simulated camera: a deterministic scene keyed by the robot's
        pose (hardware subclasses override with a real sensor read)."""
        import numpy as np
        height, width = shape
        seed = (int(float(self.share["x"]) * 100)
                ^ int(float(self.share["heading"]))
                ^ int(self.share.get("camera_frames", 0)))
        rng = np.random.default_rng(seed & 0x7FFFFFFF)
        return rng.random((3, height, width), dtype=np.float32)

    def stop(self) -> None:
        self.stop_camera()
        super().stop()


def encode_camera_frame(array) -> bytes:
    """ndarray -> zlib(.npy) bytes (binary-topic payload)."""
    import io
    import zlib

    import numpy as np
    buffer = io.BytesIO()
    np.save(buffer, np.asarray(array), allow_pickle=False)
    return zlib.compress(buffer.getvalue(), level=1)


def decode_camera_frame(payload) -> "np.ndarray":
    """Inverse of encode_camera_frame; accepts the broker's latin-1 text
    round-trip of the binary payload."""
    import io
    import zlib

    import numpy as np
    if isinstance(payload, str):
        payload = payload.encode("latin-1")
    return np.load(io.BytesIO(zlib.decompress(payload)),
                   allow_pickle=False)


def _discover_service_topic(process, name) -> str | None:
    """One-shot registrar lookup: the named service's topic_path (shared
    by RobotControl proxy resolution and camera discovery)."""
    from ..runtime import ServiceFilter
    from ..runtime.share import services_cache_create_singleton
    cache = services_cache_create_singleton(process)
    matches = list(cache.services.filter_services(
        ServiceFilter(name=str(name))))
    return matches[0].topic_path if matches else None


class RobotCameraSource(PipelineElement):
    """DataSource-style element subscribing to a robot's binary video
    topic: each received frame enters the stream as {"image": (3,H,W)}
    (reference capability: xgo_robot camera frames feeding the
    YOLO/overlay pipelines).  Parameters: "topic" (explicit) or
    "robot_service" (registrar discovery of the named robot's
    "{topic_path}/video").  Discovery is RACE-FREE: if the robot has
    not yet reached the services cache at stream start, the element
    watches the cache and subscribes the moment it appears (the
    asynchronous mirror means 'not discovered yet' is transient, not
    an error)."""

    def _subscribe(self, stream, topic: str) -> None:
        pipeline = self.pipeline
        window = int(self.get_parameter("frame_window", 16, stream))

        def handler(_topic, payload):
            if stream.pending >= window:
                # backpressure like every DataSource: a camera outrunning
                # the pipeline (e.g. during a downstream jit compile)
                # drops frames instead of queuing minutes-stale ones
                return
            try:
                image = decode_camera_frame(payload)
            except Exception as error:
                _LOGGER.warning("%s: undecodable camera frame: %s",
                                self.name, error)
                return
            if stream.stream_id in pipeline.streams:
                pipeline.create_frame(stream, {"image": image})

        stream.variables[f"{self.definition.name}.handler"] = (
            handler, topic)
        self.process.add_message_handler(handler, topic)

    def start_stream(self, stream, stream_id):
        topic = self.get_parameter("topic", None, stream)
        name = self.get_parameter("robot_service", None, stream)
        if topic:
            self._subscribe(stream, str(topic))
            return StreamEvent.OKAY, None
        if not name:
            return StreamEvent.ERROR, {
                "diagnostic": "RobotCameraSource needs a topic parameter "
                              "or a robot_service name"}
        from ..runtime import ServiceFilter
        from ..runtime.share import services_cache_create_singleton
        cache = services_cache_create_singleton(self.process)

        def on_service(command, fields):
            key = f"{self.definition.name}.handler"
            if (command == "add" and key not in stream.variables
                    and stream.stream_id in self.pipeline.streams):
                self._subscribe(stream, f"{fields.topic_path}/video")

        # add_handler replays already-known services as "add", so this
        # covers both orders: robot first or stream first
        cache.add_handler(on_service, ServiceFilter(name=str(name)))
        stream.variables[f"{self.definition.name}.watch"] = (
            cache, on_service)
        return StreamEvent.OKAY, None

    def stop_stream(self, stream, stream_id):
        watch = stream.variables.pop(
            f"{self.definition.name}.watch", None)
        if watch is not None:
            watch[0].remove_handler(watch[1])
        entry = stream.variables.pop(
            f"{self.definition.name}.handler", None)
        if entry is not None:
            self.process.remove_message_handler(*entry)
        return StreamEvent.OKAY, None

    def process_frame(self, stream, **inputs):
        return StreamEvent.OKAY, inputs


_ACTION_PATTERN = re.compile(r"\(\s*action\s+([^()]+?)\s*\)")


def parse_actions(text: str) -> list[tuple[str, list[str]]]:
    """Extract (action name args...) commands from free-form LM output
    (the reference constrains the LLM to this grammar,
    elements_llm.py:137-179)."""
    actions = []
    for match in _ACTION_PATTERN.finditer(text or ""):
        parts = match.group(1).split()
        if parts:
            actions.append((parts[0], parts[1:]))
    return actions


class RobotControl(PipelineElement):
    """Pipeline bridge LM -> robot: parses "(action ...)" commands out of
    generated text and forwards them to a robot service by proxy (the
    reference's robot_control loop).  The robot is addressed either
    directly ("robot_topic" parameter) or by registrar discovery
    ("robot_service" name).  Emits the parsed actions so graphs can also
    fan them into recorders/dashboards."""

    _proxy_cache: tuple | None = None  # (resolution key, proxy)

    def _robot_proxy(self, stream):
        from ..runtime.proxy import make_proxy
        target = self.get_parameter("robot_topic", None, stream)
        name = self.get_parameter("robot_service", None, stream)
        key = (target, name)
        if self._proxy_cache is not None and self._proxy_cache[0] == key:
            return self._proxy_cache[1]
        if target:
            proxy = make_proxy(self.process, str(target))
            self._proxy_cache = (key, proxy)
            return proxy
        if not name:
            return None
        topic_path = _discover_service_topic(self.process, name)
        if topic_path is None:
            # not cached: retry discovery on the next frame
            _LOGGER.warning("%s: robot service '%s' not discovered yet",
                            self.definition.name, name)
            return None
        proxy = make_proxy(self.process, topic_path)
        self._proxy_cache = (key, proxy)
        return proxy

    def process_frame(self, stream, text):
        prompts = [text] if isinstance(text, str) else list(text)
        parsed = []
        for item in prompts:
            parsed.extend(parse_actions(str(item)))
        sent = 0
        if parsed:
            proxy = self._robot_proxy(stream)
            if proxy is not None:
                for name, args in parsed:
                    proxy.action(name, *args)
                    sent += 1
        return StreamEvent.OKAY, {
            "actions": [[name] + list(args) for name, args in parsed],
            "dispatched": sent}
