# Video I/O elements.
#
# Capability parity with the reference video elements (reference:
# src/aiko_services/elements/media/video_io.py:119-305: VideoReadFile
# (cv2.VideoCapture frame iterator chaining files), VideoSample, VideoShow
# (cv2 GUI), VideoWriteFile (fourcc writer), VideoOutput).  VideoShow is
# headless-gated; frames flow as (3, H, W) f32 [0,1] CHW arrays ready for
# on-device compute.

from __future__ import annotations

import numpy as np

from ..pipeline import PipelineElement, StreamEvent
from ..utils import get_logger
from .common_io import DataSource, DataTarget, Sample

__all__ = ["VideoReadFile", "VideoSample", "VideoWriteFile", "VideoOutput"]

_LOGGER = get_logger("video_io")


class VideoReadFile(DataSource):
    """data_sources of video paths -> one frame per pipeline frame,
    chaining files (reference video_io.py:119-166)."""

    def start_stream(self, stream, stream_id):
        try:
            import cv2  # noqa: F401
        except ImportError:
            return StreamEvent.ERROR, {
                "diagnostic": "VideoReadFile needs cv2 (opencv)"}
        return super().start_stream(stream, stream_id)

    def _frame_generator(self, stream, frame_id):
        import cv2
        items = stream.variables[f"{self.definition.name}.items"]
        capture_key = f"{self.definition.name}.capture"
        cursor_key = f"{self.definition.name}.cursor"
        while True:
            capture = stream.variables.get(capture_key)
            if capture is None:
                cursor = stream.variables.get(cursor_key, 0)
                if cursor >= len(items):
                    return StreamEvent.STOP, {
                        "diagnostic": "video files exhausted"}
                capture = cv2.VideoCapture(str(items[cursor]))
                if not capture.isOpened():
                    return StreamEvent.ERROR, {
                        "diagnostic": f"cannot open {items[cursor]}"}
                stream.variables[capture_key] = capture
                stream.variables[cursor_key] = cursor + 1
            ok, frame_bgr = capture.read()
            if ok:
                rgb = frame_bgr[:, :, ::-1].astype(np.float32) / 255.0
                return StreamEvent.OKAY, {"image": rgb.transpose(2, 0, 1)}
            capture.release()
            stream.variables[capture_key] = None  # next file

    def read_item(self, stream, item) -> dict:  # pragma: no cover
        raise NotImplementedError("VideoReadFile streams via generator")


class VideoSample(Sample):
    """Drop-frame sampler over video frames (shared Sample base;
    reference video_io.py VideoSample)."""


class VideoWriteFile(DataTarget):
    """{"image"} frames -> one video file (reference video_io.py:240-305).
    Writer opens lazily on the first frame (size known then)."""

    def process_frame(self, stream, image):
        import cv2
        writer_key = f"{self.definition.name}.writer"
        writer = stream.variables.get(writer_key)
        array = np.asarray(image)
        if array.ndim == 4:
            array = array[0]
        if array.shape[0] in (1, 3):  # CHW -> HWC
            array = array.transpose(1, 2, 0)
        if array.dtype != np.uint8:
            array = (array * 255.0).clip(0, 255).astype(np.uint8)
        bgr = np.ascontiguousarray(array[:, :, ::-1])
        if writer is None:
            path = self.next_target_path(stream)
            rate = float(self.get_parameter("frame_rate", 25.0, stream))
            fourcc = cv2.VideoWriter_fourcc(
                *str(self.get_parameter("fourcc", "mp4v", stream)))
            writer = cv2.VideoWriter(
                path, fourcc, rate, (bgr.shape[1], bgr.shape[0]))
            stream.variables[writer_key] = writer
        writer.write(bgr)
        return StreamEvent.OKAY, {"image": image}

    def stop_stream(self, stream, stream_id):
        writer = stream.variables.get(f"{self.definition.name}.writer")
        if writer is not None:
            writer.release()
        return StreamEvent.OKAY, None


class VideoOutput(PipelineElement):
    """Log frame shapes; VideoShow's headless stand-in (reference
    video_io.py:197-233 opens a cv2 GUI window)."""

    def process_frame(self, stream, image):
        array = np.asarray(image)
        count_key = f"{self.definition.name}.count"
        stream.variables[count_key] = stream.variables.get(count_key, 0) + 1
        _LOGGER.debug("%s: frame %d %s", self.definition.name,
                      stream.variables[count_key], array.shape)
        return StreamEvent.OKAY, {"image": image}
