# JAX compute toy elements: the smallest real ComputeElements, used by
# tests and as templates for user elements.  No reference counterpart --
# the reference's compute lives in torch/CUDA user code (reference:
# src/aiko_services/examples/yolo/yolo.py:51-87); here it is jit-compiled
# JAX running on whatever mesh the definition names.

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..pipeline import ComputeElement, StreamEvent
from .common_io import DataSource

__all__ = ["ArraySource", "TokenSource", "MultiModalSource", "JaxScale",
           "JaxMLP", "ToHost"]


class ArraySource(DataSource):
    """Emits {"tensor": ndarray} frames; data_sources items give shapes,
    e.g. [[8, 16], [8, 16]] emits two 8x16 arrays (seeded, deterministic)."""

    def read_item(self, stream, item) -> dict:
        shape = tuple(int(size) for size in item)
        rng = np.random.default_rng(
            int(self.get_parameter("seed", 0, stream))
            + self.emission_index(stream))
        return {"tensor": rng.standard_normal(shape, dtype=np.float32)}


class TokenSource(DataSource):
    """Emits {"tokens": (B, L) int32} frames: data_sources [[batch, seq]],
    repeated `count` times (load-generator for LM pipelines/benchmarks)."""

    def start_stream(self, stream, stream_id):
        items = self.get_parameter("data_sources", [[8, 128]], stream)
        shapes = [tuple(int(size) for size in item) for item in items]
        count = int(self.get_parameter("count", 1, stream))
        name = self.definition.name
        stream.variables[f"{name}.shapes"] = shapes
        stream.variables[f"{name}.remaining"] = count
        rate = self.get_parameter("rate", None, stream)
        self.create_frames(stream, self._generate,
                           rate=float(rate) if rate else None)
        return StreamEvent.OKAY, None

    def _generate(self, stream, frame_id):
        import time
        name = self.definition.name
        remaining = stream.variables[f"{name}.remaining"]
        if remaining <= 0:
            return StreamEvent.STOP, {"diagnostic": "count exhausted"}
        stream.variables[f"{name}.remaining"] = remaining - 1
        shapes = stream.variables[f"{name}.shapes"]
        index = self.emission_index(stream)
        shape = shapes[index % len(shapes)]  # cycle all configured shapes
        vocab = int(self.get_parameter("vocab_size", 8192, stream))
        rng = np.random.default_rng(
            int(self.get_parameter("seed", 0, stream)) + index)
        # t0 rides the swag so consumers can measure true frame latency
        # (declare a "t0" output port to propagate it)
        return StreamEvent.OKAY, {
            "tokens": rng.integers(0, vocab, shape, dtype=np.int32),
            "t0": time.time()}


class MultiModalSource(DataSource):
    """Emits {"audio", "image"} frames: items are [frequency_hz, seconds]
    tone specs plus a synthetic image (parameter image_shape, default
    [3, 32, 32]) -- the hermetic driver for multi-modal pipelines.
    Composes audio_io.synthesize_tone + image_io.synthesize_image."""

    def read_item(self, stream, item) -> dict:
        from .audio_io import SAMPLE_RATE, synthesize_tone
        from .image_io import synthesize_image
        shape = self.get_parameter("image_shape", [3, 32, 32], stream)
        seed = (int(self.get_parameter("seed", 0, stream))
                + self.emission_index(stream))
        if self.get_parameter("on_device", False, stream):
            # synthesize directly in HBM: no host->device transfer rides
            # the frame path (the HBM-resident design property; bench
            # measures model compute, not host ingest bandwidth)
            from .audio_io import synthesize_tone_on_device
            from .image_io import synthesize_image_on_device
            return {
                "audio": synthesize_tone_on_device(
                    float(item[0]), float(item[1])),
                "image": synthesize_image_on_device(shape, seed),
            }
        return {
            "audio": synthesize_tone(float(item[0]), float(item[1])),
            "image": synthesize_image(shape, seed),
        }

    def read_batch(self, stream, items) -> dict | None:
        """Whole-row-batch synthesis as ONE device program (B tones + B
        images in a single dispatch -- the per-item path costs ~10
        dispatches per frame on a tunneled device).  Host path and
        ragged tone lengths fall back to per-item reads."""
        from .audio_io import SAMPLE_RATE
        if not self.get_parameter("on_device", False, stream):
            return None
        seconds = float(items[0][1])
        if any(float(item[1]) != seconds for item in items):
            return None  # ragged lengths cannot stack
        shape = tuple(int(size) for size in self.get_parameter(
            "image_shape", [3, 32, 32], stream))
        base_seed = int(self.get_parameter("seed", 0, stream))
        seeds = np.asarray(
            [base_seed + self.emission_index(stream) for _ in items],
            np.uint32)
        freqs = np.asarray([float(item[0]) for item in items], np.float32)
        audio, image = _multimodal_batch(
            jnp.asarray(freqs), jnp.asarray(seeds),
            int(seconds * SAMPLE_RATE), SAMPLE_RATE, shape)
        return {"audio": audio, "image": image}


@functools.partial(jax.jit,
                   static_argnames=("samples", "sample_rate", "shape"))
def _multimodal_batch(freqs, seeds, samples, sample_rate, shape):
    """(B,) tone frequencies + (B,) seeds -> ((B, samples) audio,
    (B, *shape) images): the whole multi-modal batch in one dispatch.
    Same formulas and fold_in as the per-item synthesize_tone_on_device /
    synthesize_image_on_device; images are bit-exact, audio agrees to
    f32 rounding (~1e-4 -- XLA fuses the broadcast sin differently)."""
    t = jnp.arange(samples) / sample_rate
    audio = jnp.sin(2 * jnp.pi * freqs[:, None] * t[None, :])
    keys = jax.vmap(
        lambda seed: jax.random.fold_in(jax.random.PRNGKey(0), seed))(seeds)
    image = jax.vmap(
        lambda key: jax.random.uniform(key, shape, jnp.float32))(keys)
    return audio, image


class JaxScale(ComputeElement):
    """tensor -> tensor * scale + offset: stateless pure-JAX element.
    scale/offset are dynamic parameters, so live updates (dashboard, EC
    share, stream overrides) apply without recompiling."""

    def dynamic_parameters(self, stream):
        return {"scale": float(self.get_parameter("scale", 2.0, stream)),
                "offset": float(self.get_parameter("offset", 0.0, stream))}

    def compute(self, state, tensor, scale, offset):
        return {"tensor": tensor * scale + offset}


class JaxMLP(ComputeElement):
    """Two-layer MLP over the last axis: a stateful ComputeElement whose
    params live on the element's mesh (definition "sharding" block)."""

    def setup(self):
        features = int(self.get_parameter("features", 16))
        hidden = int(self.get_parameter("hidden", 32))
        key = jax.random.PRNGKey(int(self.get_parameter("seed", 0)))
        k1, k2 = jax.random.split(key)
        return {
            "w1": jax.random.normal(k1, (features, hidden),
                                    jnp.float32) / np.sqrt(features),
            "b1": jnp.zeros((hidden,), jnp.float32),
            "w2": jax.random.normal(k2, (hidden, features),
                                    jnp.float32) / np.sqrt(hidden),
            "b2": jnp.zeros((features,), jnp.float32),
        }

    def compute(self, state, tensor):
        hidden = jax.nn.gelu(tensor @ state["w1"] + state["b1"])
        return {"tensor": hidden @ state["w2"] + state["b2"]}


class ToHost(ComputeElement):
    """Device -> host boundary: returns the tensor as numpy (the explicit
    Sink-side transfer point; everything upstream stays on device)."""

    def process_frame(self, stream, tensor):
        return StreamEvent.OKAY, {"tensor": np.asarray(tensor)}
