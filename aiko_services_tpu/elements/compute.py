# JAX compute toy elements: the smallest real ComputeElements, used by
# tests and as templates for user elements.  No reference counterpart --
# the reference's compute lives in torch/CUDA user code (reference:
# src/aiko_services/examples/yolo/yolo.py:51-87); here it is jit-compiled
# JAX running on whatever mesh the definition names.

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..pipeline import ComputeElement, StreamEvent
from .common_io import DataSource

__all__ = ["ArraySource", "TokenSource", "MultiModalSource", "JaxScale",
           "JaxMLP", "ToHost"]


class ArraySource(DataSource):
    """Emits {"tensor": ndarray} frames; data_sources items give shapes,
    e.g. [[8, 16], [8, 16]] emits two 8x16 arrays (seeded, deterministic)."""

    def read_item(self, stream, item) -> dict:
        shape = tuple(int(size) for size in item)
        rng = np.random.default_rng(
            int(self.get_parameter("seed", 0, stream))
            + self.emission_index(stream))
        return {"tensor": rng.standard_normal(shape, dtype=np.float32)}


class TokenSource(DataSource):
    """Emits {"tokens": (B, L) int32} frames: data_sources [[batch, seq]],
    repeated `count` times (load-generator for LM pipelines/benchmarks)."""

    def start_stream(self, stream, stream_id):
        items = self.get_parameter("data_sources", [[8, 128]], stream)
        shapes = [tuple(int(size) for size in item) for item in items]
        count = int(self.get_parameter("count", 1, stream))
        name = self.definition.name
        stream.variables[f"{name}.shapes"] = shapes
        stream.variables[f"{name}.remaining"] = count
        rate = self.get_parameter("rate", None, stream)
        self.create_frames(stream, self._generate,
                           rate=float(rate) if rate else None)
        return StreamEvent.OKAY, None

    def _generate(self, stream, frame_id):
        import time
        name = self.definition.name
        remaining = stream.variables[f"{name}.remaining"]
        if remaining <= 0:
            return StreamEvent.STOP, {"diagnostic": "count exhausted"}
        stream.variables[f"{name}.remaining"] = remaining - 1
        shapes = stream.variables[f"{name}.shapes"]
        index = self.emission_index(stream)
        shape = shapes[index % len(shapes)]  # cycle all configured shapes
        vocab = int(self.get_parameter("vocab_size", 8192, stream))
        rng = np.random.default_rng(
            int(self.get_parameter("seed", 0, stream)) + index)
        # t0 rides the swag so consumers can measure true frame latency
        # (declare a "t0" output port to propagate it)
        return StreamEvent.OKAY, {
            "tokens": rng.integers(0, vocab, shape, dtype=np.int32),
            "t0": time.time()}


class MultiModalSource(DataSource):
    """Emits {"audio", "image"} frames: items are [frequency_hz, seconds]
    tone specs plus a synthetic image (parameter image_shape, default
    [3, 32, 32]) -- the hermetic driver for multi-modal pipelines.
    Composes audio_io.synthesize_tone + image_io.synthesize_image."""

    def read_item(self, stream, item) -> dict:
        from .audio_io import SAMPLE_RATE, synthesize_tone
        from .image_io import synthesize_image
        shape = self.get_parameter("image_shape", [3, 32, 32], stream)
        seed = (int(self.get_parameter("seed", 0, stream))
                + self.emission_index(stream))
        if self.get_parameter("on_device", False, stream):
            # synthesize directly in HBM: no host->device transfer rides
            # the frame path (the HBM-resident design property; bench
            # measures model compute, not host ingest bandwidth)
            from .audio_io import synthesize_tone_on_device
            from .image_io import synthesize_image_on_device
            return {
                "audio": synthesize_tone_on_device(
                    float(item[0]), float(item[1])),
                "image": synthesize_image_on_device(shape, seed),
            }
        return {
            "audio": synthesize_tone(float(item[0]), float(item[1])),
            "image": synthesize_image(shape, seed),
        }


class JaxScale(ComputeElement):
    """tensor -> tensor * scale + offset: stateless pure-JAX element.
    scale/offset are dynamic parameters, so live updates (dashboard, EC
    share, stream overrides) apply without recompiling."""

    def dynamic_parameters(self, stream):
        return {"scale": float(self.get_parameter("scale", 2.0, stream)),
                "offset": float(self.get_parameter("offset", 0.0, stream))}

    def compute(self, state, tensor, scale, offset):
        return {"tensor": tensor * scale + offset}


class JaxMLP(ComputeElement):
    """Two-layer MLP over the last axis: a stateful ComputeElement whose
    params live on the element's mesh (definition "sharding" block)."""

    def setup(self):
        features = int(self.get_parameter("features", 16))
        hidden = int(self.get_parameter("hidden", 32))
        key = jax.random.PRNGKey(int(self.get_parameter("seed", 0)))
        k1, k2 = jax.random.split(key)
        return {
            "w1": jax.random.normal(k1, (features, hidden),
                                    jnp.float32) / np.sqrt(features),
            "b1": jnp.zeros((hidden,), jnp.float32),
            "w2": jax.random.normal(k2, (hidden, features),
                                    jnp.float32) / np.sqrt(hidden),
            "b2": jnp.zeros((features,), jnp.float32),
        }

    def compute(self, state, tensor):
        hidden = jax.nn.gelu(tensor @ state["w1"] + state["b1"])
        return {"tensor": hidden @ state["w2"] + state["b2"]}


class ToHost(ComputeElement):
    """Device -> host boundary: returns the tensor as numpy (the explicit
    Sink-side transfer point; everything upstream stays on device)."""

    def process_frame(self, stream, tensor):
        return StreamEvent.OKAY, {"tensor": np.asarray(tensor)}
