from .common_io import (                                      # noqa: F401
    DataSource, DataTarget, expand_data_sources)
from .text_io import (                                        # noqa: F401
    TextReadFile, TextSource, TextTransform, TextSample, TextWriteFile,
    TextOutput)
from .toys import (                                           # noqa: F401
    PE_Number, PE_Add, PE_Busy, PE_Multiply, PE_Sum2, PE_Inspect,
    PE_Metrics, PE_RandomIntegers, PE_RandomTensor, PE_Sum)
from .compute import (                                        # noqa: F401
    ArraySource, TokenSource, MultiModalSource, JaxScale, JaxMLP, ToHost)
from .ml import (                                             # noqa: F401
    LMForward, LMGenerate, SpeechToText, TextToSpeech, Detector,
    DetectionsPublish, TokensToText, TextToTokens)
from .vision import FaceDetect, ArucoDetect                   # noqa: F401
from .robot import (                                          # noqa: F401
    RobotActor, RobotControl, RobotCameraSource, parse_actions)
from .image_io import (                                       # noqa: F401
    ImageReadFile, ImageSource, ImageResize, ImageOverlay, ImageWriteFile,
    ImageOutput)
from .audio_io import (                                       # noqa: F401
    AudioReadFile, AudioWriteFile, ToneSource, AudioFraming, AudioSample,
    AudioFFT, AudioResample, MicrophoneSource, SpeakerSink)
from .video_io import (                                       # noqa: F401
    VideoReadFile, VideoSample, VideoWriteFile, VideoOutput)
from .webcam_io import VideoReadWebcam                        # noqa: F401
from .gstreamer_io import (                                   # noqa: F401
    VideoStreamReader, VideoStreamWriter, gst_available)
