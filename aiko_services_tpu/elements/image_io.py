# Image I/O elements.
#
# Capability parity with the reference image elements (reference:
# src/aiko_services/elements/media/image_io.py:82-255: ImageReadFile (PIL),
# ImageResize, ImageOverlay (cv2 boxes/labels over the YOLO "overlay"
# contract), ImageWriteFile, ImageOutput).  TPU-first differences: images
# travel as float32/uint8 arrays (CHW for compute elements), resize runs as
# jax.image on device, and ImageOverlay consumes the on-device detections
# dict from elements.ml.Detector, transferring only the small box tensors.

from __future__ import annotations

import numpy as np

from ..pipeline import PipelineElement, StreamEvent
from ..utils import get_logger
from .common_io import DataSource, DataTarget

__all__ = ["ImageReadFile", "ImageResize", "ImageOverlay",
           "ImageWriteFile", "ImageOutput", "ImageSource",
           "synthesize_image"]

_LOGGER = get_logger("image_io")


def synthesize_image(shape, seed: int) -> np.ndarray:
    """Deterministic random image (C, H, W) f32 in [0, 1]."""
    rng = np.random.default_rng(int(seed))
    return rng.random(tuple(int(size) for size in shape),
                      dtype=np.float32)


_DEVICE_SYNTH = None  # lazily-built module-level jit (stable identity)


def synthesize_image_on_device(shape, seed: int):
    """Deterministic random image synthesized directly in HBM.  The seed
    rides as a TRACED argument through a module-level jit -- one
    compilation per shape, never per frame."""
    global _DEVICE_SYNTH
    import functools

    import jax
    import jax.numpy as jnp

    if _DEVICE_SYNTH is None:
        @functools.partial(jax.jit, static_argnames=("shape",))
        def _synth(seed_value, shape):
            key = jax.random.fold_in(jax.random.PRNGKey(0), seed_value)
            return jax.random.uniform(key, shape, jnp.float32)

        _DEVICE_SYNTH = _synth
    return _DEVICE_SYNTH(jnp.uint32(seed),
                         tuple(int(size) for size in shape))


class ImageReadFile(DataSource):
    """data_sources of image paths -> {"image": (3, H, W) f32 [0,1]}."""

    def read_item(self, stream, item) -> dict:
        from PIL import Image
        with Image.open(item) as handle:
            array = np.asarray(handle.convert("RGB"), np.float32) / 255.0
        return {"image": array.transpose(2, 0, 1)}


class ImageSource(DataSource):
    """Synthetic image source: items are [channels, height, width] shapes
    (deterministic, seeded) -- the hermetic stand-in for cameras.

    on_device=true synthesizes with jax.random directly in HBM (no
    host->device transfer rides the frame path -- the framework's
    HBM-resident design property; benchmarks use this to measure the
    compute ceiling rather than host ingest bandwidth)."""

    def read_item(self, stream, item) -> dict:
        seed = (int(self.get_parameter("seed", 0, stream))
                + self.emission_index(stream))
        if self.get_parameter("on_device", False, stream):
            return {"image": synthesize_image_on_device(item, seed)}
        return {"image": synthesize_image(item, seed)}


class ImageResize(PipelineElement):
    """Resize to (resize_height, resize_width) on device via jax.image
    (reference ImageResize uses PIL on host, image_io.py:119-138)."""

    def process_frame(self, stream, image):
        import jax
        import jax.numpy as jnp
        height = int(self.get_parameter("resize_height", 256, stream))
        width = int(self.get_parameter("resize_width", 256, stream))
        image = jnp.asarray(image)
        batched = image.ndim == 4
        if not batched:
            image = image[None]
        resized = jax.image.resize(
            image, (image.shape[0], image.shape[1], height, width),
            method="bilinear")
        return StreamEvent.OKAY, {
            "image": resized if batched else resized[0]}


class ImageOverlay(PipelineElement):
    """Draw detection rectangles/labels onto the image (host-side, like
    the reference's cv2 overlay consumer, image_io.py:97-163).  Expects the
    Detector element's detections dict; emits the annotated image plus the
    reference-shaped overlay dict."""

    def process_frame(self, stream, image, detections):
        image_np = np.asarray(image)
        if image_np.ndim == 4:
            image_np = image_np[0]
        canvas = np.ascontiguousarray(
            (image_np.transpose(1, 2, 0) * 255.0).clip(0, 255)
            .astype(np.uint8))
        boxes = np.asarray(detections["boxes"])
        scores = np.asarray(detections["scores"])
        classes = np.asarray(detections["classes"])
        valid = np.asarray(detections["valid"])
        if boxes.ndim == 3:  # batched: first image
            boxes, scores, classes, valid = (
                boxes[0], scores[0], classes[0], valid[0])
        objects, rectangles = [], []
        try:
            import cv2
        except ImportError:  # pragma: no cover
            cv2 = None
        for box, score, class_id, ok in zip(boxes, scores, classes, valid):
            if not ok:
                continue
            x0, y0, x1, y1 = (int(v) for v in box)
            objects.append({"name": f"class_{int(class_id)}",
                            "confidence": float(score)})
            rectangles.append({"x": x0, "y": y0,
                               "w": x1 - x0, "h": y1 - y0})
            if cv2 is not None:
                cv2.rectangle(canvas, (x0, y0), (x1, y1), (0, 255, 0), 2)
                cv2.putText(canvas, f"{int(class_id)}:{score:.2f}",
                            (x0, max(y0 - 4, 10)),
                            cv2.FONT_HERSHEY_SIMPLEX, 0.4, (0, 255, 0), 1)
        overlay = {"objects": objects, "rectangles": rectangles}
        return StreamEvent.OKAY, {"image": canvas, "overlay": overlay}


class ImageWriteFile(DataTarget):
    """{"image"} -> image files at data_targets (templated paths)."""

    def process_frame(self, stream, image):
        from PIL import Image
        array = np.asarray(image)
        if array.ndim == 4:
            array = array[0]
        if array.ndim == 3 and array.shape[0] in (1, 3):  # CHW -> HWC
            array = array.transpose(1, 2, 0)
        if array.dtype != np.uint8:
            array = (array * 255.0).clip(0, 255).astype(np.uint8)
        path = self.next_target_path(stream)
        Image.fromarray(array.squeeze()).save(path)
        return StreamEvent.OKAY, {"image": image}


class ImageOutput(PipelineElement):
    """Log image shapes (reference ImageOutput shows on screen; headless
    here)."""

    def process_frame(self, stream, image):
        array = np.asarray(image)
        _LOGGER.info("%s: image %s %s", self.definition.name,
                     array.shape, array.dtype)
        return StreamEvent.OKAY, {"image": image}
