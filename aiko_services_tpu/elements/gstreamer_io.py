# GStreamer streaming elements (RTSP/RTMP video in/out).
#
# Capability parity with the reference gstreamer suite (reference:
# src/aiko_services/elements/gstreamer/video_reader.py:27-70,
# video_stream_reader/writer, utilities.py:17-33 codec pick): network
# video streams in and out of pipelines.  Hard-gated on PyGObject/Gst --
# absent in TPU pods -- with clear diagnostics; file/webcam elements
# (video_io, webcam_io) are the gst-free paths.

from __future__ import annotations

import numpy as np

from ..pipeline import PipelineElement, StreamEvent
from ..utils import get_logger
from .common_io import DataSource

__all__ = ["gst_available", "VideoStreamReader", "VideoStreamWriter"]

_LOGGER = get_logger("gstreamer_io")


def gst_available() -> bool:
    try:
        import gi
        gi.require_version("Gst", "1.0")
        from gi.repository import Gst  # noqa: F401
        return True
    except (ImportError, ValueError):
        return False


class VideoStreamReader(DataSource):
    """data_sources of stream URLs (rtsp://, rtmp://) -> {"image"} frames
    via a Gst appsink (reference video_stream_reader.py)."""

    def start_stream(self, stream, stream_id):
        if not gst_available():
            return StreamEvent.ERROR, {
                "diagnostic": "VideoStreamReader needs PyGObject/GStreamer"}
        import gi
        gi.require_version("Gst", "1.0")
        from gi.repository import Gst
        Gst.init(None)
        url = self.get_parameter("data_sources", [None], stream)[0]
        description = (
            f"urisourcebin uri={url} ! decodebin ! videoconvert ! "
            f"video/x-raw,format=RGB ! appsink name=sink max-buffers=30 "
            f"drop=true")
        gst_pipeline = Gst.parse_launch(description)
        sink = gst_pipeline.get_by_name("sink")
        gst_pipeline.set_state(Gst.State.PLAYING)
        stream.variables[f"{self.definition.name}.gst"] = (
            gst_pipeline, sink)
        self.create_frames(stream, self._frame_generator)
        return StreamEvent.OKAY, None

    def _frame_generator(self, stream, frame_id):
        from gi.repository import Gst
        _, sink = stream.variables[f"{self.definition.name}.gst"]
        sample = sink.emit("pull-sample")
        if sample is None:
            return StreamEvent.STOP, {"diagnostic": "stream ended"}
        buffer = sample.get_buffer()
        caps = sample.get_caps().get_structure(0)
        height, width = caps.get_value("height"), caps.get_value("width")
        ok, mapped = buffer.map(Gst.MapFlags.READ)
        if not ok:
            return StreamEvent.ERROR, {"diagnostic": "buffer map failed"}
        try:
            array = np.frombuffer(mapped.data, np.uint8).reshape(
                height, width, 3)
            image = array.astype(np.float32).transpose(2, 0, 1) / 255.0
        finally:
            buffer.unmap(mapped)
        return StreamEvent.OKAY, {"image": image}

    def stop_stream(self, stream, stream_id):
        record = stream.variables.get(f"{self.definition.name}.gst")
        if record is not None:
            from gi.repository import Gst
            record[0].set_state(Gst.State.NULL)
        return StreamEvent.OKAY, None

    def read_item(self, stream, item) -> dict:  # pragma: no cover
        raise NotImplementedError("VideoStreamReader streams via generator")


class VideoStreamWriter(PipelineElement):
    """{"image"} frames -> an RTMP/TCP video stream via appsrc + x264
    (reference video_stream_writer.py); gated like the reader."""

    def start_stream(self, stream, stream_id):
        if not gst_available():
            return StreamEvent.ERROR, {
                "diagnostic": "VideoStreamWriter needs PyGObject/GStreamer"}
        return StreamEvent.OKAY, None

    def process_frame(self, stream, image):
        import gi
        gi.require_version("Gst", "1.0")
        from gi.repository import Gst
        key = f"{self.definition.name}.gst"
        record = stream.variables.get(key)
        array = np.asarray(image)
        if array.ndim == 4:
            array = array[0]
        if array.shape[0] in (1, 3):
            array = array.transpose(1, 2, 0)
        if array.dtype != np.uint8:
            array = (array * 255.0).clip(0, 255).astype(np.uint8)
        if record is None:
            Gst.init(None)
            url = self.get_parameter("stream_url", None, stream)
            height, width = array.shape[:2]
            rate = int(self.get_parameter("frame_rate", 25, stream))
            description = (
                f"appsrc name=src is-live=true format=time "
                f"caps=video/x-raw,format=RGB,width={width},"
                f"height={height},framerate={rate}/1 ! videoconvert ! "
                f"x264enc tune=zerolatency ! flvmux ! rtmpsink "
                f"location={url}")
            gst_pipeline = Gst.parse_launch(description)
            source = gst_pipeline.get_by_name("src")
            gst_pipeline.set_state(Gst.State.PLAYING)
            record = stream.variables[key] = (gst_pipeline, source, [0])
        gst_pipeline, source, counter = record
        buffer = Gst.Buffer.new_wrapped(array.tobytes())
        rate = int(self.get_parameter("frame_rate", 25, stream))
        buffer.pts = counter[0] * Gst.SECOND // rate
        buffer.duration = Gst.SECOND // rate
        counter[0] += 1
        source.emit("push-buffer", buffer)
        return StreamEvent.OKAY, {"image": image}

    def stop_stream(self, stream, stream_id):
        record = stream.variables.get(f"{self.definition.name}.gst")
        if record is not None:
            from gi.repository import Gst
            record[1].emit("end-of-stream")
            record[0].set_state(Gst.State.NULL)
        return StreamEvent.OKAY, None
