# Webcam capture element.
#
# Capability parity with the reference webcam reader (reference:
# src/aiko_services/elements/media/webcam_io.py:35 VideoReadWebcam on
# /dev/videoN).  Gated on cv2 + an openable capture device; TPU pods have
# no cameras, so ImageSource/MultiModalSource are the hermetic stand-ins.

from __future__ import annotations

import numpy as np

from ..pipeline import StreamEvent
from ..utils import get_logger
from .common_io import DataSource

__all__ = ["VideoReadWebcam"]

_LOGGER = get_logger("webcam_io")


class VideoReadWebcam(DataSource):
    """data_sources of device indices/paths (e.g. [0] or ["/dev/video0"])
    -> continuous {"image": (3, H, W) f32} frames."""

    def start_stream(self, stream, stream_id):
        try:
            import cv2
        except ImportError:
            return StreamEvent.ERROR, {
                "diagnostic": "VideoReadWebcam needs cv2 (opencv)"}
        sources = self.get_parameter("data_sources", [0], stream)
        device = sources[0]
        if isinstance(device, str) and device.isdigit():
            device = int(device)
        capture = cv2.VideoCapture(device)
        if not capture.isOpened():
            return StreamEvent.ERROR, {
                "diagnostic": f"cannot open webcam {device!r}"}
        stream.variables[f"{self.definition.name}.capture"] = capture
        rate = self.get_parameter("rate", None, stream)
        self.create_frames(stream, self._frame_generator,
                           rate=float(rate) if rate else None)
        return StreamEvent.OKAY, None

    def _frame_generator(self, stream, frame_id):
        capture = stream.variables[f"{self.definition.name}.capture"]
        ok, frame_bgr = capture.read()
        if not ok:
            return StreamEvent.STOP, {"diagnostic": "webcam stream ended"}
        rgb = frame_bgr[:, :, ::-1].astype(np.float32) / 255.0
        return StreamEvent.OKAY, {"image": rgb.transpose(2, 0, 1)}

    def stop_stream(self, stream, stream_id):
        capture = stream.variables.get(
            f"{self.definition.name}.capture")
        if capture is not None:
            capture.release()
        return StreamEvent.OKAY, None

    def read_item(self, stream, item) -> dict:  # pragma: no cover
        raise NotImplementedError("VideoReadWebcam streams via generator")
