# ML pipeline elements backed by the in-framework model families
# (models/), replacing the reference's external-runtime elements:
# PE_WhisperX (reference: src/aiko_services/examples/speech/
# speech_elements.py:229-262), PE_LLM (examples/llm/elements_llm.py:137),
# YoloDetector (examples/yolo/yolo.py:51-87).  Those shell out to
# torch/CUDA processes; these run jit-compiled JAX on the element's mesh
# with HBM-resident tensors between stages.

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from dataclasses import replace

from ..models import (
    AsrConfig, BPETokenizer, DetectorConfig, TransformerConfig,
    count_params, detect, forward, generate, generate_stream,
    init_asr_params, init_detector_params, init_params, load_llama_params,
    load_pytree)
from ..models import configs as model_configs
from ..ops.device import as_device_array as _as_device_array
from ..pipeline import (
    AsyncHostElement, ComputeElement, PipelineElement, StreamEvent)
from ..utils import get_logger

__all__ = ["LMForward", "LMGenerate", "SpeechToText", "TextToSpeech",
           "Detector", "DetectionsPublish", "TokensToText",
           "TextToTokens"]

_LOGGER = get_logger("ml_elements")

# "preset" parameter values -> reference-scale configs (configs.py)
_LM_PRESETS = {
    "llama3_8b": model_configs.LLAMA3_8B,
    "llama32_1b": model_configs.LLAMA32_1B,
    "toy": model_configs.LM_TOY,
}
_ASR_PRESETS = {
    "whisper_tiny": model_configs.WHISPER_TINY,
    "whisper_small": model_configs.WHISPER_SMALL,
}
_DETECTOR_PRESETS = {
    "yolov8n": model_configs.YOLOV8N_SHAPE,
    "toy": model_configs.DETECTOR_TOY,
}


def _transformer_config(element) -> TransformerConfig:
    # sequence_parallel: long-context attention over the element mesh's
    # "seq" axis (ring prefill + sp decode); requires the element's
    # sharding block to name a seq axis
    from ..utils import truthy
    sequence_parallel = truthy(
        element.get_parameter("sequence_parallel", False))
    # "int8" halves KV-cache HBM and read bandwidth (serving batch
    # headroom); numerics pinned in tests/test_transformer.py
    kv_dtype = str(element.get_parameter("kv_dtype", "") or "")
    preset = element.get_parameter("preset")
    if preset:
        config = _LM_PRESETS[str(preset)]
        dtype = element.get_parameter("dtype")
        if dtype:
            config = replace(config, dtype=str(dtype))
        if sequence_parallel:
            config = replace(config, sequence_parallel=True)
        if kv_dtype:
            config = replace(config, kv_dtype=kv_dtype)
        return config
    return TransformerConfig(
        vocab_size=int(element.get_parameter("vocab_size", 8192)),
        d_model=int(element.get_parameter("d_model", 512)),
        n_layers=int(element.get_parameter("n_layers", 8)),
        n_heads=int(element.get_parameter("n_heads", 8)),
        n_kv_heads=int(element.get_parameter("n_kv_heads", 4)),
        d_ff=int(element.get_parameter("d_ff", 1536)),
        max_seq_len=int(element.get_parameter("max_seq_len", 2048)),
        dtype=str(element.get_parameter("dtype", "bfloat16")),
        sequence_parallel=sequence_parallel,
        kv_dtype=kv_dtype,
    )


def _load_transformer_params(element, config: TransformerConfig):
    """weights parameter: path to a safetensors checkpoint -- HuggingFace
    Llama naming (elements_llm.py:137-179 capability) or this framework's
    native save_pytree layout; absent -> seeded random init."""
    weights = element.get_parameter("weights")
    if weights:
        paths = weights if isinstance(weights, list) else [weights]
        probe = _probe_weight_names(weights)
        is_hf = "model.embed_tokens.weight" in probe
        probe.close()
        if is_hf:
            params = load_llama_params(paths, config)
        else:
            params = load_pytree(paths[0], dtype=config.dtype)
    else:
        params = init_params(
            config,
            jax.random.PRNGKey(int(element.get_parameter("seed", 0))))
    # "int8": weight-only serving quantization (halves the weight
    # streaming that bounds small-batch decode); numerics pinned in
    # tests/test_transformer.py::TestWeightOnlyInt8
    weight_dtype = str(element.get_parameter("weight_dtype", "") or "")
    if weight_dtype == "int8":
        from ..models import quantize_weights_int8
        params = quantize_weights_int8(params, config)
    elif weight_dtype:
        raise ValueError(
            f"weight_dtype must be '' or 'int8', got {weight_dtype!r}")
    return params


def _probe_weight_names(weights) -> "SafetensorsFile":
    """Container probe for format detection: opens the FIRST shard when
    weights is a list (shards share one naming convention).  Caller
    closes."""
    from ..models import SafetensorsFile
    paths = weights if isinstance(weights, list) else [weights]
    return SafetensorsFile(paths[0])


def _tokenizer_for(element) -> BPETokenizer | None:
    """tokenizer parameter: "default" (the committed BPE asset), a path to
    a tokenizer json (ours or HuggingFace tokenizer.json), or unset ->
    None (byte-level toy vocabulary)."""
    source = element.get_parameter("tokenizer")
    if not source:
        return None
    if source == "default":
        return BPETokenizer.default()
    return BPETokenizer.from_file(source)


def _default_state_spec(element, spec_factory) -> None:
    """Meshed model elements default their state spec to the family's
    megatron spec tree (filtered to the element mesh) instead of full
    replication -- an 8B replicated over v5e-8 would blow per-chip HBM;
    an explicit sharding.state in the definition still wins."""
    if element.mesh is not None and element._state_spec is None:
        from ..parallel import filter_specs
        element._state_spec = filter_specs(spec_factory(), element.mesh)


def _default_lm_state_spec(element, config) -> None:
    from ..models import param_specs, quantized_param_specs
    if str(element.get_parameter("weight_dtype", "") or "") == "int8":
        # the quantized tree carries w_scale planes the plain specs
        # don't know about
        _default_state_spec(
            element, lambda: quantized_param_specs(config, lm_head=True))
    else:
        _default_state_spec(
            element, lambda: param_specs(config, lm_head=True))


class LMForward(ComputeElement):
    """tokens (B, L) -> logits (B, L, V) + per-sequence mean NLL.

    The scoring workhorse: one full causal forward through the flagship
    transformer on the element's mesh.
    """

    def configure(self):
        if not hasattr(self, "config"):
            self.config = _transformer_config(self)
            _default_lm_state_spec(self, self.config)

    def setup(self):
        params = _load_transformer_params(self, self.config)
        _LOGGER.info("%s: transformer %.1fM params",
                     self.definition.name, count_params(params) / 1e6)
        return params

    def compute(self, state, tokens):
        logits = forward(state, self.config, tokens)
        log_probs = jax.nn.log_softmax(logits[:, :-1], axis=-1)
        taken = jnp.take_along_axis(
            log_probs, tokens[:, 1:, None], axis=-1, mode="clip")[..., 0]
        return {"logits": logits, "nll": -jnp.mean(taken, axis=-1)}


class LMGenerate(ComputeElement):
    """tokens (B, L) prompt -> generated (B, max_new_tokens) greedy tokens.

    Owns its KV cache; generation runs as one jit (prefill + fori_loop
    decode), so the pipeline mailbox only sees whole completions.

    Chat semantics (reference elements_llm.py:137-210): a "system_prompt"
    parameter and optional "chat_template" ({system}/{context}/{user}
    fields) format text prompts; with "detections_subscribe" the element
    watches the "{namespace}/detections" side-channel (or an explicit
    "detections_topic") and injects objects seen within
    "detections_window" seconds (default 1.0, the reference's freshness
    rule, elements_llm.py:196-210) into {context}.
    """

    def __init__(self, process, pipeline, definition):
        super().__init__(process, pipeline, definition)
        # continuous-mode engine state: present (None/empty) from
        # construction so observers can poll without racing the first
        # frame's lazy _ensure_engine()
        self._engine = None
        self._engine_frames = {}
        # subscribe at CONSTRUCTION (not lazy setup): detections published
        # before the first frame must still be visible to that frame's
        # prompt, like the reference's init-time subscription
        # (elements_llm.py:196-210)
        import time as time_module
        self._detections = None  # (names, seen_at)
        from ..utils import parse, truthy
        topic = self.get_parameter("detections_topic")
        if topic or truthy(self.get_parameter("detections_subscribe",
                                              False)):
            topic = str(topic or f"{self.process.namespace}/detections")

            def handler(_topic, payload):
                try:
                    command, parameters = parse(payload)
                except ValueError:
                    return
                if command != "detections":
                    return
                names = (parameters[0] if parameters
                         and isinstance(parameters[0], list)
                         else parameters)
                self._detections = ([str(name) for name in names],
                                    time_module.time())

            self._detections_handler = (handler, topic)
            self.process.add_message_handler(handler, topic)

    def configure(self):
        if not hasattr(self, "config"):
            self.config = _transformer_config(self)
            _default_lm_state_spec(self, self.config)
            self.tokenizer = _tokenizer_for(self)

    def setup(self):
        return _load_transformer_params(self, self.config)

    def _format_prompt(self, stream, text: str) -> str:
        """Chat formatting: system prompt + fresh vision context + user
        turn.  Plain passthrough when neither is configured."""
        import time as time_module
        system = self.get_parameter("system_prompt", None, stream)
        template = self.get_parameter("chat_template", None, stream)
        context = ""
        if self._detections is not None:
            names, seen_at = self._detections
            window = float(self.get_parameter(
                "detections_window", 1.0, stream))
            if names and time_module.time() - seen_at <= window:
                context = ("Visible objects: "
                           + ", ".join(names) + ".\n")
        if not (system or template or context):
            return text
        template = template or "{system}\n{context}{user}"
        # plain substitution, NOT str.format: templates legitimately
        # contain literal braces (JSON / S-expression reply formats)
        return (template.replace("{system}", system or "")
                .replace("{context}", context)
                .replace("{user}", text))

    def stop(self) -> None:
        handler = getattr(self, "_detections_handler", None)
        if handler is not None:
            self.process.remove_message_handler(*handler)
            self._detections_handler = None
        super().stop()

    def _sp_cache(self, batch: int, max_len: int):
        """KV cache laid out for sequence-parallel decode: length sharded
        over the element mesh's seq axis (padded to divide it)."""
        from ..models import cache_specs, init_cache
        from ..parallel import filter_specs, shard_pytree
        if self.mesh is None or "seq" not in self.mesh.axis_names:
            raise ValueError(
                f"{self.definition.name}: sequence_parallel needs a "
                "sharding block whose axes include 'seq'")
        seq_size = self.mesh.shape["seq"]
        max_len = ((max_len + seq_size - 1) // seq_size) * seq_size
        return shard_pytree(
            init_cache(self.config, batch, max_len=max_len), self.mesh,
            filter_specs(cache_specs(sequence_parallel=True), self.mesh))

    def _encode_prompts(self, stream, text):
        """Text prompts -> left-padded (B, W) int32 token matrix plus the
        post-template prompt strings.  ONE definition shared by the
        closed-batch and continuous paths, so the two modes tokenize --
        and therefore generate -- identically."""
        prompts = [text] if isinstance(text, str) else list(text)
        if self.tokenizer is None:
            raise ValueError("text input needs a tokenizer parameter")
        prompts = [self._format_prompt(stream, prompt)
                   for prompt in prompts]
        encoded = [self.tokenizer.encode(p, bos=True) for p in prompts]
        width = max(len(ids) for ids in encoded)
        pad = self.tokenizer.pad_id or 0
        tokens = np.full((len(encoded), width), pad, np.int32)
        for row, ids in enumerate(encoded):
            tokens[row, width - len(ids):] = ids  # left-pad
        return tokens, prompts

    def process_frame(self, stream, tokens=None, text=None,
                      handoff=None, restore=None):
        import contextlib
        if self.disagg_role(stream) == "prefill":
            return self._process_frame_prefill(stream, tokens, text)
        if self.engine_managed(stream):
            return self._process_frame_continuous(stream, tokens, text,
                                                  handoff, restore)
        self._ensure_ready()
        max_new = int(self.get_parameter("max_new_tokens", 32, stream))
        formatted = None
        if tokens is None:
            if text is None:
                raise ValueError("LMGenerate needs tokens or text input")
            tokens, formatted = self._encode_prompts(stream, text)
        tokens = _as_device_array(tokens, jnp.int32)
        pad = ((self.tokenizer.pad_id or 0)
               if self.tokenizer is not None else 0)
        batch = tokens.shape[0]
        if self.config.sequence_parallel:
            # ring prefill shards the prompt over the seq axis: LEFT-pad
            # the prompt up to a seq-multiple with the SAME pad id as the
            # batch left-padding above (pad tokens are causally attended,
            # so a divergent id would change generation vs the unsharded
            # path for widths not divisible by the seq axis)
            seq_size = (self.mesh.shape.get("seq", 1)
                        if self.mesh is not None else 1)
            width = tokens.shape[1]
            target = ((width + seq_size - 1) // seq_size) * seq_size
            if target != width:
                pad_block = jnp.full(
                    (tokens.shape[0], target - width), pad, jnp.int32)
                tokens = jnp.concatenate([pad_block, tokens], axis=1)
            # the seq-sharded KV cache also shards BATCH over the data
            # axis: pad ragged batches (a single prompt is the common
            # serving case) with dummy rows, sliced off the output below
            data_size = (self.mesh.shape.get("data", 1)
                         if self.mesh is not None else 1)
            extra = (-batch) % data_size
            if extra:
                from ..utils.padding import pad_axis_to
                tokens = pad_axis_to(tokens, 0, batch + extra,
                                     pad_value=pad)
        # sequence_parallel: ring prefill + sp decode run shard_map over
        # the AMBIENT mesh, and the cache must be seq-sharded
        mesh_scope = (jax.set_mesh(self.mesh) if self.mesh is not None
                      else contextlib.nullcontext())
        with mesh_scope:
            cache = (self._sp_cache(tokens.shape[0],
                                    tokens.shape[1] + max_new)
                     if self.config.sequence_parallel else None)
            if bool(self.get_parameter("stream_tokens", False, stream)):
                # streamed serving path: publish token chunks to /out as
                # they decode (reference capability: Ollama streaming)
                chunk = int(self.get_parameter("stream_chunk", 8, stream))
                blocks = []
                for offset, block in generate_stream(
                        self.state, self.config, tokens, max_new,
                        cache=cache, chunk=chunk):
                    block = block[:batch]  # drop batch-padding rows
                    blocks.append(block)
                    payload = block.tolist()
                    if self.tokenizer is not None:
                        payload = [self.tokenizer.decode(row)
                                   for row in block]
                    self.publish_out("tokens",
                                     [stream.stream_id, offset, payload])
                out = np.concatenate(blocks, axis=1)
            else:
                out, _ = generate(self.state, self.config, tokens,
                                  max_new, cache=cache)
                out = out[:batch]
        result = {"generated": out}
        if formatted is not None:
            result["prompt"] = formatted  # post-template (observability)
        if self.tokenizer is not None:
            result["text"] = [self.tokenizer.decode(np.asarray(row))
                              for row in np.asarray(out)]
        return StreamEvent.OKAY, result

    # -- continuous batching (decode/ engine) ------------------------------
    #
    # `continuous: true` swaps the whole-completion jit (prefill +
    # fori_loop above) for the slot-based DecodeEngine: each frame's
    # rows are SUBMITTED as requests and the frame parks
    # (StreamEvent.PENDING) while the engine interleaves its decode
    # steps with every other in-flight frame's.  The pump rides the
    # element's own mailbox -- one device step per message -- so new
    # frames arriving on the pipeline mailbox are admitted into the
    # RUNNING decode loop at prefill boundaries instead of convoying
    # behind a closed batch.  Completions resume their frame through
    # the ordinary process_frame_response path, bit-identical to the
    # closed-batch output for the same token rows.

    def engine_managed(self, stream):
        from ..utils import truthy
        return truthy(self.get_parameter("continuous", False, stream))

    def disagg_role(self, stream=None) -> str:
        """Disaggregated-fleet role: "" (co-located, the default),
        "prefill" (prompt kernels only -- frames return a KV handoff
        instead of tokens), or "decode" (the continuous engine, which
        ADOPTS incoming handoffs instead of re-prefilling)."""
        return str(self.get_parameter("role", "", stream) or "")

    def _ensure_engine(self):
        engine = getattr(self, "_engine", None)
        if engine is not None:
            return engine
        self._ensure_ready()
        if self.mesh is not None or self.config.sequence_parallel:
            raise ValueError(
                f"{self.definition.name}: continuous mode runs the paged "
                f"decode engine single-device; drop the sharding mesh / "
                f"sequence_parallel or use the closed-batch path")
        from ..decode import DecodeEngine
        telemetry = getattr(self.pipeline, "telemetry", None)
        registry = (telemetry.registry if telemetry is not None
                    and telemetry.enabled else None)
        kv_blocks = self.get_parameter("kv_blocks")
        max_context = self.get_parameter("max_context")
        eos_id = self.get_parameter("eos_id")
        prefill_chunk = self.get_parameter("prefill_chunk_size")
        draft_params, draft_config, spec_k = self._speculative_setup()
        prefix_spec = self.get_parameter("prefix_policy")
        prefix_policy = None
        if prefix_spec:
            # cross-request prefix KV reuse (decode/prefix.py): the
            # spec parses through the AIKO411 grammar AS-IS (string or
            # dict, same value lint checked) -- a bad value fails here
            # with the same message `aiko lint` reports
            from ..decode.prefix import PrefixPolicy
            prefix_policy = PrefixPolicy.parse(prefix_spec)
            prefix_policy.validate_engine()
        self._engine = DecodeEngine(
            self.state, self.config,
            decode_slots=int(self.get_parameter("decode_slots", 4)),
            kv_block_size=int(self.get_parameter("kv_block_size", 16)),
            kv_blocks=int(kv_blocks) if kv_blocks else None,
            max_context=int(max_context) if max_context else None,
            eos_id=int(eos_id) if eos_id is not None else None,
            prefill_chunk_size=(int(prefill_chunk) if prefill_chunk
                                else None),
            draft_params=draft_params, draft_config=draft_config,
            spec_k=spec_k,
            prefix_policy=prefix_policy,
            registry=registry)
        self._prefix_heads_shared = ""
        self._engine_frames = {}
        self._pump_posted = False
        self._checkpointer = None
        spec = self.get_parameter("checkpoint")
        if spec:
            # warm KV failover (decode/checkpoint.py): ship incremental
            # decode-state snapshots to the named keeper so a crash
            # restores on a survivor instead of re-prefilling.  The
            # spec parses through the AIKO409 grammar -- a bad value
            # fails here with the same message `aiko lint` reports
            from ..decode.checkpoint import (
                CheckpointPolicy, DecodeCheckpointer)
            # parse the spec AS-IS: the grammar accepts both directive
            # strings and dicts, and lint checked the same value --
            # stringifying a dict here would reject what lint admitted
            policy = CheckpointPolicy.parse(spec)
            policy.validate_engine()
            on_checkpoint = (telemetry.record_checkpoint
                             if telemetry is not None
                             and telemetry.enabled else None)
            self._checkpointer = DecodeCheckpointer(
                self._engine, policy, registry=registry,
                node=self.definition.name, on_checkpoint=on_checkpoint)
        return self._engine

    def _speculative_setup(self):
        """`speculative` parameter -> (draft_params, draft_config, k).
        `draft=self` shrinks the TARGET's config family (layers/d_ff
        overrides, random-init from `seed` -- the bench/test shape);
        `draft=<preset>` instantiates an _LM_PRESETS entry, which must
        share the target's vocabulary.  Greedy-exact acceptance means a
        WEAK draft only costs acceptance length, never correctness."""
        spec = self.get_parameter("speculative")
        if not spec:
            return None, None, 0
        from ..analyze.policies import parse_speculative_spec
        parsed = parse_speculative_spec(str(spec))
        draft = parsed["draft"]
        if draft == "self":
            draft_config = self.config
        elif draft in _LM_PRESETS:
            draft_config = _LM_PRESETS[draft]
            if draft_config.dtype != self.config.dtype:
                draft_config = replace(draft_config,
                                       dtype=self.config.dtype)
        else:
            raise ValueError(
                f"{self.definition.name}: speculative draft={draft!r} "
                f"is neither 'self' nor a preset "
                f"{sorted(_LM_PRESETS)}")
        overrides = {}
        if "layers" in parsed:
            overrides["n_layers"] = parsed["layers"]
        if "d_ff" in parsed:
            overrides["d_ff"] = parsed["d_ff"]
        if overrides:
            draft_config = replace(draft_config, **overrides)
        draft_params = init_params(
            draft_config,
            jax.random.PRNGKey(int(parsed.get("seed", 0))))
        return draft_params, draft_config, parsed["k"]

    # -- disaggregated prefill (decode/disagg.py PrefillEngine) ------------
    #
    # `role: prefill` turns the element into the prompt half of a
    # split fleet: frames run paged_prefill / paged_prefill_chunk into
    # a private paged pool and the response carries a KV HANDOFF (one
    # JSON-safe record per row: prompt + first token + `__tensorref__`
    # descriptors for the prompt's KV blocks) instead of tokens.  A
    # decode-role replica adopts the handoff into a free slot and
    # continues greedy decode bit-identically -- no re-prefill.

    def _ensure_prefill_engine(self):
        engine = getattr(self, "_prefill_engine", None)
        if engine is not None:
            return engine
        self._ensure_ready()
        if self.mesh is not None or self.config.sequence_parallel:
            raise ValueError(
                f"{self.definition.name}: role=prefill runs the paged "
                f"prefill engine single-device; drop the sharding mesh "
                f"/ sequence_parallel")
        from ..decode import PrefillEngine
        telemetry = getattr(self.pipeline, "telemetry", None)
        registry = (telemetry.registry if telemetry is not None
                    and telemetry.enabled else None)
        max_context = self.get_parameter("max_context")
        prefill_chunk = self.get_parameter("prefill_chunk_size")
        self._prefill_engine = PrefillEngine(
            self.state, self.config,
            kv_block_size=int(self.get_parameter("kv_block_size", 16)),
            max_context=int(max_context) if max_context else None,
            prefill_chunk_size=(int(prefill_chunk) if prefill_chunk
                                else None),
            registry=registry)
        self._prefill_frames = {}
        self._prefill_pump_posted = False
        return self._prefill_engine

    def _process_frame_prefill(self, stream, tokens, text):
        import time
        engine = self._ensure_prefill_engine()
        if tokens is None:
            if text is None:
                raise ValueError("LMGenerate needs tokens or text input")
            tokens, _ = self._encode_prompts(stream, text)
        tokens = np.asarray(tokens, np.int32)
        if tokens.ndim == 1:
            tokens = tokens[None]
        max_new = int(self.get_parameter("max_new_tokens", 32, stream))
        key = (stream.stream_id, stream.current_frame_id)
        # fleet tracing: the prefill frame's (possibly gateway-minted)
        # trace context is frozen here so the finished KV handoff can
        # carry it -- the adopting decode replica parents its adopt
        # span under THIS prefill hop, not just the gateway root
        frame = stream.frames.get(stream.current_frame_id)
        trace = getattr(frame, "trace", None) if frame is not None \
            else None
        context = None
        if trace is not None:
            from ..observe.trace import make_trace_context
            context = make_trace_context(trace)
        self._prefill_frames[key] = {
            "rows": tokens.shape[0], "done": {},
            "submitted_at": time.perf_counter(),
            "trace_context": context,
        }
        try:
            for row in range(tokens.shape[0]):
                engine.submit(key + (row,), tokens[row], max_new)
        except ValueError:
            self._prefill_frames.pop(key, None)
            engine.cancel(lambda rid: rid[:2] == key)
            raise
        self._schedule_prefill_pump()
        return StreamEvent.PENDING, None

    def _schedule_prefill_pump(self):
        if not getattr(self, "_prefill_pump_posted", False):
            self._prefill_pump_posted = True
            self.post_message("_prefill_pump", [])

    def _prefill_pump(self):
        self._prefill_pump_posted = False
        engine = getattr(self, "_prefill_engine", None)
        if engine is None:
            return
        try:
            for handoff in engine.step():
                self._finish_prefill_handoff(handoff)
        except Exception as error:
            self._fail_prefill_frames(error)
            return
        if engine.has_work():
            self._schedule_prefill_pump()

    def _finish_prefill_handoff(self, handoff):
        import time
        stream_id, frame_id, row = handoff["request_id"]
        key = (stream_id, frame_id)
        entry = self._prefill_frames.get(key)
        if entry is None:
            return  # stream destroyed mid-prefill
        record = dict(handoff)
        record["request_id"] = row  # peer-local identity, JSON-safe
        if entry.get("trace_context"):
            # the handoff DESCRIPTOR carries the trace context: even a
            # handoff forwarded through a telemetry-disabled gateway
            # still links decode's adopt span to this prefill hop
            record["trace_context"] = entry["trace_context"]
        entry["done"][row] = record
        if len(entry["done"]) < entry["rows"]:
            return
        outputs = {"handoff": [entry["done"][r]
                               for r in range(entry["rows"])]}
        self.pipeline.post_message("process_frame_response", [
            {"stream_id": stream_id, "frame_id": frame_id,
             "node": self.definition.name,
             "time": time.perf_counter() - entry["submitted_at"]},
            outputs])
        del self._prefill_frames[key]

    def _fail_prefill_frames(self, error):
        """Prefill engine failure: release every PENDING frame with an
        error response (the stream applies its on_error policy; a
        disagg gateway degrades the frame to a local decode-side
        prefill) and rebuild the engine lazily."""
        _LOGGER.error("%s: prefill engine failed, releasing %d frames: "
                      "%s", self.definition.name,
                      len(getattr(self, "_prefill_frames", {})), error)
        frames = getattr(self, "_prefill_frames", {})
        self._prefill_frames = {}
        self._prefill_engine = None
        for stream_id, frame_id in frames:
            self.pipeline.post_message("process_frame_response", [
                {"stream_id": stream_id, "frame_id": frame_id,
                 "node": self.definition.name, "event": "error"}, {}])

    def prefill_stats(self) -> dict | None:
        """Live prefill-engine occupancy; None before the first
        prefill frame."""
        engine = getattr(self, "_prefill_engine", None)
        return None if engine is None else engine.stats()

    def _process_frame_continuous(self, stream, tokens, text,
                                  handoff=None, restore=None):
        import time
        engine = self._ensure_engine()
        formatted = None
        handoffs = None
        if handoff:
            # disaggregated hop 2: adopt the prefill pool's KV blocks
            # instead of re-prefilling the prompt locally
            handoffs = handoff if isinstance(handoff, list) else [handoff]
            rows = len(handoffs)
        else:
            if tokens is None:
                if text is None:
                    raise ValueError(
                        "LMGenerate needs tokens, text, or handoff "
                        "input")
                tokens, formatted = self._encode_prompts(stream, text)
            tokens = np.asarray(tokens, np.int32)
            if tokens.ndim == 1:
                tokens = tokens[None]
            rows = tokens.shape[0]
        max_new = int(self.get_parameter("max_new_tokens", 32, stream))
        key = (stream.stream_id, stream.current_frame_id)
        from ..utils import truthy
        self._engine_frames[key] = {
            "rows": rows, "done": {},
            "formatted": formatted, "max_new": max_new,
            "submitted_at": time.perf_counter(),
            "stream_tokens": truthy(self.get_parameter(
                "stream_tokens", False, stream)),
            "chunk": max(1, int(self.get_parameter(
                "stream_chunk", 8, stream))),
            "buffers": {},
            # cross-replica prefix store (decode/prefix.py): the
            # gateway injects `prefix_keeper` when it runs both a
            # checkpoint keeper and a prefix policy; prompts are kept
            # so finished requests can export their cached prefix
            "prefix_keeper": str(self.get_parameter(
                "prefix_keeper", "", stream) or ""),
            "prompts": None,
        }
        # submission order == row order; the engine's FIFO admission
        # keeps caller-observed ordering deterministic.  A rejected row
        # (e.g. prompt + max_new over max_context) must not leak the
        # frame entry or strand already-queued sibling rows
        try:
            if handoffs is not None:
                timeout = self.get_parameter("adopt_timeout", None,
                                             stream)
                adopt_s = time.perf_counter()
                upstream = None
                for row, record in enumerate(handoffs):
                    if isinstance(record, dict) \
                            and "trace_context" in record:
                        # the prefill hop's trace identity rides the
                        # handoff descriptor: strip it before the
                        # engine sees the record, keep it as the adopt
                        # span's parent link
                        record = dict(record)
                        upstream = record.pop("trace_context") or \
                            upstream
                    report = engine.adopt_request(
                        key + (row,), record,
                        timeout=(float(timeout) if timeout else None))
                    for rid, _offset, token in report.emitted:
                        self._buffer_streamed_token(rid, token)
                    for completion in report.completions:
                        self._finish_request(completion)
                self._note_adopt_span(stream, key,
                                      time.perf_counter() - adopt_s,
                                      parent=upstream)
            elif restore:
                self._restore_rows(stream, key, tokens, max_new,
                                   restore)
            else:
                if engine.prefix is not None:
                    self._engine_frames[key]["prompts"] = tokens
                    self._prewarm_prefix(stream, tokens)
                for row in range(rows):
                    engine.submit(key + (row,), tokens[row], max_new)
        except ValueError:
            self._engine_frames.pop(key, None)
            engine.cancel(lambda rid: rid[:2] == key)
            raise
        self._schedule_pump()
        return StreamEvent.PENDING, None

    def _restore_rows(self, stream, key, tokens, max_new,
                      restore) -> None:
        """Warm failover (decode/checkpoint.py): a gateway replaying a
        dead decode replica's frames attached a RESTORE hint naming
        the checkpoint keeper.  Each row asks the keeper for its
        snapshot (keyed by (stream_id, frame_id, row) -- stable across
        replicas) and resumes via engine.restore_request; a missing/
        stale/unfetchable snapshot degrades to the ordinary re-prefill
        inside restore_request, so the frame is never lost.  The
        optional `resume_from` map (row -> highest token offset the
        client already holds) makes re-emission resume gaplessly."""
        import time
        from ..decode.checkpoint import get_keeper
        engine = self._engine
        hint = restore if isinstance(restore, dict) else {}
        keeper = get_keeper(str(hint.get("keeper") or ""))
        resume_map = hint.get("resume_from") or {}
        timeout = self.get_parameter("adopt_timeout", None, stream)
        restore_s = time.perf_counter()
        entry = self._engine_frames[key]
        for row in range(tokens.shape[0]):
            request_key = key + (row,)
            record = None
            if keeper is not None:
                try:
                    record = keeper.restore(request_key)
                except (KeyError, ValueError) as error:
                    _LOGGER.info("%s: keeper has no snapshot for %r "
                                 "(%s); re-prefilling",
                                 self.definition.name, request_key,
                                 error)
            resume = int(resume_map.get(row,
                                        resume_map.get(str(row), 0))
                         or 0)
            restores_before = engine.counters["restores"]
            report = engine.restore_request(
                request_key, record, prompt_tokens=tokens[row],
                max_new_tokens=max_new,
                timeout=(float(timeout) if timeout else None),
                resume_from=resume)
            if (resume and entry["stream_tokens"]
                    and engine.counters["restores"] > restores_before):
                # a RESTORED row resumes emission at the client's
                # floor: the chunk buffer must publish offsets from
                # there, not from 0 -- an offset-keyed consumer would
                # otherwise overwrite its held prefix with later
                # tokens.  A FALLBACK row re-prefills and re-emits
                # from offset 0, so its buffer keeps the default start
                entry["buffers"][row] = [min(resume, max_new), []]
            for rid, _offset, token in report.emitted:
                self._buffer_streamed_token(rid, token)
            for completion in report.completions:
                self._finish_request(completion)
        # restores ride the adopt span category: both are KV
        # migrations, and tune's migration-bound classifier should see
        # failover restores exactly as it sees prefill-pool adoptions;
        # the hint's trace context (frozen at failover) parents the
        # span under the gateway's replayed-frame root
        hint_context = hint.get("trace_context")
        self._note_adopt_span(
            stream, key, time.perf_counter() - restore_s,
            parent=(hint_context
                    if isinstance(hint_context, dict) else None))

    def _note_adopt_span(self, stream, key, elapsed_s: float,
                         parent: dict | None = None) -> None:
        """Record the adopt (KV-migration) span on the frame trace so
        `aiko tune` can attribute migration-bound waits distinctly from
        slot-queue waits.  `parent` is the upstream (prefill-hop) trace
        context the handoff descriptor carried, linking the adopt span
        across processes in a merged fleet artifact."""
        telemetry = getattr(self.pipeline, "telemetry", None)
        if telemetry is None or not telemetry.enabled:
            return
        telemetry.record_adopt(
            self.pipeline.streams.get(key[0]), key[1],
            self.definition.name, elapsed_s, parent=parent)

    def _prewarm_prefix(self, stream, tokens) -> None:
        """Second-chance CROSS-REPLICA prefix pre-warm: when this
        prompt's hash chain has no local cache hit, ask the stream's
        `prefix_keeper` (injected by the gateway when it runs both a
        checkpoint keeper and a prefix policy) for a snapshot keyed by
        the chain head and adopt it into the local cached tier over
        the transfer plane -- so a follow-up turn landing on a COLD
        replica still skips the shared-prefix prefill.  Best-effort
        end to end: any miss/failure just means a normal cold
        prefill."""
        engine = self._engine
        keeper_name = str(self.get_parameter(
            "prefix_keeper", "", stream) or "")
        if not keeper_name:
            return
        from ..decode.checkpoint import get_keeper
        from ..decode.prefix import chain_hashes
        keeper = get_keeper(keeper_name)
        if keeper is None:
            return
        timeout = self.get_parameter("adopt_timeout", None, stream)
        for row in range(tokens.shape[0]):
            hashes = chain_hashes(tokens[row],
                                  engine.blocks.block_size)
            if not hashes or engine.prefix.lookup(hashes):
                continue      # local hit (or sub-block prompt)
            try:
                record = keeper.restore(("prefix", hashes[0]))
            except (KeyError, ValueError):
                continue
            engine.adopt_prefix(
                record, timeout=(float(timeout) if timeout else None))

    def _export_prefix(self, entry: dict, row: int) -> None:
        """Offer a finished request's cached prefix blocks to the
        stream's prefix keeper (once per chain: skipped when the
        keeper already holds it).  The keeper ingests asynchronously,
        so this never blocks the engine pump."""
        engine = getattr(self, "_engine", None)
        if engine is None or engine.prefix is None:
            return
        prompts = entry.get("prompts")
        if prompts is None:
            return
        from ..decode.checkpoint import get_keeper
        keeper = get_keeper(entry["prefix_keeper"])
        if keeper is None:
            return
        snapshot = engine.export_prefix_snapshot(prompts[row])
        if snapshot is None:
            return
        if keeper.kept_blocks(tuple(snapshot["request_id"])) \
                >= snapshot["blocks_total"]:
            return
        keeper.store(snapshot)

    def _publish_prefix_heads(self, engine) -> None:
        """Mirror the cache's resident chain-head digests into the
        pipeline share (comma-joined, on change only) -- the compact
        summary gateway prefix-affinity routing scores against."""
        heads = ",".join(engine.prefix_heads())
        if heads != getattr(self, "_prefix_heads_shared", ""):
            self._prefix_heads_shared = heads
            self.pipeline.set_parameter("prefix_heads", heads)

    def _schedule_pump(self):
        """At most ONE pump message in flight: each tick runs one fused
        decode step and re-posts itself while the engine has work, so
        the mailbox interleaves admissions with decode progress."""
        if not getattr(self, "_pump_posted", False):
            self._pump_posted = True
            self.post_message("_engine_pump", [])

    def _engine_pump(self):
        self._pump_posted = False
        engine = getattr(self, "_engine", None)
        if engine is None:
            return
        try:
            report = engine.step()
            for request_id, offset, token in report.emitted:
                self._buffer_streamed_token(request_id, token)
            for completion in report.completions:
                self._finish_request(completion)
            if getattr(self, "_checkpointer", None) is not None:
                # live cadence override: the gateway autopilot retunes
                # `checkpoint_every` via set_element_parameter, so the
                # policy is re-read each step (wire values arrive as
                # strings) and takes effect on the NEXT cadence tick --
                # never a checkpointer rebuild, never a restart
                cadence = self.get_parameter("checkpoint_every")
                if cadence is not None:
                    try:
                        cadence = int(cadence)
                    except (TypeError, ValueError):
                        cadence = None
                if cadence is not None and cadence > 0 and cadence \
                        != self._checkpointer.policy.checkpoint_every:
                    self._checkpointer.policy.checkpoint_every = cadence
                # one cadence tick per engine step; tick() never raises
                # (a failed snapshot keeps the keeper's previous one)
                self._checkpointer.tick()
            if engine.prefix is not None:
                self._publish_prefix_heads(engine)
        except Exception as error:
            # the mailbox swallows exceptions, so an unguarded failure
            # here (device error, tokenizer crash) would strand every
            # PENDING frame with the pump never re-posted
            self._fail_engine_frames(error)
            return
        if engine.has_work():
            self._schedule_pump()

    def _fail_engine_frames(self, error):
        """Engine failure: every in-flight frame gets an error response
        (the AsyncHostElement contract, element.py) so streams apply
        their on_error policy instead of hanging; the engine is dropped
        and lazily rebuilt by the next continuous frame."""
        _LOGGER.error("%s: decode engine failed, releasing %d in-flight "
                      "frame(s): %s", self.definition.name,
                      len(self._engine_frames), error)
        frames, self._engine_frames = self._engine_frames, {}
        self._engine = None
        self._checkpointer = None  # rebuilt with the engine
        for stream_id, frame_id in frames:
            self.pipeline.post_message("process_frame_response", [
                {"stream_id": stream_id, "frame_id": frame_id,
                 "node": self.definition.name, "event": "error"}, {}])

    def _buffer_streamed_token(self, request_id, token):
        entry = self._engine_frames.get(request_id[:2])
        if entry is None or not entry["stream_tokens"]:
            return
        row = request_id[2]
        buffer = entry["buffers"].setdefault(row, [0, []])
        buffer[1].append(int(token))
        if len(buffer[1]) >= entry["chunk"]:
            self._flush_stream_buffer(request_id[:2], entry, row)

    def _flush_stream_buffer(self, key, entry, row):
        """Publish one token chunk for one request row:
        `(token_chunk stream_id frame_id row offset payload)` -- offset
        is the row's completion-token offset of the chunk's first token
        (a preempted request's regenerated tokens are never re-emitted,
        so offsets stay gapless).  Deliberately NOT the closed-batch
        `(tokens stream_id offset payload)` command: one command name,
        one schema."""
        start, chunk = entry["buffers"].pop(row, (0, []))
        if not chunk:
            return
        payload = ([self.tokenizer.decode(np.asarray(chunk, np.int32))]
                   if self.tokenizer is not None else [chunk])
        self.publish_out("token_chunk",
                         [key[0], key[1], row, start, payload])
        entry["buffers"][row] = [start + len(chunk), []]

    def _finish_request(self, completion):
        import time
        checkpointer = getattr(self, "_checkpointer", None)
        if checkpointer is not None:
            # a cleanly finished request's snapshots are dead weight on
            # the keeper; FENCED streams (failover) deliberately skip
            # this -- their snapshots are what the survivor restores
            checkpointer.forget(completion.request_id)
        stream_id, frame_id, row = completion.request_id
        key = (stream_id, frame_id)
        entry = self._engine_frames.get(key)
        if entry is None:
            return  # stream destroyed mid-decode; engine.cancel raced
        if entry["stream_tokens"]:
            self._flush_stream_buffer(key, entry, row)
            entry["buffers"].pop(row, None)
        if entry.get("prefix_keeper"):
            self._export_prefix(entry, row)
        entry["done"][row] = completion
        if len(entry["done"]) < entry["rows"]:
            return
        # entry stays registered until the response is POSTED: a crash
        # in decode/telemetry below must leave the key visible to
        # _fail_engine_frames or the frame would park forever
        out = np.stack([entry["done"][r].tokens
                        for r in range(entry["rows"])])
        outputs = {"generated": out}
        if entry["formatted"] is not None:
            outputs["prompt"] = entry["formatted"]
        if self.tokenizer is not None:
            outputs["text"] = [self.tokenizer.decode(np.asarray(r))
                               for r in out]
        stats = [entry["done"][r].stats for r in range(entry["rows"])]
        pipeline = self.pipeline
        telemetry = getattr(pipeline, "telemetry", None)
        if telemetry is not None:
            stream = pipeline.streams.get(stream_id)
            frame = (stream.frames.get(frame_id)
                     if stream is not None else None)
            if frame is not None:
                telemetry.record_engine_frame(
                    frame, self.definition.name, stats)
        # "time" is the element-compute share only: the engine's slot
        # wait is reported as time_queue_{node} by record_engine_frame
        # above, so time_{node} (written from this value by
        # mark_resume) means the same thing on the engine-managed path
        # as on the fused/chained ones -- tune's queue-vs-compute
        # attribution depends on that
        queue_wait = max((float(s.get("queue_wait_s", 0.0))
                          for s in stats), default=0.0)
        total = time.perf_counter() - entry["submitted_at"]
        pipeline.post_message("process_frame_response", [
            {"stream_id": stream_id, "frame_id": frame_id,
             "node": self.definition.name,
             "time": max(total - queue_wait, 0.0)},
            outputs])
        del self._engine_frames[key]

    def stop_stream(self, stream, stream_id):
        engine = getattr(self, "_engine", None)
        if engine is not None:
            for key in [key for key in list(self._engine_frames)
                        if key[0] == stream_id]:
                self._engine_frames.pop(key, None)
            engine.cancel(lambda rid: rid[0] == stream_id)
        prefill = getattr(self, "_prefill_engine", None)
        if prefill is not None:
            for key in [key for key in list(self._prefill_frames)
                        if key[0] == stream_id]:
                self._prefill_frames.pop(key, None)
            prefill.cancel(lambda rid: rid[0] == stream_id)
        return super().stop_stream(stream, stream_id)

    def engine_stats(self) -> dict | None:
        """Live engine occupancy (dashboard / tests); None before the
        first continuous frame."""
        engine = getattr(self, "_engine", None)
        return None if engine is None else engine.stats()

    def checkpoint_stats(self) -> dict | None:
        """Live decode-checkpointer counters; None when the element
        runs without a `checkpoint` spec (or before the engine)."""
        checkpointer = getattr(self, "_checkpointer", None)
        return None if checkpointer is None else checkpointer.stats()

    def compute(self, state, **inputs):  # pragma: no cover
        raise NotImplementedError("LMGenerate overrides process_frame")

    def group_kernel(self, stream):
        """Fused micro-batch hook for the decode stage: greedy
        generation (prefill + fori_loop, already one device program)
        traced into the scheduler's fused group program.  Falls back to
        the chained path whenever process_frame does per-frame host
        work the kernel cannot reproduce: text prompts / tokenizer
        decode, token streaming, sequence-parallel padding, meshed
        placement."""
        from ..utils import truthy
        if type(self).process_frame is not LMGenerate.process_frame:
            # a subclass overriding process_frame (host postprocessing)
            # must not have its override silently bypassed by the
            # inherited fused kernel (mirrors the ComputeElement guard)
            return None
        self._ensure_ready()  # configure(): config + tokenizer exist
        if (self.mesh is not None or self.config.sequence_parallel
                or self.tokenizer is not None
                or truthy(self.get_parameter(
                    "stream_tokens", False, stream))
                or self.engine_managed(stream)
                or self.disagg_role(stream)):
            return None
        max_new = int(self.get_parameter("max_new_tokens", 32, stream))

        def build():
            config = self.config

            def kernel(state, tokens):
                out, _ = generate(state, config,
                                  jnp.asarray(tokens, jnp.int32),
                                  max_new)
                return {"generated": out}

            return kernel

        return self._cached_group_kernel(max_new, build), self.state

    def eval_kernel(self):
        """Static-analyzer hook (PipelineElement.eval_kernel): greedy
        generation as a pure kernel over a `tokens` input, with setup()
        as the state builder -- the analyzer proves `generated` shapes
        under jax.eval_shape without allocating the transformer."""
        if type(self).process_frame is not LMGenerate.process_frame:
            return None
        self.configure()
        if self.config.sequence_parallel:
            return None  # sp decode needs an ambient mesh to trace
        if self.disagg_role():
            # a disagg element's output contract is a handoff record /
            # adopted completion, not the pure generate() shape
            return None
        max_new = int(self.get_parameter("max_new_tokens", 32))
        config = self.config

        def kernel(state, tokens):
            out, _ = generate(state, config,
                              jnp.asarray(tokens, jnp.int32), max_new)
            return {"generated": out}

        return kernel, self.setup


# byte-level toy vocabulary shared by SpeechToText and TokensToText:
# 0=pad 1=sot 2=eot, 3..258 = bytes
_BYTE_OFFSET = 3


class SpeechToText(ComputeElement):
    """audio (B, samples) 16 kHz f32 -> token ids (B, max_tokens).

    The reference's PE_WhisperX seat (reference speech_elements.py:229-262:
    5 s windows through WhisperX/CUDA); here the log-mel frontend and the
    encoder-decoder transformer run as ONE jit on the element's mesh.
    """

    def configure(self):
        if hasattr(self, "config"):
            return
        preset = self.get_parameter("preset")
        if preset:
            self.config = _ASR_PRESETS[str(preset)]
            dtype = self.get_parameter("dtype")
            if dtype:
                self.config = replace(self.config, dtype=str(dtype))
            # serving window override: chunked serving (5 s chunks, the
            # reference cadence) need not pay the full 30 s whisper
            # window -- encoder cost scales with max_frames
            max_frames = self.get_parameter("max_frames")
            if max_frames:
                self.config = replace(self.config,
                                      max_frames=int(max_frames))
        else:
            self.config = AsrConfig(
                n_mels=int(self.get_parameter("n_mels", 80)),
                d_model=int(self.get_parameter("d_model", 384)),
                enc_layers=int(self.get_parameter("enc_layers", 4)),
                dec_layers=int(self.get_parameter("dec_layers", 4)),
                n_heads=int(self.get_parameter("n_heads", 6)),
                vocab_size=int(self.get_parameter("vocab_size", 1024)),
                max_frames=int(self.get_parameter("max_frames", 1500)),
                dtype=str(self.get_parameter("dtype", "bfloat16")),
            )
        # HF whisper checkpoints decode between the real special tokens
        # (<|startoftranscript|> 50258, <|endoftext|> 50257); resolved
        # HERE (not setup) so the checkpoint-restore path -- which skips
        # setup -- still decodes with the right ids
        weights = self.get_parameter("weights")
        self._hf_weights = False
        if weights:
            probe = _probe_weight_names(weights)
            self._hf_weights = "model.encoder.conv1.weight" in probe
            probe.close()
            if self._hf_weights:
                self.config = replace(
                    self.config,
                    sot_token=int(self.get_parameter("sot_token", 50258)),
                    eot_token=int(self.get_parameter("eot_token", 50257)))
        # meshed ASR defaults to the megatron TP spec tree (HF bias
        # leaves absent from the spec replicate -- correct under
        # global-view SPMD)
        from ..models import asr_param_specs
        _default_state_spec(
            self, lambda: asr_param_specs(self.config))

    def setup(self):
        weights = self.get_parameter("weights")
        if weights:
            # container format decided in configure() (restore-safe):
            # HF openai/whisper-* naming loads through the whisper
            # name-map (pretrained transcription, reference
            # speech_elements.py:229-262); otherwise the framework's
            # own save_pytree layout
            if self._hf_weights:
                from ..models import load_whisper_params
                params = load_whisper_params(weights, self.config)
            else:
                params = load_pytree(weights, dtype=self.config.dtype)
        else:
            params = init_asr_params(
                self.config,
                jax.random.PRNGKey(int(self.get_parameter("seed", 0))))
        _LOGGER.info("%s: ASR %.1fM params", self.definition.name,
                     count_params(params) / 1e6)
        return params

    def process_frame(self, stream, audio):
        from ..models.asr import transcribe_audio
        self._ensure_ready()
        audio = _as_device_array(audio, jnp.float32)
        if audio.ndim == 1:
            audio = audio[None]
        max_tokens = int(self.get_parameter("max_tokens", 32, stream))
        # frontend + model as ONE launch (transcribe_audio): splitting
        # them costs a second dispatch round-trip per frame
        tokens = transcribe_audio(self.state, self.config, audio,
                                  max_tokens=max_tokens)
        return StreamEvent.OKAY, {"tokens": tokens}

    def group_kernel(self, stream):
        """Fused micro-batch hook: log-mel frontend + transcription as a
        pure batch kernel inside the scheduler's fused group program.
        max_tokens is a compile-time loop bound, so kernels cache per
        resolved value (stable identity keeps the scheduler's compiled
        program cached)."""
        if type(self).process_frame is not SpeechToText.process_frame:
            return None  # subclass override must run, not be bypassed
        if self.mesh is not None:
            return None  # meshed inputs need host-side placement
        self._ensure_ready()
        max_tokens = int(self.get_parameter("max_tokens", 32, stream))

        def build():
            from ..models.asr import transcribe_audio
            config = self.config

            def kernel(state, audio):
                audio = jnp.asarray(audio, jnp.float32)
                return {"tokens": transcribe_audio(
                    state, config, audio, max_tokens=max_tokens)}

            return kernel

        return self._cached_group_kernel(max_tokens, build), self.state

    def eval_kernel(self):
        """Static-analyzer hook (PipelineElement.eval_kernel): log-mel
        frontend + transcription as a pure kernel, setup() as the state
        builder; jax.eval_shape proves the `tokens` contract without
        building the ASR params."""
        if type(self).process_frame is not SpeechToText.process_frame:
            return None
        self.configure()
        max_tokens = int(self.get_parameter("max_tokens", 32))
        config = self.config
        from ..models.asr import transcribe_audio

        def kernel(state, audio):
            audio = jnp.asarray(audio, jnp.float32)
            if audio.ndim == 1:  # unbatched source, as in process_frame
                audio = audio[None]
            return {"tokens": transcribe_audio(
                state, config, audio, max_tokens=max_tokens)}

        return kernel, self.setup


class TextToSpeech(ComputeElement):
    """text -> waveform (B, samples) f32 + sample_rate: the reference's
    Coqui TTS seat (reference speech_elements.py:109-146, Coqui vits on
    CUDA).  Characters -> mel -> Griffin-Lim runs as ONE jit on the
    element's mesh (models/tts.py).  Prompt lengths pad to power-of-two
    buckets so repeated frames share a compilation; "max_chars"
    (default 512) caps the ladder, warning on truncation."""

    def configure(self):
        if hasattr(self, "config"):
            return
        from ..models.tts import TTSConfig
        self.config = TTSConfig(
            d_model=int(self.get_parameter("d_model", 256)),
            n_conv_layers=int(self.get_parameter("n_conv_layers", 4)),
            sample_rate=int(self.get_parameter("sample_rate", 16000)),
            frames_per_char=int(
                self.get_parameter("frames_per_char", 6)),
            griffin_lim_iters=int(
                self.get_parameter("griffin_lim_iters", 30)),
        )

    def setup(self):
        from ..models.tts import init_tts_params
        weights = self.get_parameter("weights")
        if weights:
            params = load_pytree(weights, dtype=self.config.dtype)
        else:
            params = init_tts_params(
                self.config,
                jax.random.PRNGKey(int(self.get_parameter("seed", 0))))
        _LOGGER.info("%s: TTS %.1fM params", self.definition.name,
                     count_params(params) / 1e6)
        return params

    def process_frame(self, stream, text):
        from ..models.tts import encode_chars, synthesize
        from ..utils.padding import bucket_length
        self._ensure_ready()
        prompts = [text] if isinstance(text, str) else list(text)
        max_chars = int(self.get_parameter("max_chars", 512, stream))
        longest = max((len(prompt.encode("utf-8", "replace"))
                       for prompt in prompts), default=1)
        if longest > max_chars:
            _LOGGER.warning(
                "%s: prompt of %d chars truncated to max_chars=%d",
                self.definition.name, longest, max_chars)
        width = bucket_length(min(longest, max_chars), minimum=16)
        chars = np.concatenate(
            [encode_chars(prompt, max_len=width)
             for prompt in prompts])
        waveform = synthesize(self.state, self.config,
                              jnp.asarray(chars))
        return StreamEvent.OKAY, {
            "audio": waveform, "sample_rate": self.config.sample_rate}


class DetectionsPublish(AsyncHostElement):
    """detections (the Detector contract) -> "(detections (names...))" on
    the "{namespace}/detections" side-channel, closing the vision->LLM
    loop (reference: the YOLO element publishes and the LLM element
    injects, elements_llm.py:196-210).  Class ids map through the
    "class_names" parameter when given.  Runs as an async host element:
    the device->host readback of the valid mask happens off the event
    loop.  Detections pass through unchanged for downstream stages."""

    def process_async(self, stream, detections):
        from ..utils import generate
        classes = np.asarray(detections["classes"])
        valid = np.asarray(detections["valid"])
        class_names = self.get_parameter("class_names", None, stream)
        names = []
        for row_classes, row_valid in zip(classes, valid):
            for class_id, ok in zip(row_classes, row_valid):
                if not ok:
                    continue
                names.append(str(class_names[int(class_id)])
                             if class_names
                             and int(class_id) < len(class_names)
                             else str(int(class_id)))
        topic = str(self.get_parameter(
            "topic", f"{self.process.namespace}/detections", stream))
        # dedupe, keep first-seen order (reference publishes object names)
        unique = list(dict.fromkeys(names))
        self.process.publish(topic, generate("detections", [unique]))
        return {"detections": detections}


class TokensToText(AsyncHostElement):
    """tokens (B, T) -> text list[str] (explicit host boundary: this is
    where token ids leave the device).  With a "tokenizer" parameter
    ("default" or a path) decoding uses the real BPE vocabulary; without
    one, the byte-level toy vocabulary.

    Runs as an ASYNC host element: the device->host readback (a fixed
    ~100 ms round-trip on tunneled TPUs) happens on a worker thread with
    the frame parked, so it never serializes the pipeline."""

    def process_async(self, stream, tokens):
        token_array = np.asarray(tokens)
        tokenizer = _tokenizer_for(self)
        texts = []
        for row in token_array:
            if tokenizer is not None:
                texts.append(tokenizer.decode(row))
            else:
                data = bytes(int(t) - _BYTE_OFFSET for t in row
                             if _BYTE_OFFSET <= t < _BYTE_OFFSET + 256)
                texts.append(data.decode("utf-8", errors="replace"))
        return {"text": texts}


class TextToTokens(PipelineElement):
    """text (str | list[str]) -> token ids (B, T) int32, left-padded.

    The host->device tokenization boundary feeding LMForward/LMGenerate;
    "tokenizer" parameter as in TokensToText (defaults to the committed
    BPE asset)."""

    def process_frame(self, stream, text):
        tokenizer = _tokenizer_for(self) or BPETokenizer.default()
        prompts = [text] if isinstance(text, str) else list(text)
        bos = bool(self.get_parameter("bos", True, stream))
        encoded = [tokenizer.encode(p, bos=bos) for p in prompts]
        max_len = self.get_parameter("max_len", None, stream)
        width = max(len(ids) for ids in encoded) if encoded else 1
        if max_len:
            width = int(max_len)
            encoded = [ids[-width:] for ids in encoded]
        pad = tokenizer.pad_id or 0
        tokens = np.full((len(encoded), max(width, 1)), pad, np.int32)
        for row, ids in enumerate(encoded):
            tokens[row, tokens.shape[1] - len(ids):] = ids
        return StreamEvent.OKAY, {"tokens": tokens}


class Detector(ComputeElement):
    """image (B, 3, H, W) [0,1] -> fixed-size detections + the reference
    overlay contract (reference yolo.py:56-87 emits {"objects": [...],
    "rectangles": [...]}) -- detections stay on device; the overlay dict is
    produced lazily by ImageOverlay/host sinks."""

    def configure(self) -> None:
        """Idempotent config construction (ComputeElement.configure hook:
        runs before BOTH first-frame setup and checkpoint restore).
        Probes the weights container:
        ultralytics YOLOv8 naming selects the REAL v8 architecture
        (models/yolo.py, BN folded), matching the reference's
        pretrained-YOLO capability (yolo.py:51-54)."""
        if hasattr(self, "config"):
            return
        self._yolo = False
        weights = self.get_parameter("weights")
        if weights:
            probe = _probe_weight_names(weights)
            self._yolo = ("model.0.conv.weight" in probe
                          or "model.model.0.conv.weight" in probe)
            probe.close()
        if self._yolo:
            from ..models import YOLO_VARIANTS, infer_yolov8_config
            overrides = dict(
                image_size=int(self.get_parameter("image_size", 640)),
                max_detections=int(
                    self.get_parameter("max_detections", 300)),
                score_threshold=float(
                    self.get_parameter("score_threshold", 0.25)),
                dtype=str(self.get_parameter("dtype", "bfloat16")))
            variant = str(self.get_parameter("yolo_variant", "auto"))
            if variant == "auto":
                # architecture read off the checkpoint's own shapes:
                # any v8 family member (or custom width) loads unnamed
                self.config = infer_yolov8_config(weights, **overrides)
            elif variant in YOLO_VARIANTS:
                self.config = replace(
                    YOLO_VARIANTS[variant],
                    n_classes=int(self.get_parameter("n_classes", 80)),
                    **overrides)
            else:
                raise ValueError(
                    f"unknown yolo_variant {variant!r}; "
                    f"'auto' or one of {sorted(YOLO_VARIANTS)}")
            return
        preset = self.get_parameter("preset")
        if preset:
            self.config = _DETECTOR_PRESETS[str(preset)]
            dtype = self.get_parameter("dtype")
            if dtype:
                self.config = replace(self.config, dtype=str(dtype))
        else:
            self.config = DetectorConfig(
                n_classes=int(self.get_parameter("n_classes", 16)),
                base_channels=int(self.get_parameter("base_channels", 32)),
                image_size=int(self.get_parameter("image_size", 256)),
                max_detections=int(
                    self.get_parameter("max_detections", 32)),
                score_threshold=float(
                    self.get_parameter("score_threshold", 0.25)),
                dtype=str(self.get_parameter("dtype", "bfloat16")),
            )

    def setup(self):
        weights = self.get_parameter("weights")
        if self._yolo:
            from ..models import load_yolov8_params
            params = load_yolov8_params(weights, self.config)
            _LOGGER.info("%s: yolov8 %.1fM params (BN folded)",
                         self.definition.name, count_params(params) / 1e6)
            return params
        if weights:
            params = load_pytree(weights, dtype=self.config.dtype)
        else:
            params = init_detector_params(
                self.config,
                jax.random.PRNGKey(int(self.get_parameter("seed", 0))))
        _LOGGER.info("%s: detector %.1fM params", self.definition.name,
                     count_params(params) / 1e6)
        return params

    def process_frame(self, stream, image):
        self._ensure_ready()  # configure() runs inside
        image = _as_device_array(image, jnp.float32)
        if image.ndim == 3:
            image = image[None]
        if self._yolo:
            from ..models import yolo_detect
            detections = yolo_detect(self.state, self.config, image)
        else:
            detections = detect(self.state, self.config, image)
        return StreamEvent.OKAY, {"detections": detections}

    def group_kernel(self, stream):
        """Fused micro-batch hook: detection as a pure batch kernel, so
        the scheduler runs concat+detect+split as ONE program (the
        round-5 standalone probe: 1 642 frames/s fused vs 1 403 for the
        three-dispatch chain on this serving path)."""
        if type(self).process_frame is not Detector.process_frame:
            return None  # subclass override must run, not be bypassed
        if self.mesh is not None:
            return None  # meshed inputs need host-side placement
        self._ensure_ready()
        if self._group_kernel_fn is None:
            if self._yolo:
                from ..models import yolo_detect as detect_fn
            else:
                detect_fn = detect
            config = self.config

            def kernel(state, image):
                image = jnp.asarray(image, jnp.float32)
                return {"detections": detect_fn(state, config, image)}

            self._group_kernel_fn = kernel
        return self._group_kernel_fn, self.state

    def eval_kernel(self):
        """Static-analyzer hook (PipelineElement.eval_kernel): the
        detection kernel with setup() as the state builder, so
        jax.eval_shape proves the detections contract without building
        detector params."""
        if type(self).process_frame is not Detector.process_frame:
            return None
        self.configure()
        if self._yolo:
            from ..models import yolo_detect as detect_fn
        else:
            detect_fn = detect
        config = self.config

        def kernel(state, image):
            image = jnp.asarray(image, jnp.float32)
            if image.ndim == 3:  # unbatched source, as in process_frame
                image = image[None]
            return {"detections": detect_fn(state, config, image)}

        return kernel, self.setup
