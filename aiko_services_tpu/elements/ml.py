# ML pipeline elements backed by the in-framework model families
# (models/), replacing the reference's external-runtime elements:
# PE_WhisperX (reference: src/aiko_services/examples/speech/
# speech_elements.py:229-262), PE_LLM (examples/llm/elements_llm.py:137),
# YoloDetector (examples/yolo/yolo.py:51-87).  Those shell out to
# torch/CUDA processes; these run jit-compiled JAX on the element's mesh
# with HBM-resident tensors between stages.

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..models import (
    AsrConfig, DetectorConfig, TransformerConfig, count_params, detect,
    forward, generate, init_asr_params, init_detector_params, init_params,
    transcribe)
from ..ops import log_mel_spectrogram
from ..pipeline import ComputeElement, PipelineElement, StreamEvent
from ..utils import get_logger

__all__ = ["LMForward", "LMGenerate", "SpeechToText", "Detector",
           "TokensToText"]

_LOGGER = get_logger("ml_elements")


def _transformer_config(element) -> TransformerConfig:
    return TransformerConfig(
        vocab_size=int(element.get_parameter("vocab_size", 8192)),
        d_model=int(element.get_parameter("d_model", 512)),
        n_layers=int(element.get_parameter("n_layers", 8)),
        n_heads=int(element.get_parameter("n_heads", 8)),
        n_kv_heads=int(element.get_parameter("n_kv_heads", 4)),
        d_ff=int(element.get_parameter("d_ff", 1536)),
        max_seq_len=int(element.get_parameter("max_seq_len", 2048)),
        dtype=str(element.get_parameter("dtype", "bfloat16")),
    )


class LMForward(ComputeElement):
    """tokens (B, L) -> logits (B, L, V) + per-sequence mean NLL.

    The scoring workhorse: one full causal forward through the flagship
    transformer on the element's mesh.
    """

    def setup(self):
        self.config = _transformer_config(self)
        params = init_params(
            self.config,
            jax.random.PRNGKey(int(self.get_parameter("seed", 0))))
        _LOGGER.info("%s: transformer %.1fM params",
                     self.definition.name, count_params(params) / 1e6)
        return params

    def compute(self, state, tokens):
        logits = forward(state, self.config, tokens)
        log_probs = jax.nn.log_softmax(logits[:, :-1], axis=-1)
        taken = jnp.take_along_axis(
            log_probs, tokens[:, 1:, None], axis=-1)[..., 0]
        return {"logits": logits, "nll": -jnp.mean(taken, axis=-1)}


class LMGenerate(ComputeElement):
    """tokens (B, L) prompt -> generated (B, max_new_tokens) greedy tokens.

    Owns its KV cache; generation runs as one jit (prefill + fori_loop
    decode), so the pipeline mailbox only sees whole completions.
    """

    def setup(self):
        self.config = _transformer_config(self)
        return init_params(
            self.config,
            jax.random.PRNGKey(int(self.get_parameter("seed", 0))))

    def process_frame(self, stream, tokens):
        self._ensure_ready()
        max_new = int(self.get_parameter("max_new_tokens", 32, stream))
        tokens = jnp.asarray(np.asarray(tokens), jnp.int32)
        out, _ = generate(self.state, self.config, tokens, max_new)
        return StreamEvent.OKAY, {"generated": out}

    def compute(self, state, **inputs):  # pragma: no cover
        raise NotImplementedError("LMGenerate overrides process_frame")


# byte-level toy vocabulary shared by SpeechToText and TokensToText:
# 0=pad 1=sot 2=eot, 3..258 = bytes
_BYTE_OFFSET = 3


class SpeechToText(ComputeElement):
    """audio (B, samples) 16 kHz f32 -> token ids (B, max_tokens).

    The reference's PE_WhisperX seat (reference speech_elements.py:229-262:
    5 s windows through WhisperX/CUDA); here the log-mel frontend and the
    encoder-decoder transformer run as ONE jit on the element's mesh.
    """

    def setup(self):
        self.config = AsrConfig(
            d_model=int(self.get_parameter("d_model", 384)),
            enc_layers=int(self.get_parameter("enc_layers", 4)),
            dec_layers=int(self.get_parameter("dec_layers", 4)),
            n_heads=int(self.get_parameter("n_heads", 6)),
            vocab_size=int(self.get_parameter("vocab_size", 1024)),
            max_frames=int(self.get_parameter("max_frames", 1500)),
            dtype=str(self.get_parameter("dtype", "bfloat16")),
        )
        params = init_asr_params(
            self.config,
            jax.random.PRNGKey(int(self.get_parameter("seed", 0))))
        _LOGGER.info("%s: ASR %.1fM params", self.definition.name,
                     count_params(params) / 1e6)
        return params

    def process_frame(self, stream, audio):
        self._ensure_ready()
        audio = jnp.asarray(np.asarray(audio), jnp.float32)
        if audio.ndim == 1:
            audio = audio[None]
        max_tokens = int(self.get_parameter("max_tokens", 32, stream))
        mel = log_mel_spectrogram(audio)
        tokens = transcribe(self.state, self.config, mel,
                            max_tokens=max_tokens)
        return StreamEvent.OKAY, {"tokens": tokens}


class TokensToText(PipelineElement):
    """tokens (B, T) -> text list[str] via the byte-level toy vocabulary
    (explicit host boundary: this is where token ids leave the device)."""

    def process_frame(self, stream, tokens):
        token_array = np.asarray(tokens)
        texts = []
        for row in token_array:
            data = bytes(int(t) - _BYTE_OFFSET for t in row
                         if _BYTE_OFFSET <= t < _BYTE_OFFSET + 256)
            texts.append(data.decode("utf-8", errors="replace"))
        return StreamEvent.OKAY, {"text": texts}


class Detector(ComputeElement):
    """image (B, 3, H, W) [0,1] -> fixed-size detections + the reference
    overlay contract (reference yolo.py:56-87 emits {"objects": [...],
    "rectangles": [...]}) -- detections stay on device; the overlay dict is
    produced lazily by ImageOverlay/host sinks."""

    def setup(self):
        self.config = DetectorConfig(
            n_classes=int(self.get_parameter("n_classes", 16)),
            base_channels=int(self.get_parameter("base_channels", 32)),
            image_size=int(self.get_parameter("image_size", 256)),
            max_detections=int(self.get_parameter("max_detections", 32)),
            score_threshold=float(
                self.get_parameter("score_threshold", 0.25)),
            dtype=str(self.get_parameter("dtype", "bfloat16")),
        )
        params = init_detector_params(
            self.config,
            jax.random.PRNGKey(int(self.get_parameter("seed", 0))))
        _LOGGER.info("%s: detector %.1fM params", self.definition.name,
                     count_params(params) / 1e6)
        return params

    def process_frame(self, stream, image):
        self._ensure_ready()
        image = jnp.asarray(np.asarray(image), jnp.float32)
        if image.ndim == 3:
            image = image[None]
        detections = detect(self.state, self.config, image)
        return StreamEvent.OKAY, {"detections": detections}
