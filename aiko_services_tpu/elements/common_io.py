# DataSource / DataTarget base elements.
#
# Capability parity with the reference media I/O bases (reference:
# src/aiko_services/elements/media/common_io.py:22-151): a DataSource turns a
# "data_sources" parameter (file path(s), glob patterns, or in-memory items)
# into a stream of frames -- single item goes through the no-thread fast path
# (create_frame), multiple items run on a frame-generator thread with
# optional rate throttling and batching; a DataTarget consumes frames into
# "data_targets" (templated file paths).

from __future__ import annotations

import glob as globlib
from pathlib import Path

from ..pipeline import PipelineElement, StreamEvent

__all__ = ["DataSource", "DataTarget", "Sample", "expand_data_sources"]


def expand_data_sources(data_sources) -> list:
    """Expand path patterns: "file://path" prefixes, globs, lists."""
    if data_sources is None:
        return []
    if isinstance(data_sources, (str, Path)):
        data_sources = [data_sources]
    expanded = []
    for source in data_sources:
        if not isinstance(source, str):
            expanded.append(source)
            continue
        path = source[len("file://"):] if source.startswith("file://") else (
            source)
        if any(character in path for character in "*?["):
            expanded.extend(sorted(globlib.glob(path)))
        else:
            expanded.append(path)
    return expanded


class Sample(PipelineElement):
    """Pass every sample_rate-th frame, DROP_FRAME otherwise -- the
    drop-frame test pattern, name-agnostic over its input ports
    (reference: text_io.py:108-115; Text/Audio/VideoSample are aliases)."""

    def process_frame(self, stream, **inputs):
        sample_rate = int(self.get_parameter("sample_rate", 1, stream))
        counter_key = f"{self.definition.name}.counter"
        counter = stream.variables.get(counter_key, 0)
        stream.variables[counter_key] = counter + 1
        if sample_rate > 1 and counter % sample_rate != 0:
            return StreamEvent.DROP_FRAME, {}
        return StreamEvent.OKAY, inputs


class DataSource(PipelineElement):
    """Subclasses implement read_item(stream, item) -> frame_data dict.

    Parameters (all stream-overridable):
      data_sources     items / paths / globs
      rate             frames per second throttle
      count            total frames to emit, cycling items (default: one
                       pass over the items)
      data_batch_size  stack N read_item results per frame (reference
                       common_io.py data_batch_size); ndarray values get a
                       leading batch axis
      timestamps       add "t0" (time.time()) to every frame -- declare a
                       "t0" output port to propagate it (latency probes)
    """

    def emission_index(self, stream) -> int:
        """Monotonic per-stream emission counter.  Use this (not
        stream.frame_id) to seed synthetic sources: frame_id only advances
        when the pipeline mailbox drains, so a fast generator would reuse
        the same value across in-flight frames."""
        key = f"{self.definition.name}.emitted"
        index = stream.variables.get(key, 0)
        stream.variables[key] = index + 1
        return index

    # path-like sources expand "file://" prefixes and glob patterns;
    # literal-content sources (TextSource: prompts may contain ? or *)
    # override with False
    expand_sources = True

    def start_stream(self, stream, stream_id):
        data_sources = self.get_parameter("data_sources", None, stream)
        if self.expand_sources:
            items = expand_data_sources(data_sources)
        elif data_sources is None:
            items = []
        elif isinstance(data_sources, (str, Path)):
            items = [data_sources]
        else:
            items = list(data_sources)
        if not items:
            return StreamEvent.ERROR, {"diagnostic": "no data_sources"}
        rate = self.get_parameter("rate", None, stream)
        rate = float(rate) if rate else None
        count = self.get_parameter("count", None, stream)
        batch = int(self.get_parameter("data_batch_size", 1, stream))
        name = self.definition.name
        stream.variables[f"{name}.items"] = items
        stream.variables[f"{name}.remaining"] = (
            int(count) if count is not None
            else max(1, len(items) // max(batch, 1)))
        if (len(items) == 1 and rate is None and batch == 1
                and count is None):
            # fast path: single item, no generator thread
            # (reference common_io.py:96-102)
            try:
                frame_data = self._read_frame(stream)
            except Exception as error:
                return StreamEvent.ERROR, {"diagnostic": str(error)}
            self.create_frame(stream, frame_data)
            return StreamEvent.OKAY, None
        self.create_frames(stream, self._frame_generator, rate=rate)
        return StreamEvent.OKAY, None

    def _read_frame(self, stream) -> dict:
        """One frame's data: `data_batch_size` read_item()s stacked."""
        import time

        import numpy as np

        name = self.definition.name
        items = stream.variables[f"{name}.items"]
        batch = int(self.get_parameter("data_batch_size", 1, stream))
        cursor_key = f"{name}.cursor"
        batch_items = []
        for _ in range(max(batch, 1)):
            cursor = stream.variables.get(cursor_key, 0)
            stream.variables[cursor_key] = cursor + 1
            batch_items.append(items[cursor % len(items)])
        if batch > 1:
            # one fused call for the whole row batch when the source
            # supports it (on tunneled devices per-row synthesis pays
            # per-dispatch latency ~2-10 ms EACH; a batched source is
            # one launch per frame)
            batched = self.read_batch(stream, batch_items)
            if batched is not None:
                if self.get_parameter("timestamps", False, stream):
                    batched["t0"] = time.time()
                return batched
        parts = [self.read_item(stream, item) for item in batch_items]
        if batch <= 1:
            frame_data = parts[0]
        else:
            frame_data = {}
            for key in parts[0]:
                values = [part[key] for part in parts]
                if isinstance(values[0], np.ndarray):
                    frame_data[key] = np.stack(values)
                else:
                    try:  # device arrays stack ON DEVICE (jnp.stack) --
                        # never a host round-trip for on_device sources
                        import jax
                        import jax.numpy as jnp
                        if isinstance(values[0], jax.Array):
                            frame_data[key] = jnp.stack(values)
                        else:
                            frame_data[key] = values
                    except ImportError:  # pragma: no cover
                        frame_data[key] = values
        if self.get_parameter("timestamps", False, stream):
            frame_data["t0"] = time.time()
        return frame_data

    def _frame_generator(self, stream, frame_id):
        name = self.definition.name
        remaining_key = f"{name}.remaining"
        remaining = stream.variables.get(remaining_key, 0)
        if remaining <= 0:
            return StreamEvent.STOP, {"diagnostic": "data sources exhausted"}
        stream.variables[remaining_key] = remaining - 1
        return StreamEvent.OKAY, self._read_frame(stream)

    def read_item(self, stream, item) -> dict:
        raise NotImplementedError

    def read_batch(self, stream, items) -> dict | None:
        """Optional whole-batch read: return {key: (B, ...) stacked} for
        `items`, or None to fall back to per-item read_item() + stack.
        Sources that can synthesize/load a batch in one device program
        should implement this (dispatch-latency economy)."""
        return None

    def process_frame(self, stream, **inputs):
        # sources inject frames; a frame passing through is forwarded as-is
        return StreamEvent.OKAY, inputs


class DataTarget(PipelineElement):
    """Subclasses implement write_item(stream, path, **inputs)."""

    def start_stream(self, stream, stream_id):
        data_targets = self.get_parameter("data_targets", None, stream)
        targets = expand_data_sources(data_targets)
        if not targets:
            return StreamEvent.ERROR, {"diagnostic": "no data_targets"}
        stream.variables[f"{self.definition.name}.target"] = targets[0]
        stream.variables[f"{self.definition.name}.count"] = 0
        return StreamEvent.OKAY, None

    def next_target_path(self, stream) -> str:
        """Template "{}" in the target expands to the write counter."""
        template = stream.variables[f"{self.definition.name}.target"]
        count_key = f"{self.definition.name}.count"
        count = stream.variables[count_key]
        stream.variables[count_key] = count + 1
        return (template.format(count) if "{" in str(template)
                else str(template))
