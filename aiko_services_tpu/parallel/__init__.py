from .mesh import (                                           # noqa: F401
    MESH_AXIS_ORDER, create_mesh, get_mesh, named_sharding, partition_spec,
    shard_pytree, filter_specs)
from .attention import (                                      # noqa: F401
    attention_reference, flash_attention, ring_attention,
    ring_attention_sharded, sp_decode_attention,
    sp_decode_attention_sharded, ulysses_attention,
    ulysses_attention_sharded)
from .distributed import (                                    # noqa: F401
    global_mesh, initialize_distributed, is_distributed, process_count,
    process_index, shutdown_distributed)
