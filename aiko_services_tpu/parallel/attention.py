# Attention kernels and sequence parallelism.
#
# The reference has NO sequence parallelism -- long audio is handled by
# temporal chunking (reference: src/aiko_services/examples/speech/
# speech_elements.py:54-83) and LLM context is a single prompt.  This module
# supplies the real thing for TPU (SURVEY.md 2.4, 5):
#
#   flash_attention  -- blockwise online-softmax attention as a Pallas TPU
#                       kernel (MXU matmuls, VMEM-resident blocks, f32
#                       accumulation); interpreter mode on CPU for tests.
#   ring_attention   -- sequence-parallel attention: Q stays put, KV blocks
#                       rotate around the mesh "seq" axis via ppermute; each
#                       hop overlaps with blockwise attention compute and
#                       merges via the associative online-softmax update.
#   ulysses_attention - all-to-all alternative: swap seq-sharding for
#                       head-sharding, run dense local attention, swap back.
#
# All take q/k/v shaped (batch, heads, seq, head_dim).

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams; support both so the
# kernels trace on either runtime (the tunneled TPU toolchain and the
# CPU test environment may pin different jax versions)
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams")
from jax.sharding import PartitionSpec as P

from ..utils.padding import pad_axis_to
from .mesh import create_mesh  # noqa: F401  (re-exported convenience)

__all__ = [
    "attention_reference", "flash_attention", "ring_attention",
    "sp_decode_attention", "ulysses_attention",
]

_NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def attention_reference(q, k, v, causal: bool = False, sm_scale=None,
                        q_offset: int = 0):
    """Plain-XLA softmax attention: the correctness oracle for the kernels
    and the backward pass of the custom-VJP flash kernel."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k,
        preferred_element_type=jnp.float32) * sm_scale
    if causal:
        q_len, k_len = logits.shape[-2], logits.shape[-1]
        q_pos = jnp.arange(q_len)[:, None] + (k_len - q_len) + q_offset
        k_pos = jnp.arange(k_len)[None, :]
        logits = jnp.where(k_pos <= q_pos, logits, _NEG_INF)
    weights = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", weights.astype(v.dtype), v)


# -- Pallas flash attention -------------------------------------------------

_STAT_LANES = 128  # min f32 lane width for the m/l scratch tiles


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref,
                  acc_ref, *,
                  causal: bool, sm_scale: float, kv_len: int, q_offset: int):
    """One (batch*head, q_block, k_block) grid step of the online-softmax
    recurrence.  K/V stream through VMEM one block per step (HBM->VMEM via
    the grid pipeline -- whole-sequence K/V never resides on chip), with
    m/l/acc scratch persisting across the sequential k dimension."""
    block_q = q_ref.shape[1]
    block_k = k_ref.shape[1]
    # program ids must be read OUTSIDE pl.when bodies (interpret-mode
    # lowering of program_id inside cond is unsupported)
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    num_kb = pl.num_programs(2)
    q_base = qi * block_q + q_offset
    q_pos = (q_base + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0))
    k_pos = (ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1))

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    needed = ki * block_k < kv_len
    if causal:  # skip blocks entirely above the causal diagonal
        needed = jnp.logical_and(
            needed, ki * block_k <= q_base + block_q - 1)

    @pl.when(needed)
    def _update():
        q = q_ref[0].astype(jnp.float32) * sm_scale    # (block_q, d)
        k_blk = k_ref[0].astype(jnp.float32)           # (block_k, d)
        v_blk = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # (block_q, block_k)
        mask = k_pos < kv_len
        if causal:
            mask = jnp.logical_and(mask, k_pos <= q_pos)
        s = jnp.where(mask, s, _NEG_INF)
        m_prev = m_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.broadcast_to(
            jnp.sum(p, axis=-1, keepdims=True), l_ref.shape)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(ki == num_kb - 1)
    def _finish():
        o_ref[0] = (acc_ref[:] / jnp.maximum(
            l_ref[:, :1], 1e-30)).astype(o_ref.dtype)
        # per-row logsumexp: the only forward residual the backward
        # kernels need beyond q/k/v/o
        lse_ref[0] = m_ref[:] + jnp.log(jnp.maximum(l_ref[:], 1e-30))


def _pad_seq(x, block: int):
    length = x.shape[2]
    padded = ((length + block - 1) // block) * block
    return pad_axis_to(x, 2, padded)


def flash_attention(q, k, v, causal: bool = False, sm_scale=None,
                    block_q: int = 128, block_k: int = 128,
                    q_offset: int = 0):
    """Blockwise attention, (B, H, L, D) in and out.

    q_offset shifts the causal mask for callers whose q shard starts at a
    nonzero global position (ring attention resumes, KV-cached decode).

    Differentiable end-to-end in Pallas: the forward kernel saves the
    per-row logsumexp, and the backward pass runs two blockwise kernels
    (dq; dk/dv) that recompute p inside VMEM -- backward peak memory is
    O(L x block), never O(L^2).
    """
    batch, heads, q_len, head_dim = q.shape
    kv_len = k.shape[2]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(head_dim)
    block_q = min(block_q, max(q_len, 1))
    block_k = min(block_k, max(kv_len, 1))
    return _flash(q, k, v, bool(causal), float(sm_scale), int(block_q),
                  int(block_k), int(q_offset))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, sm_scale, block_q, block_k, q_offset):
    out, _ = _flash_impl(q, k, v, causal, sm_scale, block_q, block_k,
                         q_offset)
    return out


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, q_offset):
    out, lse = _flash_impl(q, k, v, causal, sm_scale, block_q, block_k,
                           q_offset)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, sm_scale, block_q, block_k, q_offset, residuals,
               cotangent):
    q, k, v, out, lse = residuals
    return _flash_bwd_impl(q, k, v, out, lse, cotangent, causal, sm_scale,
                           block_q, block_k, q_offset)


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "sm_scale", "block_q", "block_k", "q_offset"))
def _flash_impl(q, k, v, causal, sm_scale, block_q, block_k, q_offset):
    batch, heads, q_len, head_dim = q.shape
    kv_len = k.shape[2]

    q_padded = _pad_seq(q, block_q).reshape(
        batch * heads, -1, head_dim)
    k_padded = _pad_seq(k, block_k).reshape(
        batch * heads, -1, head_dim)
    v_padded = _pad_seq(v, block_k).reshape(
        batch * heads, -1, head_dim)
    padded_q_len = q_padded.shape[1]
    # k blocks stream through the grid's sequential minor dimension, so
    # VMEM holds one (block_q, d) q tile + one (block_k, d) k/v tile each
    # step regardless of sequence length
    grid = (batch * heads, padded_q_len // block_q,
            k_padded.shape[1] // block_k)

    kernel = functools.partial(
        _flash_kernel,
        causal=causal, sm_scale=float(sm_scale), kv_len=kv_len,
        q_offset=int(q_offset) + (kv_len - q_len if causal else 0))
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, head_dim),
                         lambda bh, qi, ki: (bh, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, head_dim),
                         lambda bh, qi, ki: (bh, ki, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, head_dim),
                         lambda bh, qi, ki: (bh, ki, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec(
                (1, block_q, head_dim), lambda bh, qi, ki: (bh, qi, 0),
                memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (1, block_q, _STAT_LANES), lambda bh, qi, ki: (bh, qi, 0),
                memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(
                (batch * heads, padded_q_len, head_dim), q.dtype),
            jax.ShapeDtypeStruct(
                (batch * heads, padded_q_len, _STAT_LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _STAT_LANES), jnp.float32),   # m
            pltpu.VMEM((block_q, _STAT_LANES), jnp.float32),   # l
            pltpu.VMEM((block_q, head_dim), jnp.float32),      # acc
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(q_padded, k_padded, v_padded)
    out = out.reshape(batch, heads, padded_q_len, head_dim)[:, :, :q_len]
    lse = lse.reshape(batch, heads, padded_q_len, _STAT_LANES)[:, :, :q_len,
                                                               0]
    return out, lse


# -- Pallas flash attention backward ----------------------------------------
#
# FlashAttention-2-style: p is recomputed blockwise inside VMEM from the
# saved logsumexp; dq accumulates over the sequential k dimension, dk/dv
# over the sequential q dimension.  delta = rowsum(dO * O) is a cheap
# O(L*D) XLA pass.

def _flash_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     dq_ref, dq_acc_ref, *,
                     causal: bool, sm_scale: float, kv_len: int,
                     q_offset: int):
    block_q = q_ref.shape[1]
    block_k = k_ref.shape[1]
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    num_kb = pl.num_programs(2)
    q_base = qi * block_q + q_offset
    q_pos = (q_base + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0))
    k_pos = (ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1))

    @pl.when(ki == 0)
    def _init():
        dq_acc_ref[:] = jnp.zeros_like(dq_acc_ref)

    needed = ki * block_k < kv_len
    if causal:
        needed = jnp.logical_and(
            needed, ki * block_k <= q_base + block_q - 1)

    @pl.when(needed)
    def _update():
        q = q_ref[0].astype(jnp.float32)
        k_blk = k_ref[0].astype(jnp.float32)
        v_blk = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q * sm_scale, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        mask = k_pos < kv_len
        if causal:
            mask = jnp.logical_and(mask, k_pos <= q_pos)
        p = jnp.where(mask, jnp.exp(s - lse_ref[0][:, :1]), 0.0)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0][:, :1])
        dq_acc_ref[:] += jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale

    @pl.when(ki == num_kb - 1)
    def _finish():
        dq_ref[0] = dq_acc_ref[:].astype(dq_ref.dtype)


def _flash_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dk_ref, dv_ref, dk_acc_ref, dv_acc_ref, *,
                      causal: bool, sm_scale: float, kv_len: int,
                      q_len: int, q_offset: int):
    block_k = k_ref.shape[1]
    block_q = q_ref.shape[1]
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    num_qb = pl.num_programs(2)
    q_base = qi * block_q + q_offset
    # transposed layout: rows are k positions, columns q positions
    k_pos = (ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_k, block_q), 0))
    q_pos = (q_base + jax.lax.broadcasted_iota(
        jnp.int32, (block_k, block_q), 1))

    @pl.when(qi == 0)
    def _init():
        dk_acc_ref[:] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[:] = jnp.zeros_like(dv_acc_ref)

    needed = qi * block_q < q_len
    if causal:
        # skip q blocks entirely ABOVE this k block's causal reach
        needed = jnp.logical_and(
            needed, q_base + block_q - 1 >= ki * block_k)

    @pl.when(needed)
    def _update():
        q = q_ref[0].astype(jnp.float32)
        k_blk = k_ref[0].astype(jnp.float32)
        v_blk = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s_t = jax.lax.dot_general(
            k_blk, q * sm_scale, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)   # (block_k, block_q)
        mask = k_pos < kv_len
        if causal:
            mask = jnp.logical_and(mask, k_pos <= q_pos)
        lse_row = lse_ref[0][:, 0]                # (block_q,)
        p_t = jnp.where(mask, jnp.exp(s_t - lse_row[None, :]), 0.0)
        dv_acc_ref[:] += jax.lax.dot_general(
            p_t, do, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp_t = jax.lax.dot_general(
            v_blk, do, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)   # (block_k, block_q)
        delta_row = delta_ref[0][:, 0]
        ds_t = p_t * (dp_t - delta_row[None, :])
        dk_acc_ref[:] += jax.lax.dot_general(
            ds_t, q, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale

    @pl.when(qi == num_qb - 1)
    def _finish():
        dk_ref[0] = dk_acc_ref[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[:].astype(dv_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "sm_scale", "block_q", "block_k", "q_offset"))
def _flash_bwd_impl(q, k, v, out, lse, dout, causal, sm_scale, block_q,
                    block_k, q_offset):
    batch, heads, q_len, head_dim = q.shape
    kv_len = k.shape[2]
    effective_offset = int(q_offset) + (kv_len - q_len if causal else 0)

    delta = jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)                               # (B, H, Lq)

    q_p = _pad_seq(q, block_q).reshape(batch * heads, -1, head_dim)
    do_p = _pad_seq(dout, block_q).reshape(batch * heads, -1, head_dim)
    k_p = _pad_seq(k, block_k).reshape(batch * heads, -1, head_dim)
    v_p = _pad_seq(v, block_k).reshape(batch * heads, -1, head_dim)
    padded_q_len = q_p.shape[1]
    padded_kv_len = k_p.shape[1]

    def lanes(x, block):  # (B, H, L) -> (B*H, padded L, _STAT_LANES)
        x = pad_axis_to(x[..., None], 2,
                        ((x.shape[2] + block - 1) // block) * block)
        return jnp.broadcast_to(
            x.reshape(batch * heads, -1, 1),
            (batch * heads, x.shape[2], _STAT_LANES))

    lse_p = lanes(lse, block_q)
    delta_p = lanes(delta, block_q)

    q_spec = pl.BlockSpec((1, block_q, head_dim),
                          lambda bh, qi, ki: (bh, qi, 0),
                          memory_space=pltpu.VMEM)
    k_spec = pl.BlockSpec((1, block_k, head_dim),
                          lambda bh, qi, ki: (bh, ki, 0),
                          memory_space=pltpu.VMEM)
    stat_spec = pl.BlockSpec((1, block_q, _STAT_LANES),
                             lambda bh, qi, ki: (bh, qi, 0),
                             memory_space=pltpu.VMEM)

    dq = pl.pallas_call(
        functools.partial(
            _flash_dq_kernel, causal=causal, sm_scale=float(sm_scale),
            kv_len=kv_len, q_offset=effective_offset),
        grid=(batch * heads, padded_q_len // block_q,
              padded_kv_len // block_k),
        in_specs=[q_spec, k_spec, k_spec, q_spec, stat_spec, stat_spec],
        out_specs=pl.BlockSpec((1, block_q, head_dim),
                               lambda bh, qi, ki: (bh, qi, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(
            (batch * heads, padded_q_len, head_dim), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, head_dim), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(q_p, k_p, v_p, do_p, lse_p, delta_p)

    # dk/dv: k blocks are the parallel dimension, q streams sequentially
    q_spec_t = pl.BlockSpec((1, block_q, head_dim),
                            lambda bh, ki, qi: (bh, qi, 0),
                            memory_space=pltpu.VMEM)
    k_spec_t = pl.BlockSpec((1, block_k, head_dim),
                            lambda bh, ki, qi: (bh, ki, 0),
                            memory_space=pltpu.VMEM)
    stat_spec_t = pl.BlockSpec((1, block_q, _STAT_LANES),
                               lambda bh, ki, qi: (bh, qi, 0),
                               memory_space=pltpu.VMEM)
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_dkv_kernel, causal=causal, sm_scale=float(sm_scale),
            kv_len=kv_len, q_len=q_len, q_offset=effective_offset),
        grid=(batch * heads, padded_kv_len // block_k,
              padded_q_len // block_q),
        in_specs=[q_spec_t, k_spec_t, k_spec_t, q_spec_t, stat_spec_t,
                  stat_spec_t],
        out_specs=[
            pl.BlockSpec((1, block_k, head_dim),
                         lambda bh, ki, qi: (bh, ki, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, head_dim),
                         lambda bh, ki, qi: (bh, ki, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(
                (batch * heads, padded_kv_len, head_dim), k.dtype),
            jax.ShapeDtypeStruct(
                (batch * heads, padded_kv_len, head_dim), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, head_dim), jnp.float32),
                        pltpu.VMEM((block_k, head_dim), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(q_p, k_p, v_p, do_p, lse_p, delta_p)

    dq = dq.reshape(batch, heads, padded_q_len, head_dim)[:, :, :q_len]
    dk = dk.reshape(batch, heads, padded_kv_len, head_dim)[:, :, :kv_len]
    dv = dv.reshape(batch, heads, padded_kv_len, head_dim)[:, :, :kv_len]
    return dq, dk, dv


# -- Ring attention (sequence parallel) -------------------------------------

# Test hook: when set to a callable, it is invoked (via jax.debug.callback)
# once per EXECUTED ring hop -- hops skipped by the causal lax.cond branch
# never fire it.  Tests use this to assert the masked-hop skip is real.
_RING_HOP_CALLBACK = None


def _merge_softmax_partials(out, lse, out_blk, lse_blk):
    """Associative merge of two normalized attention partials via their
    per-row logsumexp: exact online-softmax combination."""
    lse_new = jnp.logaddexp(lse, lse_blk)
    w_old = jnp.exp(lse - lse_new)[..., None]
    w_blk = jnp.exp(lse_blk - lse_new)[..., None]
    merged = (out.astype(jnp.float32) * w_old
              + out_blk.astype(jnp.float32) * w_blk)
    return merged.astype(out.dtype), lse_new


def ring_attention_sharded(q, k, v, axis_name: str = "seq",
                           causal: bool = True, sm_scale=None,
                           block_q: int = 128, block_k: int = 128):
    """Sequence-parallel attention over mesh axis `axis_name`; call INSIDE
    shard_map with q/k/v seq-sharded as (B, H, L/n, D).

    Q stays resident; K/V shards rotate n-1 hops around the ring via
    ppermute (XLA lowers to ICI collective-permute, overlapping each hop
    with the current block's MXU work).  Each hop runs the Pallas flash
    kernel (O(block) VMEM, never a materialized (L/n)^2 logit tensor) and
    returns (out, lse); hops merge with the associative online-softmax
    combination, so the result is exact.

    Under causal masking the ring ordering sends device i the K/V shard of
    device (i - step) mod n at hop `step`; that shard is entirely in the
    future (fully masked) exactly when step > i, so those hops are skipped
    with lax.cond -- no flash call, no wasted MXU work.  Device i executes
    i + 1 of the n hops; total executed hops are n(n+1)/2 instead of n^2.

    Differentiable: the custom VJP runs a second ring in which dk/dv
    accumulators travel WITH their K/V shards; each executed hop runs the
    blockwise Pallas backward kernels against the forward's GLOBAL
    logsumexp, so backward peak memory stays O(L/n x block) per device.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    local_len = q.shape[2]
    return _ring(q, k, v, bool(causal), float(sm_scale), str(axis_name),
                 int(min(block_q, local_len)), int(min(block_k, local_len)))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _ring(q, k, v, causal, sm_scale, axis_name, block_q, block_k):
    out, _ = _ring_fwd_impl(q, k, v, causal, sm_scale, axis_name, block_q,
                            block_k)
    return out


def _ring_fwd_impl(q, k, v, causal, sm_scale, axis_name, block_q, block_k):
    axis_size = jax.lax.axis_size(axis_name)
    my_index = jax.lax.axis_index(axis_name)
    batch, heads, local_len, _ = q.shape
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def compute_hop(k_blk, v_blk, step):
        # step 0 is the diagonal block (standard causal); every executed
        # later hop holds strictly-past keys, so it runs dense non-causal.
        out_blk, lse_blk = _flash_impl(
            q, k_blk, v_blk, causal and step == 0, sm_scale, block_q,
            block_k, 0)
        if _RING_HOP_CALLBACK is not None:
            jax.debug.callback(_RING_HOP_CALLBACK, step)
        return out_blk, lse_blk

    def skipped_hop(k_blk, v_blk, step):
        return (jnp.zeros_like(q),
                jnp.full((batch, heads, local_len), _NEG_INF, jnp.float32))

    out, lse = compute_hop(k, v, 0)
    out = out.astype(jnp.float32)
    k_blk, v_blk = k, v
    for step in range(1, axis_size):
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        if causal:
            # src shard = (my_index - step) mod n; fully masked iff it
            # wrapped, i.e. my_index < step
            out_blk, lse_blk = jax.lax.cond(
                my_index >= step,
                functools.partial(compute_hop, step=step),
                functools.partial(skipped_hop, step=step),
                k_blk, v_blk)
        else:
            out_blk, lse_blk = compute_hop(k_blk, v_blk, step)
        out, lse = _merge_softmax_partials(out, lse, out_blk, lse_blk)
    return out.astype(q.dtype), lse


def _ring_fwd(q, k, v, causal, sm_scale, axis_name, block_q, block_k):
    out, lse = _ring_fwd_impl(q, k, v, causal, sm_scale, axis_name,
                              block_q, block_k)
    return out, (q, k, v, out, lse)


def _ring_bwd(causal, sm_scale, axis_name, block_q, block_k, residuals,
              dout):
    """Ring backward: a second rotation in which each K/V shard travels
    with its dk/dv accumulator.  Every executed hop recomputes p blockwise
    inside the Pallas backward kernels from the forward's global lse (so
    per-hop partial gradients are exactly the global-attention gradients
    restricted to that shard); a final ppermute delivers each dk/dv
    accumulator back to its home device."""
    q, k, v, out, lse = residuals
    dq_acc = jnp.zeros(q.shape, jnp.float32)

    def compute_hop(k_blk, v_blk, dk_blk, dv_blk, step):
        dq_h, dk_h, dv_h = _flash_bwd_impl(
            q, k_blk, v_blk, out, lse, dout, causal and step == 0,
            sm_scale, block_q, block_k, 0)
        return (dq_h.astype(jnp.float32), dk_blk + dk_h.astype(jnp.float32),
                dv_blk + dv_h.astype(jnp.float32))

    def skipped_hop(k_blk, v_blk, dk_blk, dv_blk, step):
        return jnp.zeros(q.shape, jnp.float32), dk_blk, dv_blk

    axis_size = jax.lax.axis_size(axis_name)
    my_index = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    carry = dq_acc
    kv = (k, v, jnp.zeros(k.shape, jnp.float32),
          jnp.zeros(v.shape, jnp.float32))
    for step in range(axis_size):
        if step > 0:
            kv = tuple(jax.lax.ppermute(x, axis_name, perm) for x in kv)
        if causal and step > 0:
            hop_out = jax.lax.cond(
                my_index >= step,
                functools.partial(compute_hop, step=step),
                functools.partial(skipped_hop, step=step),
                *kv)
        else:
            hop_out = compute_hop(*kv, step=step)
        dq_h, dk_blk, dv_blk = hop_out
        carry = carry + dq_h
        kv = (kv[0], kv[1], dk_blk, dv_blk)
    # shard s sits on device (s + n - 1) mod n after the loop; one more
    # rotation returns every dk/dv accumulator to its home device
    dk = jax.lax.ppermute(kv[2], axis_name, perm)
    dv = jax.lax.ppermute(kv[3], axis_name, perm)
    return (carry.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype))


_ring.defvjp(_ring_fwd, _ring_bwd)


def ring_attention(q, k, v, mesh=None, axis_name: str = "seq",
                   causal: bool = True, sm_scale=None):
    """shard_map entry point: shards (B, H, L, D) on the seq axis and runs
    ring_attention_sharded.  mesh=None uses the ambient mesh (callers
    inside a jax.set_mesh context, e.g. the transformer's
    sequence-parallel prefill)."""
    spec = P(None, None, axis_name, None)
    fn = functools.partial(ring_attention_sharded, axis_name=axis_name,
                           causal=causal, sm_scale=sm_scale)
    kwargs = {} if mesh is None else {"mesh": mesh}
    return jax.shard_map(
        fn, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False, **kwargs)(q, k, v)


# -- Sequence-parallel decode attention --------------------------------------

def sp_decode_attention_sharded(q, cache_k, cache_v, pos,
                                axis_name: str = "seq", sm_scale=None):
    """Sequence-parallel KV-cached decode: call INSIDE shard_map with the
    cache length axis sharded as (B, Hkv, Lc/n, D) and q (B, H, Lq, D)
    replicated over the seq axis (Lq = 1 for single-token decode; Hkv may
    be a divisor of H -- GQA heads expand on the LOCAL shard only).

    Long-context *generation* with the cache spread over the mesh: each
    device attends q over only its local cache shard (masked to positions
    <= pos), producing a normalized partial + logsumexp; partials combine
    exactly with a pmax/psum online-softmax merge over the axis, so
    per-device attention bandwidth is O(Lc/n).  No ring needed -- q is
    tiny, so an all-reduce of the (B, H, Lq, D) partial is cheap.

    Two decode-path optimizations (round-2 weak #6):
      - GQA contracts GROUPED q heads (B, Hkv, G, Lq, D) against the
        un-expanded (B, Hkv, Lc/n, D) cache -- the cache shard, the
        dominant HBM traffic at long context, is streamed once instead
        of being materialized G times;
      - num and den merge in ONE fused psum (payload (B, H, Lq, D+1)).
        With Lq = 1 the payloads are tiny and per-step cost is
        collective LATENCY, so 2 collectives (pmax + psum) beat 3.
        A reduce-to-owner would not beat the all-reduce: every device
        needs the summed output (the following wo/MLP compute is
        replicated over the seq axis), and reduce (n-1)/n + broadcast
        (n-1)/n moves the same bytes as the 2(n-1)/n all-reduce with
        an extra latency hop.
    """
    axis_index = jax.lax.axis_index(axis_name)
    batch, kv_heads, local_len, head_dim = cache_k.shape
    q_len, heads = q.shape[2], q.shape[1]
    groups = heads // kv_heads
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(head_dim)

    q_grouped = q.reshape(batch, kv_heads, groups, q_len, head_dim)
    k_pos = (axis_index * local_len
             + jnp.arange(local_len))[None, None, None, None, :]
    q_pos = (pos + jnp.arange(q_len))[None, None, None, :, None]
    s = jnp.einsum("bhgqd,bhkd->bhgqk", q_grouped, cache_k,
                   preferred_element_type=jnp.float32) * sm_scale
    s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
    m_local = jnp.max(s, axis=-1)                       # (B, Hkv, G, Lq)
    m_global = jax.lax.pmax(m_local, axis_name)
    p = jnp.exp(s - m_global[..., None])
    num = jnp.einsum("bhgqk,bhkd->bhgqd", p,
                     cache_v.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    den = jnp.sum(p, axis=-1, keepdims=True)         # (B, Hkv, G, Lq, 1)
    fused = jax.lax.psum(jnp.concatenate([num, den], axis=-1), axis_name)
    num, den = fused[..., :head_dim], fused[..., head_dim:]
    out = (num / jnp.maximum(den, 1e-30)).astype(q.dtype)
    return out.reshape(batch, heads, q_len, head_dim)


def sp_decode_attention(q, cache_k, cache_v, pos, mesh=None,
                        axis_name: str = "seq", sm_scale=None,
                        batch_axis: str = "data", head_axis: str = "model"):
    """shard_map entry point for sequence-parallel decode: cache length
    sharded over `axis_name`, q sharded only on batch/head axes (when the
    mesh has them -- composes with DP + TP), output sharded like q."""
    if mesh is None:
        axis_names = jax.sharding.get_abstract_mesh().axis_names
    else:
        axis_names = mesh.axis_names
    b_ax = batch_axis if batch_axis in axis_names else None
    h_ax = head_axis if head_axis in axis_names else None
    q_spec = P(b_ax, h_ax, None, None)
    cache_spec = P(b_ax, h_ax, axis_name, None)
    fn = functools.partial(sp_decode_attention_sharded,
                           axis_name=axis_name, sm_scale=sm_scale)
    kwargs = {} if mesh is None else {"mesh": mesh}
    return jax.shard_map(
        fn,
        in_specs=(q_spec, cache_spec, cache_spec, P()),
        out_specs=q_spec,
        check_vma=False, **kwargs)(q, cache_k, cache_v, jnp.asarray(pos))


# -- Ulysses (all-to-all) sequence parallelism ------------------------------

def ulysses_attention_sharded(q, k, v, axis_name: str = "seq",
                              causal: bool = False, sm_scale=None):
    """DeepSpeed-Ulysses style: all-to-all swaps seq-sharding for
    head-sharding, dense local attention (flash kernel) over the full
    sequence, then all-to-all back.  Call INSIDE shard_map with q/k/v
    seq-sharded (B, H, L/n, D); the head count must be divisible by the
    axis size."""
    axis_size = jax.lax.axis_size(axis_name)
    heads = q.shape[1]
    if heads % axis_size != 0:
        raise ValueError(
            f"ulysses_attention: heads ({heads}) must be divisible by "
            f"mesh axis '{axis_name}' size ({axis_size}); use "
            f"ring_attention for head counts smaller than the axis")
    def seq_to_heads(x):   # (B, H, L/n, D) -> (B, H/n, L, D)
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    def heads_to_seq(x):   # (B, H/n, L, D) -> (B, H, L/n, D)
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    out = flash_attention(
        seq_to_heads(q), seq_to_heads(k), seq_to_heads(v),
        causal=causal, sm_scale=sm_scale)
    return heads_to_seq(out)


def ulysses_attention(q, k, v, mesh=None, axis_name: str = "seq",
                      causal: bool = False, sm_scale=None):
    """mesh=None uses the ambient mesh (callers inside jax.set_mesh,
    e.g. the transformer's sp_mechanism=\"ulysses\" prefill)."""
    spec = P(None, None, axis_name, None)
    fn = functools.partial(ulysses_attention_sharded, axis_name=axis_name,
                           causal=causal, sm_scale=sm_scale)
    kwargs = {} if mesh is None else {"mesh": mesh}
    # check_vma=False: pallas_call inside shard_map can't declare varying
    # mesh axes on its ShapeDtypeStruct outputs yet
    return jax.shard_map(
        fn, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False, **kwargs)(q, k, v)
