# Device-mesh management: the TPU-native placement layer.
#
# The reference has no counterpart (SURVEY.md 2.4: TP/SP "absent") -- its
# only parallelism is process-level replication over MQTT.  Here the mesh is
# the first-class primitive: every ComputeElement may name mesh axes in its
# definition's "sharding" block and the engine places its state and batch
# math with jax.sharding.NamedSharding over a shared jax.sharding.Mesh.
#
# Axis convention (the "How to Scale Your Model" recipe):
#   data  -- batch-axis data parallelism (gradients psum here)
#   fsdp  -- parameter sharding axis (zero-style, all-gather on use)
#   model -- tensor parallelism (megatron-style matmul sharding)
#   seq   -- sequence/context parallelism (ring attention / Ulysses)
#   expert - expert parallelism for MoE layers
#
# Meshes are cached by (axes, device fingerprint) so every element naming the
# same topology shares one Mesh object (and therefore one XLA compilation
# environment).

from __future__ import annotations

import threading

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = [
    "MESH_AXIS_ORDER", "create_mesh", "get_mesh", "named_sharding",
    "partition_spec", "shard_pytree", "filter_specs",
]

# Ordering matters for ICI locality: innermost (fastest-varying) axes get
# the most tightly coupled devices.  model/seq want maximum ICI bandwidth,
# so they are last (minor-most) in the device grid.
MESH_AXIS_ORDER = ("data", "fsdp", "expert", "pipeline", "seq", "model")

_MESH_CACHE: dict = {}
_MESH_LOCK = threading.Lock()


def _canonical_axes(axes: dict, device_count: int) -> tuple:
    """Order axes by MESH_AXIS_ORDER (unknown names keep given order at the
    end) and resolve a single -1 entry to fill the remaining devices."""
    known = [name for name in MESH_AXIS_ORDER if name in axes]
    unknown = [name for name in axes if name not in MESH_AXIS_ORDER]
    ordered = known + unknown
    sizes = {name: int(axes[name]) for name in ordered}
    fill = [name for name, size in sizes.items() if size == -1]
    if len(fill) > 1:
        raise ValueError(f"Only one mesh axis may be -1, got {fill}")
    if fill:
        fixed = 1
        for name, size in sizes.items():
            if size != -1:
                fixed *= size
        if device_count % fixed != 0:
            raise ValueError(
                f"{device_count} devices not divisible by fixed axes "
                f"{sizes} (product {fixed})")
        sizes[fill[0]] = device_count // fixed
    return tuple((name, sizes[name]) for name in ordered)


def create_mesh(axes: dict | None = None, devices=None) -> Mesh:
    """Build a Mesh from an axis-size mapping, e.g. {"data": -1, "model": 4}.

    With no axes, the whole device set becomes a 1-D "data" mesh.  Device
    grids come from mesh_utils.create_device_mesh so multi-chip TPU slices
    get an ICI-aware layout; on CPU (tests) this degenerates to a reshape.
    """
    devices = list(devices if devices is not None else jax.devices())
    axes = dict(axes or {"data": -1})
    canonical = _canonical_axes(axes, len(devices))
    shape = tuple(size for _, size in canonical)
    names = tuple(name for name, _ in canonical)
    total = int(np.prod(shape))
    if total != len(devices):
        raise ValueError(
            f"Mesh axes {dict(canonical)} need {total} devices, "
            f"have {len(devices)}")
    try:
        grid = mesh_utils.create_device_mesh(shape, devices=devices)
    except (ValueError, AssertionError):
        grid = np.asarray(devices).reshape(shape)
    return Mesh(grid, names)


def get_mesh(axes: dict | None = None, devices=None) -> Mesh:
    """Cached create_mesh: elements naming the same topology share a Mesh."""
    devices_list = list(devices if devices is not None else jax.devices())
    axes = dict(axes or {"data": -1})
    key = (tuple(sorted(axes.items())),
           tuple(id(device) for device in devices_list))
    with _MESH_LOCK:
        mesh = _MESH_CACHE.get(key)
        if mesh is None:
            mesh = create_mesh(axes, devices_list)
            _MESH_CACHE[key] = mesh
        return mesh


def partition_spec(spec) -> PartitionSpec:
    """Coerce a user-level spec into a PartitionSpec.

    Accepts: PartitionSpec (passthrough), None (replicated), a single axis
    name ("data" == shard dim 0 on data), or a list whose entries are axis
    names, None, or tuples/lists of axis names, e.g. ["data", None, "model"]
    or [["data", "fsdp"], None].
    """
    if isinstance(spec, PartitionSpec):
        return spec
    if spec is None:
        return PartitionSpec()
    if isinstance(spec, str):  # bare name, NOT iterated per-character
        return PartitionSpec(spec)
    entries = []
    for entry in spec:
        if isinstance(entry, (list, tuple)):
            entries.append(tuple(entry))
        else:
            entries.append(entry)
    return PartitionSpec(*entries)


def named_sharding(mesh: Mesh, spec) -> NamedSharding:
    return NamedSharding(mesh, partition_spec(spec))


def shard_pytree(tree, mesh: Mesh, specs):
    """device_put a pytree with per-leaf PartitionSpecs.

    specs may be a single spec applied to every leaf, or a (possibly
    PARTIAL) pytree: leaves present in `tree` but absent from `specs`
    replicate.  Partial trees matter for checkpoint ingestion -- an HF
    whisper pytree carries bias leaves the published asr_param_specs
    doesn't name, and under global-view SPMD a replicated bias is
    correct (XLA still partitions the matmuls it feeds)."""
    if isinstance(specs, (PartitionSpec, list, tuple)) or specs is None:
        shardings = jax.tree_util.tree_map(
            lambda _: named_sharding(mesh, specs), tree)
    else:
        def spec_leaf(entry):
            """Is `entry` one whole-array spec (vs a structural subtree)?
            Axis lists like ["data", None] or [("data", "fsdp"), None]
            count -- they BROADCAST over a subtree (the old device_put
            prefix-tree semantics); per-item spec lists must therefore
            use PartitionSpec objects to stay unambiguous."""
            if entry is None or isinstance(entry, (PartitionSpec, str)):
                return True
            if isinstance(entry, (list, tuple)):
                return all(
                    axis is None or isinstance(axis, str)
                    or (isinstance(axis, (list, tuple))
                        and all(isinstance(name, str) for name in axis))
                    for axis in entry)
            return False

        def build(node, spec_node):
            if isinstance(node, dict):
                if isinstance(spec_node, dict):
                    return {key: build(value, spec_node.get(key))
                            for key, value in node.items()}
                broadcast = spec_node if spec_leaf(spec_node) else None
                return {key: build(value, broadcast)
                        for key, value in node.items()}
            if isinstance(node, (list, tuple)):
                if (isinstance(spec_node, (list, tuple))
                        and not spec_leaf(spec_node)
                        and len(spec_node) == len(node)):
                    built = [build(value, spec)
                             for value, spec in zip(node, spec_node)]
                else:
                    broadcast = spec_node if spec_leaf(spec_node) else None
                    built = [build(value, broadcast) for value in node]
                if isinstance(node, tuple):
                    # namedtuples (e.g. optax opt_state) take positional
                    # fields, not an iterable
                    return (type(node)(*built) if hasattr(node, "_fields")
                            else type(node)(built))
                return built
            return named_sharding(
                mesh, spec_node if spec_leaf(spec_node) else None)

        shardings = build(tree, specs)
    return jax.device_put(tree, shardings)


def filter_specs(specs, mesh: Mesh):
    """Drop axis names a mesh doesn't have from a pytree of PartitionSpecs.

    Model code publishes specs over the full axis vocabulary (data/fsdp/
    seq/model); a deployment that collapses an axis (e.g. no FSDP on a
    single host) filters rather than rewriting every spec.
    """
    names = set(mesh.axis_names)

    def _filter_entry(entry):
        if entry is None:
            return None
        if isinstance(entry, (tuple, list)):
            kept = tuple(name for name in entry if name in names)
            return kept if kept else None
        return entry if entry in names else None

    def _filter(spec):
        spec = partition_spec(spec)
        return PartitionSpec(*(_filter_entry(entry) for entry in spec))

    return jax.tree_util.tree_map(
        _filter, specs,
        is_leaf=lambda leaf: (leaf is None
                              or isinstance(leaf, (PartitionSpec, list,
                                                   str))))
