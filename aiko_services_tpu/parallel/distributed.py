# Multi-host runtime: jax.distributed initialization + global meshes.
#
# The reference's only cross-host fabric is the MQTT broker (reference:
# src/aiko_services/main/message/mqtt.py; SURVEY.md 2.4 "Distributed comm
# backend" -- no NCCL/MPI/Gloo anywhere).  The TPU-native equivalent keeps
# the broker for CONTROL traffic and runs the DATA plane over the runtime
# fabric XLA already owns: jax.distributed connects every host's runtime to
# a coordinator, after which jax.devices() spans the whole pod/slice and
# meshes built here generate ICI/DCN collectives (psum/ppermute/all_gather)
# directly between chips -- no broker hop, no serialization.
#
# Deployment contract (mirrors TPU pod env conventions):
#   AIKO_COORDINATOR   host:port of process 0 (also JAX auto-detects on
#                      Cloud TPU -- leave everything unset there)
#   AIKO_NUM_PROCESSES total framework Processes in the job
#   AIKO_PROCESS_ID    this process's rank
#
# Works on CPU backends too (Gloo), which is how the tests exercise a
# 2-process global mesh without TPU hardware.

from __future__ import annotations

import os
import threading

import jax

from .mesh import create_mesh

__all__ = [
    "initialize_distributed", "shutdown_distributed", "is_distributed",
    "global_mesh", "process_index", "process_count",
]

_LOCK = threading.Lock()
_INITIALIZED = False


def initialize_distributed(coordinator_address: str | None = None,
                           num_processes: int | None = None,
                           process_id: int | None = None,
                           local_device_ids=None) -> bool:
    """Connect this process to the job's JAX runtime fabric.

    Arguments default to the AIKO_* env contract above; with nothing set
    anywhere (single-process deployment) this is a no-op returning False.
    Idempotent: repeated calls after a successful init return True.
    """
    global _INITIALIZED
    with _LOCK:
        if _INITIALIZED:
            return True
        coordinator_address = (coordinator_address
                               or os.environ.get("AIKO_COORDINATOR"))
        if num_processes is None and "AIKO_NUM_PROCESSES" in os.environ:
            num_processes = int(os.environ["AIKO_NUM_PROCESSES"])
        if process_id is None and "AIKO_PROCESS_ID" in os.environ:
            process_id = int(os.environ["AIKO_PROCESS_ID"])
        if coordinator_address is None and num_processes is None:
            return False  # single-process deployment
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=local_device_ids)
        _INITIALIZED = True
        return True


def shutdown_distributed():
    global _INITIALIZED
    with _LOCK:
        if _INITIALIZED:
            jax.distributed.shutdown()
            _INITIALIZED = False


def is_distributed() -> bool:
    """True once this process has joined a multi-process job.  Must NOT
    touch jax.process_count()/jax.devices() here: those initialize the
    local backend, after which jax.distributed.initialize refuses to run
    -- the `if not is_distributed(): initialize_distributed()` idiom has
    to stay safe."""
    if _INITIALIZED:
        return True
    try:
        from jax._src.distributed import global_state
        return getattr(global_state, "client", None) is not None
    except (ImportError, AttributeError):  # pragma: no cover - internals moved
        return False


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def global_mesh(axes: dict | None = None):
    """A mesh over the JOB's devices (all hosts), not just this host's.

    After initialize_distributed, jax.devices() already spans every
    process; axis sizes follow the same conventions as create_mesh
    ({"data": -1, "model": 4}, one -1 fills).  Computations jit over this
    mesh move data between hosts via XLA collectives -- the cross-host
    data plane (SURVEY.md 5).
    """
    return create_mesh(axes, devices=jax.devices())
