# Audio feature ops: log-mel spectrogram as pure jit-able JAX.
#
# The reference's speech stack feeds raw 16 kHz chunks to WhisperX, which
# computes features internally on CUDA (reference: src/aiko_services/
# examples/speech/speech_elements.py:229-262; audio constants
# elements/media/audio_io.py:455-460 -- 16 kHz, 5 s chunks).  Here the
# frontend is explicit, differentiable, and fuses into the encoder's jit.
#
# STFT via jnp.fft.rfft over framed windows; mel filterbank built host-side
# with numpy (static per config) and closed over as a constant.

from __future__ import annotations

import functools

import jax.numpy as jnp
import numpy as np

__all__ = ["mel_filterbank", "log_mel_spectrogram", "SAMPLE_RATE",
           "N_FFT", "HOP_LENGTH", "N_MELS"]

SAMPLE_RATE = 16000
N_FFT = 400
HOP_LENGTH = 160
N_MELS = 80


def _hz_to_mel(frequency):
    return 2595.0 * np.log10(1.0 + np.asarray(frequency) / 700.0)


def _mel_to_hz(mel):
    return 700.0 * (10.0 ** (np.asarray(mel) / 2595.0) - 1.0)


@functools.lru_cache(maxsize=8)
def mel_filterbank(sample_rate: int = SAMPLE_RATE, n_fft: int = N_FFT,
                   n_mels: int = N_MELS) -> np.ndarray:
    """(n_mels, n_fft//2 + 1) triangular slaney-style filterbank."""
    n_freqs = n_fft // 2 + 1
    fft_freqs = np.linspace(0, sample_rate / 2, n_freqs)
    mel_points = np.linspace(_hz_to_mel(0.0), _hz_to_mel(sample_rate / 2),
                             n_mels + 2)
    hz_points = _mel_to_hz(mel_points)
    bank = np.zeros((n_mels, n_freqs), np.float32)
    for index in range(n_mels):
        lower, center, upper = hz_points[index:index + 3]
        up_slope = (fft_freqs - lower) / max(center - lower, 1e-10)
        down_slope = (upper - fft_freqs) / max(upper - center, 1e-10)
        bank[index] = np.maximum(0.0, np.minimum(up_slope, down_slope))
        # slaney area normalization
        enorm = 2.0 / (upper - lower)
        bank[index] *= enorm
    return bank


def log_mel_spectrogram(waveform, sample_rate: int = SAMPLE_RATE,
                        n_fft: int = N_FFT, hop_length: int = HOP_LENGTH,
                        n_mels: int = N_MELS):
    """waveform (..., samples) f32 -> log-mel (..., n_mels, frames).

    Whisper-style: hann window, magnitude^2, mel projection, log10 clamped
    to 8 decades below the peak, scaled to roughly [-1, 1].
    """
    waveform = jnp.asarray(waveform, jnp.float32)
    pad = n_fft // 2
    padded = jnp.pad(waveform,
                     [(0, 0)] * (waveform.ndim - 1) + [(pad, pad)],
                     mode="reflect")
    n_frames = 1 + (padded.shape[-1] - n_fft) // hop_length
    frame_starts = jnp.arange(n_frames) * hop_length
    indices = frame_starts[:, None] + jnp.arange(n_fft)[None, :]
    frames = padded[..., indices]                  # (..., frames, n_fft)
    window = jnp.hanning(n_fft).astype(jnp.float32)
    spectrum = jnp.fft.rfft(frames * window, axis=-1)
    power = jnp.abs(spectrum) ** 2                 # (..., frames, n_freqs)
    bank = jnp.asarray(mel_filterbank(sample_rate, n_fft, n_mels))
    mel = jnp.einsum("...tf,mf->...mt", power, bank)
    log_mel = jnp.log10(jnp.maximum(mel, 1e-10))
    log_mel = jnp.maximum(log_mel, jnp.max(log_mel, axis=(-2, -1),
                                           keepdims=True) - 8.0)
    return (log_mel + 4.0) / 4.0
