# Audio feature ops: log-mel spectrogram as pure jit-able JAX.
#
# The reference's speech stack feeds raw 16 kHz chunks to WhisperX, which
# computes features internally on CUDA (reference: src/aiko_services/
# examples/speech/speech_elements.py:229-262; audio constants
# elements/media/audio_io.py:455-460 -- 16 kHz, 5 s chunks).  Here the
# frontend is explicit, differentiable, and fuses into the encoder's jit.
#
# STFT via jnp.fft.rfft over framed windows; mel filterbank built host-side
# with numpy (static per config) and closed over as a constant.

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["mel_filterbank", "log_mel_spectrogram", "SAMPLE_RATE",
           "N_FFT", "HOP_LENGTH", "N_MELS"]

SAMPLE_RATE = 16000
N_FFT = 400
HOP_LENGTH = 160
N_MELS = 80


def _hz_to_mel(frequency):
    return 2595.0 * np.log10(1.0 + np.asarray(frequency) / 700.0)


def _mel_to_hz(mel):
    return 700.0 * (10.0 ** (np.asarray(mel) / 2595.0) - 1.0)


@functools.lru_cache(maxsize=8)
def dft_basis(n_fft: int) -> tuple:
    """rfft as real matmul bases: (cos, -sin), each (n_fft, bins) --
    the rfft convention e^{-i angle}.  Shared by the ASR conv-STFT
    kernel and the TTS Griffin-Lim transforms (models/tts.py).
    CAUTION: run these through matmul/conv with Precision.HIGHEST --
    the default TPU precision loses ~3 decimal digits on the DFT's
    cancellation-heavy sums (measured in log_mel_spectrogram)."""
    n_freqs = n_fft // 2 + 1
    angles = (2.0 * np.pi / n_fft) * np.outer(np.arange(n_fft),
                                              np.arange(n_freqs))
    return (np.cos(angles).astype(np.float32),
            (-np.sin(angles)).astype(np.float32))


@functools.lru_cache(maxsize=8)
def _stft_kernel(n_fft: int) -> np.ndarray:
    """Windowed real-DFT basis as a conv kernel (n_fft, 1, n_fft+2):
    the whole STFT becomes ONE strided convolution.

    TPU-first, twice over: XLA lowers jnp.fft.rfft to a slow generic FFT
    on TPU, and the frame-extraction gather (samples -> overlapping
    windows) is a bandwidth-hostile materialization.  A conv with stride
    hop_length and 2*(n_fft//2+1) output channels (cos|sin per frequency)
    does framing, windowing, and the DFT in one MXU-native op: ~2.6 GFLOP
    for 16x5 s of audio (measured: 29 ms via rfft+gather -> sub-ms)."""
    cos_m, sin_m = dft_basis(n_fft)
    window = np.hanning(n_fft).astype(np.float32)[:, None]
    basis = np.concatenate([cos_m, sin_m], axis=1)
    return (window * basis)[:, None, :]            # (W, I=1, O=2*n_freqs)


@functools.lru_cache(maxsize=8)
def mel_filterbank(sample_rate: int = SAMPLE_RATE, n_fft: int = N_FFT,
                   n_mels: int = N_MELS) -> np.ndarray:
    """(n_mels, n_fft//2 + 1) triangular slaney-style filterbank."""
    n_freqs = n_fft // 2 + 1
    fft_freqs = np.linspace(0, sample_rate / 2, n_freqs)
    mel_points = np.linspace(_hz_to_mel(0.0), _hz_to_mel(sample_rate / 2),
                             n_mels + 2)
    hz_points = _mel_to_hz(mel_points)
    bank = np.zeros((n_mels, n_freqs), np.float32)
    for index in range(n_mels):
        lower, center, upper = hz_points[index:index + 3]
        up_slope = (fft_freqs - lower) / max(center - lower, 1e-10)
        down_slope = (upper - fft_freqs) / max(upper - center, 1e-10)
        bank[index] = np.maximum(0.0, np.minimum(up_slope, down_slope))
        # slaney area normalization
        enorm = 2.0 / (upper - lower)
        bank[index] *= enorm
    return bank


def log_mel_spectrogram(waveform, sample_rate: int = SAMPLE_RATE,
                        n_fft: int = N_FFT, hop_length: int = HOP_LENGTH,
                        n_mels: int = N_MELS):
    """waveform (..., samples) f32 -> log-mel (..., n_mels, frames).

    Whisper-style: hann window, magnitude^2, mel projection, log10 clamped
    to 8 decades below the peak, scaled to roughly [-1, 1].
    """
    waveform = jnp.asarray(waveform, jnp.float32)
    pad = n_fft // 2
    padded = jnp.pad(waveform,
                     [(0, 0)] * (waveform.ndim - 1) + [(pad, pad)],
                     mode="reflect")
    # framing + windowing + real DFT as ONE strided conv (_stft_kernel)
    lead_shape = padded.shape[:-1]
    x = padded.reshape((-1, padded.shape[-1], 1))  # NWC, C=1
    spectrum = jax.lax.conv_general_dilated(
        x, jnp.asarray(_stft_kernel(n_fft)),
        window_strides=(hop_length,), padding="VALID",
        dimension_numbers=("NWC", "WIO", "NWC"),
        # full-f32 accumulation: the default TPU/CPU conv precision
        # loses ~3 decimal digits on the DFT's cancellation-heavy sums
        # (measured p50 relative error 3e-3 -> 1e-7 at HIGHEST); the
        # extra passes are noise at ~2.6 GFLOP
        precision=jax.lax.Precision.HIGHEST)
    n_freqs = n_fft // 2 + 1
    real, imag = spectrum[..., :n_freqs], spectrum[..., n_freqs:]
    power = real * real + imag * imag
    power = power.reshape(lead_shape + power.shape[1:])
    bank = jnp.asarray(mel_filterbank(sample_rate, n_fft, n_mels))
    mel = jnp.einsum("...tf,mf->...mt", power, bank)
    log_mel = jnp.log10(jnp.maximum(mel, 1e-10))
    log_mel = jnp.maximum(log_mel, jnp.max(log_mel, axis=(-2, -1),
                                           keepdims=True) - 8.0)
    return (log_mel + 4.0) / 4.0
