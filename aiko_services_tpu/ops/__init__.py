from .audio import (                                          # noqa: F401
    mel_filterbank, log_mel_spectrogram, SAMPLE_RATE, N_FFT, HOP_LENGTH,
    N_MELS)
