# Device-array coercion shared by every element that takes tensor input.

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["as_device_array"]


def as_device_array(value, dtype):
    """Coerce an element input to a device array WITHOUT a host round-trip
    when it is already a jax.Array (np.asarray on a device array forces a
    device->host sync + copy -- poison for HBM-resident pipelines)."""
    if isinstance(value, jax.Array):
        return value.astype(dtype)
    return jnp.asarray(np.asarray(value), dtype)
