# Real YOLOv8 architecture (CSP backbone + PAN neck + decoupled DFL
# head) with ultralytics checkpoint ingestion.
#
# The reference runs pretrained Ultralytics YOLOv8 through torch/CUDA
# (reference: src/aiko_services/examples/yolo/yolo.py:51-87).  This module
# re-implements the v8 graph TPU-first -- NHWC convs on the MXU
# (layers.py conv2d), BatchNorm FOLDED into conv weights at load time so
# inference is pure conv+bias, the whole network one jit -- and maps the
# published ultralytics tensor naming ("model.0.conv.weight",
# "model.22.cv2.0.0.conv.weight", ...) onto the pytree, so an exported
# yolov8n safetensors loads with no code changes.  Detection decode uses
# the same fixed-size Jacobi NMS as the native detector (detector.py).
#
# The width/repeats tuples parametrize the v8 family (n/s/m/l/x); the
# yolov8n defaults mirror ultralytics' width_multiple 0.25 / depth 0.33.

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

from .detector import non_max_suppression
from .layers import conv2d

__all__ = ["YoloV8Config", "YOLOV8N", "YOLO_VARIANTS",
           "init_yolo_params", "infer_yolov8_config",
           "load_yolov8_params", "yolo_forward", "yolo_detect"]

_BN_EPS = 1e-3  # ultralytics Conv uses BatchNorm2d(eps=0.001)


@dataclass(frozen=True)
class YoloV8Config:
    n_classes: int = 80
    # channels after: stem(P1), P2, P3, P4, P5
    width: tuple = (16, 32, 64, 128, 256)
    # C2f bottleneck repeats at P2..P5 (neck C2fs are always 1 deep here:
    # true for n/s; larger family members repeat the neck too)
    repeats: tuple = (1, 2, 2, 1)
    neck_repeats: int = 1
    reg_max: int = 16
    strides: tuple = (8, 16, 32)
    image_size: int = 640
    max_detections: int = 300
    score_threshold: float = 0.25
    iou_threshold: float = 0.45
    dtype: str = "bfloat16"

    @property
    def jnp_dtype(self):
        return jnp.dtype(self.dtype)

    @property
    def head_box_channels(self) -> int:
        return 4 * self.reg_max

    @property
    def head_cls_hidden(self) -> int:
        # ultralytics Detect: c3 = max(ch[0], min(nc, 100))
        return max(self.width[2], min(self.n_classes, 100))

    @property
    def head_box_hidden(self) -> int:
        # ultralytics Detect: c2 = max(16, ch[0] // 4, reg_max * 4)
        return max(16, self.width[2] // 4, self.reg_max * 4)


YOLOV8N = YoloV8Config()

# the published v8 family (ultralytics width/depth multiples applied to
# the base (64, 128, 256, 512, 1024) channel ladder with per-variant
# max_channels; repeats = round(base (3, 6, 6, 3) * depth))
YOLO_VARIANTS = {
    "n": YoloV8Config(),
    "s": YoloV8Config(width=(32, 64, 128, 256, 512)),
    "m": YoloV8Config(width=(48, 96, 192, 384, 576),
                      repeats=(2, 4, 4, 2), neck_repeats=2),
    "l": YoloV8Config(width=(64, 128, 256, 512, 512),
                      repeats=(3, 6, 6, 3), neck_repeats=3),
    "x": YoloV8Config(width=(80, 160, 320, 640, 640),
                      repeats=(3, 6, 6, 3), neck_repeats=3),
}


# -- parameter construction --------------------------------------------------

def _conv_init(key, c_in, c_out, kernel, dtype):
    fan = c_in * kernel * kernel
    return {"w": (jax.random.normal(
        key, (kernel, kernel, c_in, c_out), jnp.float32)
        / np.sqrt(fan)).astype(dtype),
        "b": jnp.zeros((c_out,), dtype)}


def _c2f_init(key, c_in, c_out, n, dtype):
    keys = jax.random.split(key, 2 + 2 * n)
    half = c_out // 2
    return {
        "cv1": _conv_init(keys[0], c_in, c_out, 1, dtype),
        "cv2": _conv_init(keys[1], (2 + n) * half, c_out, 1, dtype),
        "m": [{"cv1": _conv_init(keys[2 + 2 * i], half, half, 3, dtype),
               "cv2": _conv_init(keys[3 + 2 * i], half, half, 3, dtype)}
              for i in range(n)],
    }


def init_yolo_params(config: YoloV8Config, key) -> dict:
    """Random-init pytree with the exact structure the ultralytics loader
    produces (tests / training-from-scratch)."""
    keys = iter(jax.random.split(key, 64))
    w = config.width
    dtype = config.jnp_dtype
    r = config.repeats
    nr = config.neck_repeats
    params = {
        "m0": _conv_init(next(keys), 3, w[0], 3, dtype),
        "m1": _conv_init(next(keys), w[0], w[1], 3, dtype),
        "m2": _c2f_init(next(keys), w[1], w[1], r[0], dtype),
        "m3": _conv_init(next(keys), w[1], w[2], 3, dtype),
        "m4": _c2f_init(next(keys), w[2], w[2], r[1], dtype),
        "m5": _conv_init(next(keys), w[2], w[3], 3, dtype),
        "m6": _c2f_init(next(keys), w[3], w[3], r[2], dtype),
        "m7": _conv_init(next(keys), w[3], w[4], 3, dtype),
        "m8": _c2f_init(next(keys), w[4], w[4], r[3], dtype),
        "m9": {"cv1": _conv_init(next(keys), w[4], w[4] // 2, 1, dtype),
               "cv2": _conv_init(next(keys), w[4] * 2, w[4], 1, dtype)},
        "m12": _c2f_init(next(keys), w[4] + w[3], w[3], nr, dtype),
        "m15": _c2f_init(next(keys), w[3] + w[2], w[2], nr, dtype),
        "m16": _conv_init(next(keys), w[2], w[2], 3, dtype),
        "m18": _c2f_init(next(keys), w[3] + w[2], w[3], nr, dtype),
        "m19": _conv_init(next(keys), w[3], w[3], 3, dtype),
        "m21": _c2f_init(next(keys), w[4] + w[3], w[4], nr, dtype),
    }
    box_c, cls_c = config.head_box_hidden, config.head_cls_hidden
    head = {"cv2": [], "cv3": []}
    for c_in in (w[2], w[3], w[4]):
        head["cv2"].append([
            _conv_init(next(keys), c_in, box_c, 3, dtype),
            _conv_init(next(keys), box_c, box_c, 3, dtype),
            _conv_init(next(keys), box_c, config.head_box_channels, 1,
                       dtype)])
        head["cv3"].append([
            _conv_init(next(keys), c_in, cls_c, 3, dtype),
            _conv_init(next(keys), cls_c, cls_c, 3, dtype),
            _conv_init(next(keys), cls_c, config.n_classes, 1, dtype)])
    params["m22"] = head
    return params


# -- ultralytics checkpoint ingestion ----------------------------------------

def _fold_bn(weight, gamma, beta, mean, var, dtype):
    """Fold BatchNorm into the conv: w' = w * g/sqrt(v+eps) per output
    channel, b' = beta - mean * g/sqrt(v+eps).  Torch (O, I, kh, kw) ->
    HWIO."""
    scale = gamma / np.sqrt(var + _BN_EPS)
    folded = weight * scale[:, None, None, None]
    bias = beta - mean * scale
    return {"w": np.ascontiguousarray(
        folded.transpose(2, 3, 1, 0)).astype(dtype, copy=False),
        "b": bias.astype(dtype, copy=False)}


def infer_yolov8_config(paths, **overrides) -> YoloV8Config:
    """Derive the family layout (width ladder, C2f repeats, n_classes,
    reg_max) from an ultralytics checkpoint's own tensor shapes -- any
    v8 variant (or custom width) loads without naming it.  `overrides`
    set the non-architectural fields (image_size, thresholds, dtype)."""
    from .weights import open_checkpoint
    with open_checkpoint(paths) as (index, _raw):
        prefix = "" if "model.0.conv.weight" in index else "model."
        if prefix + "model.0.conv.weight" not in index:
            raise KeyError(
                "not an ultralytics YOLOv8 checkpoint: missing "
                "model.0.conv.weight")

        def out_channels(name):
            return index[prefix + name].shape(prefix + name)[0]

        def repeats_of(module):
            count = 0
            while (f"{prefix}model.{module}.m.{count}.cv1.conv.weight"
                   in index):
                count += 1
            return max(count, 1)

        return YoloV8Config(
            width=tuple(out_channels(f"model.{i}.conv.weight")
                        for i in (0, 1, 3, 5, 7)),
            repeats=(repeats_of(2), repeats_of(4), repeats_of(6),
                     repeats_of(8)),
            neck_repeats=repeats_of(12),
            n_classes=out_channels("model.22.cv3.0.2.weight"),
            reg_max=out_channels("model.22.cv2.0.2.weight") // 4,
            **overrides)


def load_yolov8_params(paths, config: YoloV8Config) -> dict:
    """Ultralytics YOLOv8 naming -> this module's pytree (BN folded).

    Expects the model's state_dict exported to safetensors (names like
    "model.0.conv.weight", "model.2.m.0.cv1.bn.running_mean",
    "model.22.cv3.1.2.bias"; an optional "model." -> "" prefix variation
    is handled).  The fixed DFL conv ("model.22.dfl.conv.weight") is an
    arange and is not stored -- decode recomputes it."""
    from .weights import open_checkpoint
    with open_checkpoint(paths) as (index, fetch):
        prefix = "" if "model.0.conv.weight" in index else "model."
        if prefix + "model.0.conv.weight" not in index:
            raise KeyError(
                "not an ultralytics YOLOv8 checkpoint: missing "
                "model.0.conv.weight")
        stem_out = index[prefix + "model.0.conv.weight"].shape(
            prefix + "model.0.conv.weight")[0]
        if stem_out != config.width[0]:
            variants = {cfg.width[0]: name
                        for name, cfg in YOLO_VARIANTS.items()}
            hint = variants.get(stem_out)
            raise ValueError(
                f"checkpoint stem has {stem_out} channels but the config "
                f"expects width {config.width}"
                + (f" -- this looks like yolov8{hint}; set the "
                   f"yolo_variant parameter (or YOLO_VARIANTS[{hint!r}])"
                   if hint else ""))
        dtype = np.dtype(config.dtype)

        def raw(name):
            return np.asarray(fetch(prefix + name), np.float32)

        def conv_bn(stem):
            return _fold_bn(raw(f"{stem}.conv.weight"),
                            raw(f"{stem}.bn.weight"), raw(f"{stem}.bn.bias"),
                            raw(f"{stem}.bn.running_mean"),
                            raw(f"{stem}.bn.running_var"), dtype)

        def plain_conv(stem):
            weight = raw(f"{stem}.weight")
            return {"w": np.ascontiguousarray(
                weight.transpose(2, 3, 1, 0)).astype(dtype, copy=False),
                "b": raw(f"{stem}.bias").astype(dtype, copy=False)}

        def c2f(module, n):
            return {
                "cv1": conv_bn(f"model.{module}.cv1"),
                "cv2": conv_bn(f"model.{module}.cv2"),
                "m": [{"cv1": conv_bn(f"model.{module}.m.{i}.cv1"),
                       "cv2": conv_bn(f"model.{module}.m.{i}.cv2")}
                      for i in range(n)],
            }

        r, nr = config.repeats, config.neck_repeats
        params = {
            "m0": conv_bn("model.0"), "m1": conv_bn("model.1"),
            "m2": c2f(2, r[0]), "m3": conv_bn("model.3"),
            "m4": c2f(4, r[1]), "m5": conv_bn("model.5"),
            "m6": c2f(6, r[2]), "m7": conv_bn("model.7"),
            "m8": c2f(8, r[3]),
            "m9": {"cv1": conv_bn("model.9.cv1"),
                   "cv2": conv_bn("model.9.cv2")},
            "m12": c2f(12, nr), "m15": c2f(15, nr),
            "m16": conv_bn("model.16"),
            "m18": c2f(18, nr), "m19": conv_bn("model.19"),
            "m21": c2f(21, nr),
        }
        head = {"cv2": [], "cv3": []}
        for scale in range(3):
            head["cv2"].append([
                conv_bn(f"model.22.cv2.{scale}.0"),
                conv_bn(f"model.22.cv2.{scale}.1"),
                plain_conv(f"model.22.cv2.{scale}.2")])
            head["cv3"].append([
                conv_bn(f"model.22.cv3.{scale}.0"),
                conv_bn(f"model.22.cv3.{scale}.1"),
                plain_conv(f"model.22.cv3.{scale}.2")])
        params["m22"] = head
        return jax.tree_util.tree_map(jnp.asarray, params)


# -- forward -----------------------------------------------------------------

def _conv(params, x, stride=1):
    return jax.nn.silu(conv2d(params, x, stride=stride))


def _c2f_forward(params, x, shortcut: bool):
    y = _conv(params["cv1"], x)
    half = y.shape[-1] // 2
    parts = [y[..., :half], y[..., half:]]
    for bottleneck in params["m"]:
        h = _conv(bottleneck["cv2"], _conv(bottleneck["cv1"], parts[-1]))
        parts.append(parts[-1] + h if shortcut else h)
    return _conv(params["cv2"], jnp.concatenate(parts, axis=-1))


def _sppf_forward(params, x):
    y = _conv(params["cv1"], x)
    pools = [y]
    for _ in range(3):
        pools.append(jax.lax.reduce_window(
            pools[-1], -jnp.inf, jax.lax.max, (1, 5, 5, 1), (1, 1, 1, 1),
            "SAME"))
    return _conv(params["cv2"], jnp.concatenate(pools, axis=-1))


def _upsample2(x):
    batch, height, width, channels = x.shape
    x = jnp.broadcast_to(x[:, :, None, :, None, :],
                         (batch, height, 2, width, 2, channels))
    return x.reshape(batch, height * 2, width * 2, channels)


def yolo_forward(params: dict, config: YoloV8Config, images):
    """images (B, 3, H, W) in [0, 1] -> per-scale raw heads
    [(B, Hs, Ws, 4*reg_max + nc)] for strides 8/16/32.

    One transpose to NHWC at entry; every conv runs channels-last on the
    MXU (layers.py conv2d NHWC/HWIO rationale)."""
    height, width = images.shape[2], images.shape[3]
    if height % 32 or width % 32:
        raise ValueError(
            f"yolov8 needs H and W divisible by 32 (5 stride-2 stages + "
            f"exact 2x upsampling), got {height}x{width}; resize or pad "
            f"first (e.g. the ImageResize element)")
    x = images.astype(config.jnp_dtype).transpose(0, 2, 3, 1)
    x = _conv(params["m0"], x, stride=2)                     # P1
    x = _conv(params["m1"], x, stride=2)                     # P2
    x = _c2f_forward(params["m2"], x, shortcut=True)
    x = _conv(params["m3"], x, stride=2)                     # P3
    p3 = _c2f_forward(params["m4"], x, shortcut=True)
    x = _conv(params["m5"], p3, stride=2)                    # P4
    p4 = _c2f_forward(params["m6"], x, shortcut=True)
    x = _conv(params["m7"], p4, stride=2)                    # P5
    x = _c2f_forward(params["m8"], x, shortcut=True)
    p5 = _sppf_forward(params["m9"], x)

    # PAN neck: top-down then bottom-up
    n4 = _c2f_forward(params["m12"],
                      jnp.concatenate([_upsample2(p5), p4], axis=-1),
                      shortcut=False)
    n3 = _c2f_forward(params["m15"],
                      jnp.concatenate([_upsample2(n4), p3], axis=-1),
                      shortcut=False)
    d4 = _c2f_forward(params["m18"],
                      jnp.concatenate(
                          [_conv(params["m16"], n3, stride=2), n4],
                          axis=-1),
                      shortcut=False)
    d5 = _c2f_forward(params["m21"],
                      jnp.concatenate(
                          [_conv(params["m19"], d4, stride=2), p5],
                          axis=-1),
                      shortcut=False)

    outputs = []
    for scale, feature in enumerate((n3, d4, d5)):
        box = feature
        for i, stage in enumerate(params["m22"]["cv2"][scale]):
            box = (_conv(stage, box) if i < 2
                   else conv2d(stage, box))      # last conv: no act
        cls = feature
        for i, stage in enumerate(params["m22"]["cv3"][scale]):
            cls = (_conv(stage, cls) if i < 2
                   else conv2d(stage, cls))
        outputs.append(jnp.concatenate([box, cls], axis=-1))
    return outputs


def _decode_scale(raw, stride: float, config: YoloV8Config):
    """raw (B, H, W, 4*reg_max + nc) -> boxes (B, H*W, 4) xyxy pixels,
    scores (B, H*W), classes (B, H*W).  DFL: softmax over reg_max bins ->
    expected l/t/r/b distance from the cell center, in stride units."""
    batch, height, width, _ = raw.shape
    reg_max = config.reg_max
    box_logits = raw[..., :4 * reg_max].astype(jnp.float32).reshape(
        batch, height, width, 4, reg_max)
    bins = jnp.arange(reg_max, dtype=jnp.float32)
    distances = jnp.einsum(
        "bhwcr,r->bhwc", jax.nn.softmax(box_logits, axis=-1), bins)
    cx = (jnp.arange(width, dtype=jnp.float32) + 0.5)[None, :]
    cy = (jnp.arange(height, dtype=jnp.float32) + 0.5)[:, None]
    x1 = (cx - distances[..., 0]) * stride
    y1 = (cy - distances[..., 1]) * stride
    x2 = (cx + distances[..., 2]) * stride
    y2 = (cy + distances[..., 3]) * stride
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(batch, -1, 4)
    class_probs = jax.nn.sigmoid(
        raw[..., 4 * reg_max:].astype(jnp.float32))
    scores = jnp.max(class_probs, axis=-1).reshape(batch, -1)
    classes = jnp.argmax(class_probs, axis=-1).reshape(batch, -1)
    return boxes, scores, classes


@partial(jax.jit, static_argnames=("config",))
def yolo_detect(params: dict, config: YoloV8Config, images):
    """images (B, 3, H, W) -> the same fixed-size detection contract as
    detector.detect: boxes/scores/classes/valid, Jacobi NMS."""
    all_boxes, all_scores, all_classes = [], [], []
    for raw, stride in zip(yolo_forward(params, config, images),
                           config.strides):
        boxes, scores, classes = _decode_scale(raw, float(stride), config)
        all_boxes.append(boxes)
        all_scores.append(scores)
        all_classes.append(classes)
    boxes = jnp.concatenate(all_boxes, axis=1)
    scores = jnp.concatenate(all_scores, axis=1)
    classes = jnp.concatenate(all_classes, axis=1)
    nms = jax.vmap(lambda b, s, c: non_max_suppression(b, s, c, config))
    final_boxes, final_scores, final_classes, valid = nms(
        boxes, scores, classes)
    return {"boxes": final_boxes, "scores": final_scores,
            "classes": final_classes, "valid": valid}
