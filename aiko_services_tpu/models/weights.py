# Weight ingestion: safetensors read/write + checkpoint -> pytree mapping.
#
# The reference loads real model weights through third-party runtimes
# (reference: src/aiko_services/examples/yolo/yolo.py:51-54 ultralytics .pt,
# speech_elements.py:229 whisperx, elements_llm.py:137-179 Ollama).  This
# framework ingests weights directly: the safetensors container format is
# parsed in pure numpy (8-byte little-endian header length, JSON header of
# {name: {dtype, shape, data_offsets}}, flat data buffer) with zero-copy
# mmap reads -- no torch, no network.
#
#   - read_safetensors / write_safetensors: the container
#   - save_pytree / load_pytree: any model pytree <-> one .safetensors file
#     (dotted flat names)
#   - load_llama_params: HuggingFace Llama-family checkpoint naming ->
#     this framework's stacked-layer TransformerConfig pytree (transposed
#     to (in, out), scan-stacked, cast to config dtype, optionally
#     device_put with mesh shardings as it loads so an 8B model never
#     needs 2x host RAM)

from __future__ import annotations

import contextlib
import json
import mmap
from pathlib import Path

import numpy as np
import ml_dtypes

__all__ = [
    "read_safetensors", "write_safetensors", "SafetensorsFile",
    "save_pytree", "load_pytree", "load_llama_params", "llama_name_map",
    "load_whisper_params", "whisper_layer_map",
]

_DTYPES = {
    "F64": np.float64, "F32": np.float32, "F16": np.float16,
    "BF16": ml_dtypes.bfloat16,
    "I64": np.int64, "I32": np.int32, "I16": np.int16, "I8": np.int8,
    "U8": np.uint8, "BOOL": np.bool_,
}
_DTYPE_NAMES = {np.dtype(dtype): name for name, dtype in _DTYPES.items()}


class SafetensorsFile:
    """mmap-backed lazy reader: tensors materialize on get()."""

    def __init__(self, path):
        self.path = Path(path)
        with open(self.path, "rb") as handle:
            header_len = int.from_bytes(handle.read(8), "little")
            header = json.loads(handle.read(header_len))
            self._data_start = 8 + header_len
        self.metadata = header.pop("__metadata__", {})
        self._entries = header
        self._mmap = None

    def keys(self):
        return list(self._entries.keys())

    def __contains__(self, name):
        return name in self._entries

    def shape(self, name) -> tuple:
        return tuple(self._entries[name]["shape"])

    def get(self, name: str) -> np.ndarray:
        entry = self._entries[name]
        if self._mmap is None:
            handle = open(self.path, "rb")
            self._mmap = mmap.mmap(handle.fileno(), 0,
                                   access=mmap.ACCESS_READ)
        start, end = entry["data_offsets"]
        dtype = _DTYPES[entry["dtype"]]
        buffer = self._mmap[self._data_start + start:self._data_start + end]
        array = np.frombuffer(buffer, dtype=dtype)
        return array.reshape(entry["shape"])

    def close(self):
        if self._mmap is not None:
            self._mmap.close()
            self._mmap = None


def read_safetensors(path, names=None) -> dict:
    """Eagerly load {name: np.ndarray} (names=None loads everything)."""
    reader = SafetensorsFile(path)
    wanted = names if names is not None else reader.keys()
    tensors = {name: np.array(reader.get(name)) for name in wanted}
    reader.close()
    return tensors


def write_safetensors(path, tensors: dict, metadata: dict = None) -> None:
    header: dict = {}
    if metadata:
        header["__metadata__"] = {str(k): str(v)
                                  for k, v in metadata.items()}
    offset = 0
    arrays = {}
    for name, value in tensors.items():
        array = np.ascontiguousarray(np.asarray(value))
        if array.dtype not in _DTYPE_NAMES:
            raise TypeError(f"{name}: unsupported dtype {array.dtype}")
        arrays[name] = array
        header[name] = {
            "dtype": _DTYPE_NAMES[array.dtype],
            "shape": list(array.shape),
            "data_offsets": [offset, offset + array.nbytes],
        }
        offset += array.nbytes
    header_bytes = json.dumps(header).encode("utf-8")
    with open(path, "wb") as handle:
        handle.write(len(header_bytes).to_bytes(8, "little"))
        handle.write(header_bytes)
        for array in arrays.values():
            handle.write(array.tobytes())


# -- pytree <-> safetensors --------------------------------------------------

def save_pytree(path, tree, metadata: dict = None) -> None:
    """Persist any nested-dict pytree of arrays with dotted flat names."""
    flat: dict = {}

    def walk(node, prefix):
        if isinstance(node, dict):
            for key, value in node.items():
                walk(value, f"{prefix}.{key}" if prefix else str(key))
        else:
            flat[prefix] = np.asarray(node)

    walk(tree, "")
    write_safetensors(path, flat, metadata)


def load_pytree(path, dtype=None) -> dict:
    """Inverse of save_pytree; dtype casts every float leaf."""
    tree: dict = {}
    for name, array in read_safetensors(path).items():
        if dtype is not None and np.issubdtype(
                np.asarray(array).dtype, np.floating):
            array = array.astype(dtype)
        node = tree
        parts = name.split(".")
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = array
    return tree


@contextlib.contextmanager
def open_checkpoint(paths):
    """Multi-shard safetensors index shared by the checkpoint loaders:
    yields (index, raw) where index maps tensor name -> reader and
    raw(name) materializes a tensor (KeyError names the missing tensor).
    Readers are closed even when a load fails partway."""
    if isinstance(paths, (str, Path)):
        paths = [paths]
    readers = [SafetensorsFile(path) for path in paths]
    index = {name: reader for reader in readers for name in reader.keys()}

    def raw(name: str) -> np.ndarray:
        reader = index.get(name)
        if reader is None:
            raise KeyError(f"Checkpoint is missing tensor: {name}")
        return reader.get(name)

    try:
        yield index, raw
    finally:
        for reader in readers:
            reader.close()


# -- HuggingFace Llama naming -> framework pytree ---------------------------

def llama_name_map(layer: int) -> dict:
    """HF tensor name -> (pytree path under layers, transpose?) for one
    decoder layer.  HF nn.Linear stores (out, in); this framework stores
    (in, out) so matmuls read x @ w (layers.py:10-12)."""
    prefix = f"model.layers.{layer}."
    return {
        prefix + "input_layernorm.weight": (("attn_norm", "scale"), False),
        prefix + "post_attention_layernorm.weight": (
            ("mlp_norm", "scale"), False),
        prefix + "self_attn.q_proj.weight": (("wq", "w"), True),
        prefix + "self_attn.k_proj.weight": (("wk", "w"), True),
        prefix + "self_attn.v_proj.weight": (("wv", "w"), True),
        prefix + "self_attn.o_proj.weight": (("wo", "w"), True),
        prefix + "mlp.gate_proj.weight": (("w_gate", "w"), True),
        prefix + "mlp.up_proj.weight": (("w_up", "w"), True),
        prefix + "mlp.down_proj.weight": (("w_down", "w"), True),
    }


def load_llama_params(paths, config, mesh=None, specs=None):
    """Build the TransformerConfig pytree from HF Llama-family safetensors
    shard(s).

    paths: one file or a list of shards (names are disjoint across shards).
    With mesh+specs (models.transformer.param_specs), every leaf is
    device_put onto its NamedSharding as it is read, so peak host memory
    stays ~one-tensor-sized above the checkpoint mmap.
    Matches the capability of reference elements_llm.py:137-179 (llama3.1)
    with in-framework weights instead of an external runtime.
    """
    dtype = np.dtype(config.dtype)
    with open_checkpoint(paths) as (index, raw):
        return _load_llama_indexed(index, raw, config, mesh, specs, dtype)


def _load_llama_indexed(index, raw, config, mesh, specs, dtype):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    def fetch(name, transpose=False):
        array = raw(name)
        if transpose:
            array = array.T
        return np.ascontiguousarray(array).astype(dtype, copy=False)

    def spec_for(path_parts):
        if mesh is None or specs is None:
            return None
        node = specs
        for part in path_parts:
            if not isinstance(node, dict):
                return None
            node = node.get(part)
            if node is None:
                return None
        return node if isinstance(node, PartitionSpec) else None

    def place(path_parts, array):
        spec = spec_for(path_parts)
        if spec is None:
            return jnp.asarray(array)
        return jax.device_put(array, NamedSharding(mesh, spec))

    params: dict = {
        "embed": {"w": place(("embed", "w"),
                             fetch("model.embed_tokens.weight"))},
        "norm_out": {"scale": place(("norm_out", "scale"),
                                    fetch("model.norm.weight"))},
    }
    if "lm_head.weight" in index:
        # untied output head (Llama-3-8B+); same (V, D) layout as embed
        params["lm_head"] = {"w": place(("embed", "w"),
                                        fetch("lm_head.weight"))}

    per_layer: list[dict] = []
    for layer in range(config.n_layers):
        mapping = llama_name_map(layer)
        layer_params: dict = {}
        for hf_name, (path_parts, transpose) in mapping.items():
            node = layer_params
            for part in path_parts[:-1]:
                node = node.setdefault(part, {})
            node[path_parts[-1]] = fetch(hf_name, transpose)
        per_layer.append(layer_params)

    stacked_layers = jax.tree_util.tree_map(
        lambda *leaves: np.stack(leaves), *per_layer)
    if mesh is not None and specs is not None:
        stacked_layers = jax.tree_util.tree_map(
            lambda leaf, spec: jax.device_put(
                leaf, NamedSharding(mesh, spec)),
            stacked_layers, specs["layers"])
    else:
        stacked_layers = jax.tree_util.tree_map(jnp.asarray,
                                                stacked_layers)
    params["layers"] = stacked_layers
    return params


# -- HuggingFace Whisper naming -> framework ASR pytree ----------------------

def _attention_map(hf_prefix: str, ours: str) -> dict:
    """One whisper attention block: q/v/out projections carry biases,
    k_proj does not (HF WhisperAttention)."""
    return {
        hf_prefix + "q_proj.weight": ((ours, "wq", "w"), True),
        hf_prefix + "q_proj.bias": ((ours, "wq", "b"), False),
        hf_prefix + "k_proj.weight": ((ours, "wk", "w"), True),
        hf_prefix + "v_proj.weight": ((ours, "wv", "w"), True),
        hf_prefix + "v_proj.bias": ((ours, "wv", "b"), False),
        hf_prefix + "out_proj.weight": ((ours, "wo", "w"), True),
        hf_prefix + "out_proj.bias": ((ours, "wo", "b"), False),
    }


def whisper_layer_map(layer: int, decoder: bool) -> dict:
    """HF tensor name -> (pytree path under enc_layers/dec_layers,
    transpose?) for one whisper transformer layer.  Linear weights are
    (out, in) in HF and (in, out) here; layer norms carry weight+bias
    (models/asr.py pre-LN blocks apply both)."""
    side = "decoder" if decoder else "encoder"
    prefix = f"model.{side}.layers.{layer}."
    mapping = {
        prefix + "fc1.weight": (("mlp", "w1", "w"), True),
        prefix + "fc1.bias": (("mlp", "w1", "b"), False),
        prefix + "fc2.weight": (("mlp", "w2", "w"), True),
        prefix + "fc2.bias": (("mlp", "w2", "b"), False),
        prefix + "final_layer_norm.weight": (("mlp_norm", "scale"), False),
        prefix + "final_layer_norm.bias": (("mlp_norm", "bias"), False),
    }
    if decoder:
        mapping.update(_attention_map(prefix + "self_attn.", "self"))
        mapping.update(_attention_map(prefix + "encoder_attn.", "cross"))
        mapping.update({
            prefix + "self_attn_layer_norm.weight": (
                ("self_norm", "scale"), False),
            prefix + "self_attn_layer_norm.bias": (
                ("self_norm", "bias"), False),
            prefix + "encoder_attn_layer_norm.weight": (
                ("cross_norm", "scale"), False),
            prefix + "encoder_attn_layer_norm.bias": (
                ("cross_norm", "bias"), False),
        })
    else:
        mapping.update(_attention_map(prefix + "self_attn.", "attn"))
        mapping.update({
            prefix + "self_attn_layer_norm.weight": (
                ("attn_norm", "scale"), False),
            prefix + "self_attn_layer_norm.bias": (
                ("attn_norm", "bias"), False),
        })
    return mapping


def load_whisper_params(paths, config) -> dict:
    """Build the AsrConfig pytree from HuggingFace openai/whisper-*
    safetensors naming (capability parity with the reference's pretrained
    WhisperX element, reference speech_elements.py:229-262 -- here the
    checkpoint feeds the in-framework encoder-decoder, models/asr.py).

    Layout notes: HF conv1/conv2 weights are (d_model, in, kernel),
    exactly this framework's _conv1d layout; positional tables are sliced
    to config.max_frames / config.max_text_len (shorter serving windows
    read a prefix of the 30 s table); the output head is tied to
    model.decoder.embed_tokens (HF WhisperForConditionalGeneration ties
    proj_out the same way)."""
    dtype = np.dtype(config.dtype)
    with open_checkpoint(paths) as (_index, raw):
        return _load_whisper_indexed(raw, config, dtype)


def _load_whisper_indexed(raw, config, dtype):
    import jax
    import jax.numpy as jnp

    def fetch(name, transpose=False):
        array = raw(name)
        if transpose:
            array = array.T
        return np.ascontiguousarray(array).astype(dtype, copy=False)

    def build_layers(count, decoder):
        per_layer = []
        for layer in range(count):
            layer_params: dict = {}
            for hf_name, (parts, transpose) in whisper_layer_map(
                    layer, decoder).items():
                node = layer_params
                for part in parts[:-1]:
                    node = node.setdefault(part, {})
                node[parts[-1]] = fetch(hf_name, transpose)
            per_layer.append(layer_params)
        return jax.tree_util.tree_map(
            lambda *leaves: jnp.asarray(np.stack(leaves)), *per_layer)

    params = {
        "conv1": {"w": fetch("model.encoder.conv1.weight"),
                  "b": fetch("model.encoder.conv1.bias")},
        "conv2": {"w": fetch("model.encoder.conv2.weight"),
                  "b": fetch("model.encoder.conv2.bias")},
        "enc_positions": fetch(
            "model.encoder.embed_positions.weight")[:config.max_frames],
        "enc_layers": build_layers(config.enc_layers, decoder=False),
        "enc_norm": {
            "scale": fetch("model.encoder.layer_norm.weight"),
            "bias": fetch("model.encoder.layer_norm.bias")},
        "token_embed": {"w": fetch("model.decoder.embed_tokens.weight")},
        "dec_positions": fetch(
            "model.decoder.embed_positions.weight")[:config.max_text_len],
        "dec_layers": build_layers(config.dec_layers, decoder=True),
        "dec_norm": {
            "scale": fetch("model.decoder.layer_norm.weight"),
            "bias": fetch("model.decoder.layer_norm.bias")},
    }
    return jax.tree_util.tree_map(jnp.asarray, params)
