# Byte-level BPE tokenizer: the real-text path into the LM/ASR elements.
#
# The reference delegates tokenization to external runtimes (reference:
# src/aiko_services/examples/llm/elements_llm.py:137-179 shells out to
# Ollama; speech_elements.py:229-262 to whisperx) so it ships none.  A
# standalone framework needs its own: this is GPT-2-family byte-level BPE --
# every UTF-8 byte maps to a printable unicode "symbol", merges are learned
# over symbol pairs, so ANY string round-trips losslessly with no <unk>.
#
# Three ways to get a tokenizer:
#   - BPETokenizer.from_file("tokenizer.json")  loads the HuggingFace
#     tokenizer.json format (vocab + merges), so real Llama/GPT vocabularies
#     drop in;
#   - train_bpe(texts, vocab_size)  trains from scratch (used to build the
#     committed default asset, zero-egress);
#   - BPETokenizer.default()  loads the committed asset.

from __future__ import annotations

import json
import re
from pathlib import Path

__all__ = ["BPETokenizer", "train_bpe"]

# GPT-2-style pre-tokenization: contractions, words-with-leading-space,
# number runs, punctuation runs, whitespace
_PRETOKEN_PATTERN = re.compile(
    r"'s|'t|'re|'ve|'m|'ll|'d"
    r"| ?[^\W\d_]+| ?\d+| ?(?:[^\s\w]|_)+|\s+(?!\S)|\s+",
    re.UNICODE)

_DEFAULT_SPECIALS = ("<pad>", "<s>", "</s>")
_DEFAULT_ASSET = Path(__file__).parent / "assets" / "bpe_default.json"


def _bytes_to_unicode() -> dict[int, str]:
    """Map every byte 0..255 to a printable unicode char (printable ASCII
    and latin-1 map to themselves; the rest shift into U+0100+)."""
    keep = (list(range(ord("!"), ord("~") + 1))
            + list(range(0xA1, 0xAD)) + list(range(0xAE, 0x100)))
    mapping = {}
    offset = 0
    for byte in range(256):
        if byte in keep:
            mapping[byte] = chr(byte)
        else:
            mapping[byte] = chr(0x100 + offset)
            offset += 1
    return mapping


_BYTE_TO_CHAR = _bytes_to_unicode()
_CHAR_TO_BYTE = {char: byte for byte, char in _BYTE_TO_CHAR.items()}


def _text_to_symbols(text: str) -> list[str]:
    return [_BYTE_TO_CHAR[b] for b in text.encode("utf-8")]


class BPETokenizer:
    def __init__(self, vocab: dict[str, int], merges: list[tuple[str, str]],
                 special_tokens: dict[str, int] | None = None):
        self.vocab = dict(vocab)
        self.merges = [tuple(m) for m in merges]
        self.special_tokens = dict(special_tokens or {})
        self._ranks = {pair: rank for rank, pair in enumerate(self.merges)}
        self._id_to_token = {token_id: token
                             for token, token_id in self.vocab.items()}
        for token, token_id in self.special_tokens.items():
            self._id_to_token.setdefault(token_id, token)
        self._cache: dict[str, list[int]] = {}

    # -- token id properties ------------------------------------------------

    @property
    def vocab_size(self) -> int:
        ids = list(self.vocab.values()) + list(self.special_tokens.values())
        return max(ids) + 1 if ids else 0

    @property
    def pad_id(self) -> int | None:
        return self.special_tokens.get("<pad>")

    @property
    def bos_id(self) -> int | None:
        return self.special_tokens.get("<s>")

    @property
    def eos_id(self) -> int | None:
        return self.special_tokens.get("</s>")

    # -- encode / decode ----------------------------------------------------

    def _bpe(self, symbols: list[str]) -> list[str]:
        """Greedily apply the lowest-rank merge until none applies."""
        while len(symbols) > 1:
            best_rank, best_index = None, None
            for index in range(len(symbols) - 1):
                rank = self._ranks.get((symbols[index], symbols[index + 1]))
                if rank is not None and (best_rank is None
                                         or rank < best_rank):
                    best_rank, best_index = rank, index
            if best_index is None:
                break
            symbols = (symbols[:best_index]
                       + [symbols[best_index] + symbols[best_index + 1]]
                       + symbols[best_index + 2:])
        return symbols

    def _encode_pretoken(self, pretoken: str) -> list[int]:
        cached = self._cache.get(pretoken)
        if cached is not None:
            return cached
        pieces = self._bpe(_text_to_symbols(pretoken))
        ids = []
        for piece in pieces:
            token_id = self.vocab.get(piece)
            if token_id is not None:
                ids.append(token_id)
            else:  # unmerged symbols always exist as single-char tokens
                ids.extend(self.vocab[char] for char in piece)
        if len(self._cache) < 65536:
            self._cache[pretoken] = ids
        return ids

    def encode(self, text: str, bos: bool = False,
               eos: bool = False) -> list[int]:
        ids: list[int] = []
        if bos and self.bos_id is not None:
            ids.append(self.bos_id)
        for pretoken in _PRETOKEN_PATTERN.findall(text):
            ids.extend(self._encode_pretoken(pretoken))
        if eos and self.eos_id is not None:
            ids.append(self.eos_id)
        return ids

    def decode(self, ids) -> str:
        special_ids = set(self.special_tokens.values())
        chars = []
        for token_id in ids:
            token_id = int(token_id)
            if token_id in special_ids:
                continue
            token = self._id_to_token.get(token_id)
            if token is not None:
                chars.append(token)
        data = bytes(_CHAR_TO_BYTE[char]
                     for token in chars for char in token
                     if char in _CHAR_TO_BYTE)
        return data.decode("utf-8", errors="replace")

    # -- persistence --------------------------------------------------------

    def save(self, path) -> None:
        Path(path).write_text(json.dumps({
            "type": "bpe",
            "vocab": self.vocab,
            "merges": [list(m) for m in self.merges],
            "special_tokens": self.special_tokens,
        }, ensure_ascii=False))

    @classmethod
    def from_file(cls, path) -> "BPETokenizer":
        data = json.loads(Path(path).read_text())
        if "model" in data:  # HuggingFace tokenizer.json
            model = data["model"]
            vocab = model["vocab"]
            merges = []
            for merge in model.get("merges", []):
                if isinstance(merge, str):
                    left, right = merge.split(" ", 1)
                else:
                    left, right = merge
                merges.append((left, right))
            specials = {}
            for added in data.get("added_tokens", []):
                content = added.get("content", "")
                if "begin" in content or content in ("<s>",
                                                     "<|begin_of_text|>"):
                    specials["<s>"] = added["id"]
                elif "end" in content or content in ("</s>",
                                                     "<|end_of_text|>"):
                    specials["</s>"] = added["id"]
                elif "pad" in content:
                    specials["<pad>"] = added["id"]
            return cls(vocab, merges, specials)
        return cls(data["vocab"],
                   [tuple(m) for m in data["merges"]],
                   data.get("special_tokens"))

    @classmethod
    def default(cls) -> "BPETokenizer":
        """The committed zero-egress asset (trained by train_bpe on the
        repository's own documentation corpus)."""
        return cls.from_file(_DEFAULT_ASSET)


def train_bpe(texts, vocab_size: int,
              special_tokens=_DEFAULT_SPECIALS) -> BPETokenizer:
    """Classic BPE training over byte-level symbols.

    Specials take ids 0..S-1, the 256 byte symbols follow, then merges
    until vocab_size.  Incremental pair-count maintenance keeps training
    fast enough for multi-thousand-token vocabularies in pure Python.
    """
    word_counts: dict[tuple, int] = {}
    for text in texts:
        for pretoken in _PRETOKEN_PATTERN.findall(text):
            word = tuple(_text_to_symbols(pretoken))
            if word:
                word_counts[word] = word_counts.get(word, 0) + 1

    pair_counts: dict[tuple, int] = {}
    pair_words: dict[tuple, set] = {}

    def count_word(word, count, sign):
        for pair in zip(word, word[1:]):
            pair_counts[pair] = pair_counts.get(pair, 0) + sign * count
            if sign > 0:
                pair_words.setdefault(pair, set()).add(word)
            elif pair_counts.get(pair, 0) <= 0:
                pair_counts.pop(pair, None)
                pair_words.pop(pair, None)

    for word, count in word_counts.items():
        count_word(word, count, +1)

    n_specials = len(special_tokens)
    base_symbols = sorted(set(_BYTE_TO_CHAR.values()))
    vocab = {symbol: n_specials + index
             for index, symbol in enumerate(base_symbols)}
    merges: list[tuple[str, str]] = []

    while len(vocab) + n_specials < vocab_size and pair_counts:
        best_pair = max(pair_counts, key=lambda p: (pair_counts[p], p))
        if pair_counts[best_pair] < 2:
            break
        merges.append(best_pair)
        merged_symbol = best_pair[0] + best_pair[1]
        vocab[merged_symbol] = n_specials + len(vocab)
        affected = list(pair_words.get(best_pair, ()))
        for word in affected:
            count = word_counts.pop(word, 0)
            if count == 0:
                continue
            count_word(word, count, -1)
            new_word = []
            index = 0
            while index < len(word):
                if (index < len(word) - 1
                        and (word[index], word[index + 1]) == best_pair):
                    new_word.append(merged_symbol)
                    index += 2
                else:
                    new_word.append(word[index])
                    index += 1
            new_word = tuple(new_word)
            word_counts[new_word] = word_counts.get(new_word, 0) + count
            count_word(new_word, count, +1)

    specials = {token: index for index, token in enumerate(special_tokens)}
    return BPETokenizer(vocab, merges, specials)
