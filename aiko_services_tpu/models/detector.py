# Single-stage anchor-free object detector (YOLO-family architecture).
#
# Replaces the reference's YoloDetector element (reference:
# src/aiko_services/examples/yolo/yolo.py:51-87: Ultralytics YOLOv8 on
# CUDA emitting an "overlay" dict of objects/rectangles).  Same capability
# contract -- image in, {objects, rectangles} overlay out -- built as pure
# JAX: conv backbone to stride 16, anchor-free head (cx, cy, w, h,
# objectness, classes per cell), box decode and fixed-size NMS all inside
# one jit so the whole detector fuses on the MXU.

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

from .layers import conv2d, init_conv

__all__ = ["DetectorConfig", "init_detector_params", "detect",
           "detector_forward", "decode_boxes", "make_detector_train_step",
           "non_max_suppression"]


@dataclass(frozen=True)
class DetectorConfig:
    n_classes: int = 16
    base_channels: int = 32
    image_size: int = 256          # square input, multiple of 16
    stride: int = 16
    max_detections: int = 32
    score_threshold: float = 0.25
    iou_threshold: float = 0.45
    dtype: str = "bfloat16"

    @property
    def jnp_dtype(self):
        return jnp.dtype(self.dtype)

    @property
    def grid_size(self) -> int:
        return self.image_size // self.stride


def init_detector_params(config: DetectorConfig, key) -> dict:
    keys = jax.random.split(key, 8)
    c = config.base_channels
    dtype = config.jnp_dtype
    return {
        "stem": init_conv(keys[0], 3, c, 3, dtype),            # /2
        "stage1": init_conv(keys[1], c, c * 2, 3, dtype),      # /4
        "block1": init_conv(keys[2], c * 2, c * 2, 3, dtype),
        "stage2": init_conv(keys[3], c * 2, c * 4, 3, dtype),  # /8
        "block2": init_conv(keys[4], c * 4, c * 4, 3, dtype),
        "stage3": init_conv(keys[5], c * 4, c * 8, 3, dtype),  # /16
        "block3": init_conv(keys[6], c * 8, c * 8, 3, dtype),
        "head": init_conv(keys[7], c * 8, 5 + config.n_classes, 1, dtype),
    }


def detector_forward(params: dict, config: DetectorConfig, images):
    """images (B, 3, H, W) in [0, 1] -> raw head (B, 5+C, H/16, W/16).

    Public contract stays channels-first; internally ONE transpose to NHWC
    at entry and one back at exit so every conv runs channels-last on the
    MXU (layers.py conv2d)."""
    x = images.astype(config.jnp_dtype).transpose(0, 2, 3, 1)  # -> NHWC
    x = jax.nn.silu(conv2d(params["stem"], x, stride=2))
    x = jax.nn.silu(conv2d(params["stage1"], x, stride=2))
    x = x + jax.nn.silu(conv2d(params["block1"], x))
    x = jax.nn.silu(conv2d(params["stage2"], x, stride=2))
    x = x + jax.nn.silu(conv2d(params["block2"], x))
    x = jax.nn.silu(conv2d(params["stage3"], x, stride=2))
    x = x + jax.nn.silu(conv2d(params["block3"], x))
    return conv2d(params["head"], x).transpose(0, 3, 1, 2)  # -> NCHW


def decode_boxes(raw, config: DetectorConfig):
    """raw (B, 5+C, G, G) -> boxes (B, G*G, 4) xyxy in pixels,
    scores (B, G*G), classes (B, G*G)."""
    batch, _, grid_h, grid_w = raw.shape
    raw = raw.astype(jnp.float32)
    stride = float(config.stride)
    cell_x = jnp.arange(grid_w, dtype=jnp.float32)[None, :]
    cell_y = jnp.arange(grid_h, dtype=jnp.float32)[:, None]
    center_x = (jax.nn.sigmoid(raw[:, 0]) + cell_x) * stride
    center_y = (jax.nn.sigmoid(raw[:, 1]) + cell_y) * stride
    width = jnp.exp(jnp.clip(raw[:, 2], -8, 8)) * stride
    height = jnp.exp(jnp.clip(raw[:, 3], -8, 8)) * stride
    objectness = jax.nn.sigmoid(raw[:, 4])
    class_probs = jax.nn.sigmoid(raw[:, 5:])           # (B, C, G, G)
    class_ids = jnp.argmax(class_probs, axis=1)
    class_score = jnp.max(class_probs, axis=1)
    scores = (objectness * class_score).reshape(batch, -1)
    boxes = jnp.stack([
        center_x - width / 2, center_y - height / 2,
        center_x + width / 2, center_y + height / 2], axis=-1)
    return (boxes.reshape(batch, -1, 4), scores,
            class_ids.reshape(batch, -1))


def _pairwise_iou(boxes):
    """(N, 4) xyxy -> (N, N) IoU matrix (one batched VPU pass)."""
    lt = jnp.maximum(boxes[:, None, :2], boxes[None, :, :2])
    rb = jnp.minimum(boxes[:, None, 2:], boxes[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    intersection = wh[..., 0] * wh[..., 1]
    areas = ((boxes[:, 2] - boxes[:, 0])
             * (boxes[:, 3] - boxes[:, 1]))
    union = areas[:, None] + areas[None, :] - intersection
    return intersection / jnp.maximum(union, 1e-9)


def non_max_suppression(boxes, scores, classes, config: DetectorConfig):
    """Fixed-size EXACT greedy NMS: (N, 4), (N,), (N,) -> top
    max_detections (boxes, scores, classes, valid), suppressed zeroed.

    TPU-first formulation: instead of N sequential suppress steps (a
    fori_loop whose per-step latency dominates on real devices), greedy
    NMS is solved as the unique fixed point of
        alive[i] = not any(j < i, overlap[i, j], alive[j])
    over the score-sorted candidates: Jacobi iteration on the
    precomputed (T, T) IoU/class/priority mask, each round one parallel
    masked reduction, lax.while_loop until stable.  Convergence takes at
    most the suppression-chain depth (a handful of rounds in practice)
    and the result is exactly sequential greedy NMS.
    """
    deficit = config.max_detections - scores.shape[0]
    if deficit > 0:  # fewer candidates than output slots: zero-pad
        boxes = jnp.concatenate(
            [boxes, jnp.zeros((deficit, 4), boxes.dtype)])
        scores = jnp.concatenate(
            [scores, jnp.zeros((deficit,), scores.dtype)])
        classes = jnp.concatenate(
            [classes, jnp.zeros((deficit,), classes.dtype)])
    top = min(config.max_detections * 4, scores.shape[0])
    top_scores, order = jax.lax.top_k(scores, top)
    top_boxes = boxes[order]
    top_classes = classes[order]

    iou = _pairwise_iou(top_boxes.astype(jnp.float32))
    same_class = top_classes[:, None] == top_classes[None, :]
    earlier = jnp.arange(top)[None, :] < jnp.arange(top)[:, None]
    # dominated[i, j]: higher-priority j suppresses i (when j is alive)
    dominated = (iou > config.iou_threshold) & same_class & earlier

    def unstable(state):
        _, changed = state
        return changed

    def jacobi_round(state):
        alive, _ = state
        new_alive = ~jnp.any(dominated & alive[None, :], axis=1)
        return new_alive, jnp.any(new_alive != alive)

    alive, _ = jax.lax.while_loop(
        unstable, jacobi_round,
        (jnp.ones((top,), bool), jnp.bool_(True)))
    kept = jnp.where(alive, top_scores, 0.0)
    final_scores, final_order = jax.lax.top_k(kept, config.max_detections)
    valid = final_scores > config.score_threshold
    return (top_boxes[final_order] * valid[:, None],
            final_scores * valid,
            top_classes[final_order] * valid,
            valid)


def make_detector_train_step(config: DetectorConfig, optimizer):
    """Returns train_step(params, opt_state, images, targets) ->
    (params, opt_state, loss) for single-object supervision.

    targets: {"box": (B, 4) xyxy pixels, "class": (B,) int32}.  YOLO-
    style cell assignment: the cell containing the box center is the
    positive; loss = BCE objectness over every cell + BCE class + L2 on
    (sigmoid-offset, log-size) at the positive cell.  The trainable
    path makes detection a LEARNED capability (reference parity: the
    reference detects because it loads pretrained ultralytics weights,
    yolo.py:51-54; with no published checkpoints in this image,
    correctness is proven by training -- see
    examples/train_detector_shapes.py)."""
    import optax

    def loss_fn(params, images, boxes, classes):
        raw = detector_forward(params, config, images).astype(jnp.float32)
        batch, _, grid_h, grid_w = raw.shape
        stride = float(config.stride)
        center_x = (boxes[:, 0] + boxes[:, 2]) / 2.0
        center_y = (boxes[:, 1] + boxes[:, 3]) / 2.0
        cell_x = jnp.clip((center_x // stride).astype(jnp.int32),
                          0, grid_w - 1)
        cell_y = jnp.clip((center_y // stride).astype(jnp.int32),
                          0, grid_h - 1)
        rows = jnp.arange(batch)
        positive = raw[rows, :, cell_y, cell_x]        # (B, 5+C)
        # box regression matches decode_boxes' parameterization
        target_dx = center_x / stride - cell_x.astype(jnp.float32)
        target_dy = center_y / stride - cell_y.astype(jnp.float32)
        target_w = jnp.log(jnp.maximum(
            (boxes[:, 2] - boxes[:, 0]) / stride, 1e-3))
        target_h = jnp.log(jnp.maximum(
            (boxes[:, 3] - boxes[:, 1]) / stride, 1e-3))
        box_loss = ((jax.nn.sigmoid(positive[:, 0]) - target_dx) ** 2
                    + (jax.nn.sigmoid(positive[:, 1]) - target_dy) ** 2
                    + (positive[:, 2] - target_w) ** 2
                    + (positive[:, 3] - target_h) ** 2)
        # objectness: positive cell 1, everything else 0
        objectness = raw[:, 4]                         # (B, G, G)
        positive_mask = jnp.zeros_like(objectness).at[
            rows, cell_y, cell_x].set(1.0)
        objectness_loss = jnp.mean(
            optax.sigmoid_binary_cross_entropy(objectness, positive_mask))
        class_logits = positive[:, 5:]
        class_loss = jnp.mean(optax.sigmoid_binary_cross_entropy(
            class_logits, jax.nn.one_hot(classes, config.n_classes)))
        return (jnp.mean(box_loss) + 5.0 * objectness_loss + class_loss)

    @partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, opt_state, images, targets):
        loss, grads = jax.value_and_grad(loss_fn)(
            params, images, targets["box"].astype(jnp.float32),
            targets["class"])
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(
            lambda p, u: p + u.astype(p.dtype), params, updates)
        return params, opt_state, loss

    return train_step


@partial(jax.jit, static_argnames=("config",))
def detect(params: dict, config: DetectorConfig, images):
    """images (B, 3, H, W) -> dict of per-image fixed-size detections:
    boxes (B, D, 4), scores (B, D), classes (B, D), valid (B, D)."""
    raw = detector_forward(params, config, images)
    boxes, scores, classes = decode_boxes(raw, config)
    nms = jax.vmap(lambda b, s, c: non_max_suppression(b, s, c, config))
    final_boxes, final_scores, final_classes, valid = nms(
        boxes, scores, classes)
    return {"boxes": final_boxes, "scores": final_scores,
            "classes": final_classes, "valid": valid}
