# Whisper-style encoder-decoder speech recognizer.
#
# Replaces the reference's PE_WhisperX element (reference:
# src/aiko_services/examples/speech/speech_elements.py:186-262: WhisperX on
# CUDA, tiny..large ladder, 5 s windows).  Same shape of capability --
# log-mel audio in, token text out -- built TPU-first: conv subsampling +
# bidirectional transformer encoder, causal transformer decoder with
# cross-attention, all pure-JAX pytrees jit-compiled with the flash kernel
# for every attention flavor, greedy decode as one jit (scan over steps).
#
# Sharding: encoder/decoder matmuls follow the same megatron TP pattern as
# the LM (param_specs), batch on "data".

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..parallel.attention import flash_attention
from .layers import dense, init_dense, init_norm, layer_norm

__all__ = ["AsrConfig", "init_asr_params", "asr_param_specs",
           "make_asr_train_step", "transcribe_audio", "transcribe_rescore",
           "encode_audio", "decode_tokens", "asr_forward", "transcribe"]


@dataclass(frozen=True)
class AsrConfig:
    n_mels: int = 80
    d_model: int = 384
    enc_layers: int = 4
    dec_layers: int = 4
    n_heads: int = 6
    vocab_size: int = 1024
    max_frames: int = 1500        # mel frames after conv (30 s @ 10 ms hop)
    max_text_len: int = 128
    sot_token: int = 1            # start-of-transcript
    eot_token: int = 2            # end-of-transcript
    dtype: str = "bfloat16"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def jnp_dtype(self):
        return jnp.dtype(self.dtype)


def _sinusoids(length: int, channels: int) -> np.ndarray:
    """Whisper-style fixed sinusoidal positions (length, channels)."""
    log_timescale = np.log(10000.0) / (channels // 2 - 1)
    inv_timescales = np.exp(-log_timescale * np.arange(channels // 2))
    scaled = np.arange(length)[:, None] * inv_timescales[None, :]
    return np.concatenate([np.sin(scaled), np.cos(scaled)],
                          axis=1).astype(np.float32)


def _init_attention(key, d_model: int, dtype) -> dict:
    keys = jax.random.split(key, 4)
    return {
        "wq": init_dense(keys[0], d_model, d_model, dtype),
        "wk": init_dense(keys[1], d_model, d_model, dtype),
        "wv": init_dense(keys[2], d_model, d_model, dtype),
        "wo": init_dense(keys[3], d_model, d_model, dtype),
    }


def _init_mlp(key, d_model: int, dtype) -> dict:
    k1, k2 = jax.random.split(key)
    return {"w1": init_dense(k1, d_model, d_model * 4, dtype),
            "w2": init_dense(k2, d_model * 4, d_model, dtype)}


def _init_enc_layer(key, config: AsrConfig) -> dict:
    k1, k2 = jax.random.split(key)
    d, dtype = config.d_model, config.jnp_dtype
    return {
        "attn_norm": init_norm(d, dtype), "attn": _init_attention(k1, d, dtype),
        "mlp_norm": init_norm(d, dtype), "mlp": _init_mlp(k2, d, dtype),
    }


def _init_dec_layer(key, config: AsrConfig) -> dict:
    k1, k2, k3 = jax.random.split(key, 3)
    d, dtype = config.d_model, config.jnp_dtype
    return {
        "self_norm": init_norm(d, dtype), "self": _init_attention(k1, d, dtype),
        "cross_norm": init_norm(d, dtype), "cross": _init_attention(k2, d, dtype),
        "mlp_norm": init_norm(d, dtype), "mlp": _init_mlp(k3, d, dtype),
    }


def _stack(layer_list):
    return jax.tree_util.tree_map(lambda *leaves: jnp.stack(leaves),
                                  *layer_list)


def init_asr_params(config: AsrConfig, key) -> dict:
    keys = jax.random.split(key, config.enc_layers + config.dec_layers + 4)
    d, dtype = config.d_model, config.jnp_dtype
    conv1 = {"w": (jax.random.normal(
        keys[0], (d, config.n_mels, 3), jnp.float32)
        / np.sqrt(config.n_mels * 3)).astype(dtype),
        "b": jnp.zeros((d,), dtype)}
    conv2 = {"w": (jax.random.normal(
        keys[1], (d, d, 3), jnp.float32) / np.sqrt(d * 3)).astype(dtype),
        "b": jnp.zeros((d,), dtype)}
    enc = [_init_enc_layer(keys[2 + i], config)
           for i in range(config.enc_layers)]
    dec = [_init_dec_layer(keys[2 + config.enc_layers + i], config)
           for i in range(config.dec_layers)]
    return {
        "conv1": conv1,
        "conv2": conv2,
        "enc_positions": jnp.asarray(
            _sinusoids(config.max_frames, d), dtype),
        "enc_layers": _stack(enc),
        "enc_norm": init_norm(d, dtype),
        "token_embed": {"w": (jax.random.normal(
            keys[-2], (config.vocab_size, d), jnp.float32) * 0.02
            ).astype(dtype)},
        "dec_positions": (jax.random.normal(
            keys[-1], (config.max_text_len, d), jnp.float32) * 0.01
            ).astype(dtype),
        "dec_layers": _stack(dec),
        "dec_norm": init_norm(d, dtype),
    }


def asr_param_specs(config: AsrConfig) -> dict:
    attention = {"wq": {"w": P(None, "fsdp", "model")},
                 "wk": {"w": P(None, "fsdp", "model")},
                 "wv": {"w": P(None, "fsdp", "model")},
                 "wo": {"w": P(None, "model", "fsdp")}}
    mlp = {"w1": {"w": P(None, "fsdp", "model")},
           "w2": {"w": P(None, "model", "fsdp")}}
    norm = {"scale": P(None, None)}
    return {
        "conv1": {"w": P(None, None, None), "b": P(None)},
        "conv2": {"w": P(None, None, None), "b": P(None)},
        "enc_positions": P(None, None),
        "enc_layers": {"attn_norm": norm, "attn": attention,
                       "mlp_norm": norm, "mlp": mlp},
        "enc_norm": {"scale": P(None)},
        "token_embed": {"w": P(None, "fsdp")},
        "dec_positions": P(None, None),
        "dec_layers": {"self_norm": norm, "self": attention,
                       "cross_norm": norm, "cross": attention,
                       "mlp_norm": norm, "mlp": mlp},
        "dec_norm": {"scale": P(None)},
    }


# -- model ------------------------------------------------------------------

def _split_heads(x, n_heads: int):
    batch, length, _ = x.shape
    return x.reshape(batch, length, n_heads, -1).transpose(0, 2, 1, 3)


def _merge_heads(x):
    batch, heads, length, dim = x.shape
    return x.transpose(0, 2, 1, 3).reshape(batch, length, heads * dim)


def _attend(attention, x, memory, n_heads: int, causal: bool):
    q = _split_heads(dense(attention["wq"], x), n_heads)
    k = _split_heads(dense(attention["wk"], memory), n_heads)
    v = _split_heads(dense(attention["wv"], memory), n_heads)
    out = flash_attention(q, k, v, causal=causal)
    return dense(attention["wo"], _merge_heads(out))


def _conv1d(params, x, stride: int):
    """x (B, T, C_in), w (C_out, C_in, K) -> (B, T/stride, C_out)."""
    out = jax.lax.conv_general_dilated(
        x, params["w"].astype(x.dtype).transpose(2, 1, 0),
        window_strides=(stride,), padding="SAME",
        dimension_numbers=("NWC", "WIO", "NWC"),
        preferred_element_type=jnp.float32)
    return (out + params["b"].astype(jnp.float32)).astype(x.dtype)


def encode_audio(params: dict, config: AsrConfig, mel):
    """mel (B, n_mels, frames) -> encoder memory (B, frames//2, d)."""
    x = mel.astype(config.jnp_dtype).transpose(0, 2, 1)  # (B, T, mels)
    x = jax.nn.gelu(_conv1d(params["conv1"], x, stride=1))
    x = jax.nn.gelu(_conv1d(params["conv2"], x, stride=2))
    # whisper-style fixed context window: audio beyond max_frames post-conv
    # positions is truncated (callers chunk longer audio -- AudioFraming)
    x = x[:, :config.max_frames]
    x = x + params["enc_positions"][:x.shape[1]]

    def enc_layer(h, layer):
        h = h + _attend(layer["attn"],
                        layer_norm(layer["attn_norm"], h),
                        layer_norm(layer["attn_norm"], h),
                        config.n_heads, causal=False)
        normed = layer_norm(layer["mlp_norm"], h)
        h = h + dense(layer["mlp"]["w2"],
                      jax.nn.gelu(dense(layer["mlp"]["w1"], normed)))
        return h, None

    x, _ = jax.lax.scan(enc_layer, x, params["enc_layers"])
    return layer_norm(params["enc_norm"], x)


def decode_tokens(params: dict, config: AsrConfig, tokens, memory):
    """tokens (B, T) + encoder memory -> logits (B, T, vocab)."""
    h = jnp.take(params["token_embed"]["w"], tokens, axis=0, mode="clip")
    h = h + params["dec_positions"][:tokens.shape[1]]

    def dec_layer(h, layer):
        h = h + _attend(layer["self"],
                        layer_norm(layer["self_norm"], h),
                        layer_norm(layer["self_norm"], h),
                        config.n_heads, causal=True)
        h = h + _attend(layer["cross"],
                        layer_norm(layer["cross_norm"], h), memory,
                        config.n_heads, causal=False)
        normed = layer_norm(layer["mlp_norm"], h)
        h = h + dense(layer["mlp"]["w2"],
                      jax.nn.gelu(dense(layer["mlp"]["w1"], normed)))
        return h, None

    h, _ = jax.lax.scan(dec_layer, h, params["dec_layers"])
    h = layer_norm(params["dec_norm"], h)
    return jnp.einsum("btd,vd->btv", h.astype(jnp.float32),
                      params["token_embed"]["w"].astype(jnp.float32))


def asr_forward(params: dict, config: AsrConfig, mel, tokens):
    """Teacher-forced forward (training/scoring): logits (B, T, vocab)."""
    return decode_tokens(params, config, tokens,
                         encode_audio(params, config, mel))


def _cross_kv(params: dict, config: AsrConfig, memory):
    """Cross-attention K/V for every decoder layer, computed ONCE per
    transcription -- the rescore loop recomputed them at every step.
    Returns (L, B, H, M, hd) stacked pairs."""
    def layer_kv(_, layer):
        k = _split_heads(dense(layer["cross"]["wk"], memory),
                         config.n_heads)
        v = _split_heads(dense(layer["cross"]["wv"], memory),
                         config.n_heads)
        return None, (k, v)

    _, (ks, vs) = jax.lax.scan(layer_kv, None, params["dec_layers"])
    return ks, vs


def _attend_cached(q, k, v):
    """(B, H, 1, hd) query over cached keys/values, f32 softmax."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    att = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", att, v)


def _decode_step(params: dict, config: AsrConfig, token, index,
                 self_k, self_v, cross_k, cross_v):
    """One incremental decode step: token (B, 1) consumed at buffer
    position `index` (traced int32).  Self K/V caches (L, B, H, T, hd)
    update in place at `index`; attention masks positions > index.
    Returns (next-position logits (B, vocab) f32, self_k, self_v)."""
    h = jnp.take(params["token_embed"]["w"], token, axis=0, mode="clip")
    h = h + jax.lax.dynamic_slice(
        params["dec_positions"], (index, 0),
        (1, params["dec_positions"].shape[1]))[None, 0:1]
    max_tokens = self_k.shape[3]
    mask = (jnp.arange(max_tokens) > index)[None, None, None, :]

    def dec_layer(h, xs):
        layer, sk, sv, ck, cv = xs
        x = layer_norm(layer["self_norm"], h)
        q = _split_heads(dense(layer["self"]["wq"], x), config.n_heads)
        k_new = _split_heads(dense(layer["self"]["wk"], x), config.n_heads)
        v_new = _split_heads(dense(layer["self"]["wv"], x), config.n_heads)
        sk = jax.lax.dynamic_update_slice(sk, k_new, (0, 0, index, 0))
        sv = jax.lax.dynamic_update_slice(sv, v_new, (0, 0, index, 0))
        scale = 1.0 / np.sqrt(q.shape[-1])
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, sk,
                            preferred_element_type=jnp.float32) * scale
        scores = jnp.where(mask, -1e30, scores)
        att = jax.nn.softmax(scores, axis=-1).astype(sv.dtype)
        self_out = jnp.einsum("bhqk,bhkd->bhqd", att, sv)
        h = h + dense(layer["self"]["wo"], _merge_heads(self_out))
        xc = layer_norm(layer["cross_norm"], h)
        qc = _split_heads(dense(layer["cross"]["wq"], xc), config.n_heads)
        h = h + dense(layer["cross"]["wo"],
                      _merge_heads(_attend_cached(qc, ck, cv)))
        normed = layer_norm(layer["mlp_norm"], h)
        h = h + dense(layer["mlp"]["w2"],
                      jax.nn.gelu(dense(layer["mlp"]["w1"], normed)))
        return h, (sk, sv)

    (h), (self_k, self_v) = jax.lax.scan(
        dec_layer, h,
        (params["dec_layers"], self_k, self_v, cross_k, cross_v))
    h = layer_norm(params["dec_norm"], h)
    logits = jnp.einsum("btd,vd->btv", h.astype(jnp.float32),
                        params["token_embed"]["w"].astype(jnp.float32))
    return logits[:, 0], self_k, self_v


@partial(jax.jit, static_argnames=("config", "max_tokens"))
def transcribe_rescore(params: dict, config: AsrConfig, mel,
                       max_tokens: int = 32):
    """Greedy transcription by FULL re-score per step (no KV cache): the
    simple quadratic loop, kept as the numerics oracle for the
    incremental path (and for tiny configs where cache setup dominates)."""
    memory = encode_audio(params, config, mel)
    batch = mel.shape[0]
    tokens = jnp.full((batch, max_tokens + 1), config.eot_token, jnp.int32)
    tokens = tokens.at[:, 0].set(config.sot_token)
    finished = jnp.zeros((batch,), bool)

    def step(carry, index):
        tokens, finished = carry
        logits = decode_tokens(params, config, tokens[:, :-1], memory)
        next_token = jnp.argmax(logits[:, index], axis=-1).astype(jnp.int32)
        next_token = jnp.where(finished, config.eot_token, next_token)
        tokens = tokens.at[:, index + 1].set(next_token)
        finished = jnp.logical_or(finished,
                                  next_token == config.eot_token)
        return (tokens, finished), None

    (tokens, _), _ = jax.lax.scan(
        step, (tokens, finished), jnp.arange(max_tokens))
    return tokens[:, 1:]


def make_asr_train_step(config: AsrConfig, optimizer):
    """Returns train_step(params, opt_state, mel, tokens) -> (params,
    opt_state, loss): teacher-forced next-token cross-entropy (same
    convention as transformer.make_train_step).  The trainable path
    makes transcription a LEARNED capability, not a shape: fit
    mel -> token targets and transcribe() decodes them greedily --
    functional parity with the reference's pretrained WhisperX seat
    (speech_elements.py:229-262) proven by training to correctness on
    synthetic data (no published checkpoints exist in this image)."""

    def loss_fn(params, mel, tokens):
        logits = asr_forward(params, config, mel, tokens[:, :-1])
        targets = tokens[:, 1:]
        log_probs = jax.nn.log_softmax(logits, axis=-1)
        taken = jnp.take_along_axis(
            log_probs, targets[..., None], axis=-1, mode="clip")[..., 0]
        return -jnp.mean(taken)

    @partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, opt_state, mel, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, mel, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(
            lambda p, u: p + u.astype(p.dtype), params, updates)
        return params, opt_state, loss

    return train_step


@partial(jax.jit, static_argnames=("config", "max_tokens"))
def transcribe_audio(params: dict, config: AsrConfig, audio,
                     max_tokens: int = 32):
    """audio (B, samples) 16 kHz f32 -> (B, max_tokens) token ids: the
    log-mel frontend AND the full transcription as ONE device program.
    On tunneled devices each dispatch costs ~2-10 ms, so the serving
    path must never split frontend and model into separate launches."""
    from ..ops import log_mel_spectrogram
    mel = log_mel_spectrogram(audio, n_mels=config.n_mels)
    return transcribe(params, config, mel, max_tokens=max_tokens)


@partial(jax.jit, static_argnames=("config", "max_tokens"))
def transcribe(params: dict, config: AsrConfig, mel, max_tokens: int = 32):
    """Greedy transcription: mel (B, n_mels, frames) -> (B, max_tokens)
    token ids (eot-padded).  One jit: encoder once, cross K/V cached
    once, then an INCREMENTAL KV-cached decode loop -- one position
    through the decoder per step instead of the full buffer (the rescore
    loop cost max_tokens x the whole decoder + logits head; this is
    ~max_tokens x cheaper and the bench-critical ASR path)."""
    memory = encode_audio(params, config, mel)
    cross_k, cross_v = _cross_kv(params, config, memory)
    batch = mel.shape[0]
    n_heads = config.n_heads
    head_dim = config.d_model // n_heads
    shape = (config.dec_layers, batch, n_heads, max_tokens, head_dim)
    self_k = jnp.zeros(shape, config.jnp_dtype)
    self_v = jnp.zeros(shape, config.jnp_dtype)
    tokens = jnp.full((batch, max_tokens + 1), config.eot_token, jnp.int32)
    tokens = tokens.at[:, 0].set(config.sot_token)
    finished = jnp.zeros((batch,), bool)

    def step(carry, index):
        tokens, finished, self_k, self_v = carry
        token = jax.lax.dynamic_slice(tokens, (0, index), (batch, 1))
        logits, self_k, self_v = _decode_step(
            params, config, token, index, self_k, self_v,
            cross_k, cross_v)
        next_token = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        next_token = jnp.where(finished, config.eot_token, next_token)
        tokens = tokens.at[:, index + 1].set(next_token)
        finished = jnp.logical_or(finished,
                                  next_token == config.eot_token)
        return (tokens, finished, self_k, self_v), None

    (tokens, _, _, _), _ = jax.lax.scan(
        step, (tokens, finished, self_k, self_v), jnp.arange(max_tokens))
    return tokens[:, 1:]
