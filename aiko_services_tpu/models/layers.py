# Shared neural-net layers as pure functions over parameter pytrees.
#
# No reference counterpart: the reference delegates all model math to
# third-party torch libraries (reference: src/aiko_services/examples/
# yolo/yolo.py:51, speech/speech_elements.py:233).  Here models are plain
# JAX -- params are dicts of jax.Array, layers are pure functions, so the
# whole model jits, shards with NamedSharding, and differentiates without
# framework machinery.
#
# Conventions: weights stored (in_features, out_features) so forward is
# x @ w; attention heads live in the last-but-one axis (B, H, L, D);
# everything computes in the dtype of the incoming activations with f32
# accumulation for matmuls and reductions.

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "dense", "rms_norm", "layer_norm", "rotary_embedding", "apply_rotary",
    "swiglu", "init_dense", "init_norm", "repeat_kv", "conv2d", "init_conv",
]


def init_dense(key, in_features: int, out_features: int,
               dtype=jnp.float32) -> dict:
    scale = 1.0 / np.sqrt(in_features)
    return {"w": (jax.random.normal(key, (in_features, out_features),
                                    jnp.float32) * scale).astype(dtype)}


def dense(params: dict, x):
    w = params["w"]
    if w.dtype == jnp.int8:
        # weight-only int8 (transformer.quantize_weights_int8): weights
        # stream from HBM as 8-bit codes -- the convert fuses into the
        # dot's operand load -- and the per-output-channel scale folds
        # in AFTER the f32 accumulation (scales factor out of the
        # contraction), so the matmul itself never sees a dequantized
        # copy in memory
        out = jnp.einsum("...i,io->...o", x, w.astype(x.dtype),
                         preferred_element_type=jnp.float32)
        out = out * params["w_scale"].astype(jnp.float32)
    else:
        out = jnp.einsum("...i,io->...o", x, w,
                         preferred_element_type=jnp.float32)
    if "b" in params:
        out = out + params["b"]
    return out.astype(x.dtype)


def init_norm(features: int, dtype=jnp.float32) -> dict:
    return {"scale": jnp.ones((features,), dtype)}


def rms_norm(params: dict, x, eps: float = 1e-6):
    x_f32 = x.astype(jnp.float32)
    rms = jax.lax.rsqrt(jnp.mean(x_f32 * x_f32, axis=-1, keepdims=True)
                        + eps)
    return (x_f32 * rms).astype(x.dtype) * params["scale"]


def layer_norm(params: dict, x, eps: float = 1e-5):
    x_f32 = x.astype(jnp.float32)
    mean = jnp.mean(x_f32, axis=-1, keepdims=True)
    var = jnp.var(x_f32, axis=-1, keepdims=True)
    out = (x_f32 - mean) * jax.lax.rsqrt(var + eps)
    out = out.astype(x.dtype) * params["scale"]
    if "bias" in params:
        out = out + params["bias"]
    return out


def rotary_embedding(positions, head_dim: int, theta: float = 10000.0):
    """positions (..., L) int -> cos/sin tables (..., L, head_dim//2)."""
    frequencies = 1.0 / (theta ** (
        jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    angles = positions[..., None].astype(jnp.float32) * frequencies
    return jnp.cos(angles), jnp.sin(angles)


def apply_rotary(x, cos, sin):
    """x (B, H, L, D); cos/sin (L, D//2) or broadcastable (B, 1, L, D//2)."""
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    rotated = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return rotated.astype(x.dtype)


def swiglu(gate_params: dict, up_params: dict, down_params: dict, x):
    return dense(down_params,
                 jax.nn.silu(dense(gate_params, x)) * dense(up_params, x))


def repeat_kv(x, repeats: int):
    """Expand grouped KV heads to full head count: (B, Hkv, L, D) ->
    (B, Hkv*repeats, L, D)."""
    if repeats == 1:
        return x
    batch, kv_heads, length, dim = x.shape
    x = jnp.broadcast_to(x[:, :, None],
                         (batch, kv_heads, repeats, length, dim))
    return x.reshape(batch, kv_heads * repeats, length, dim)


def init_conv(key, in_channels: int, out_channels: int, kernel: int,
              dtype=jnp.float32, bias: bool = True) -> dict:
    fan_in = in_channels * kernel * kernel
    params = {"w": (jax.random.normal(
        key, (kernel, kernel, in_channels, out_channels), jnp.float32)
        / np.sqrt(fan_in)).astype(dtype)}
    if bias:
        params["b"] = jnp.zeros((out_channels,), dtype)
    return params


def conv2d(params: dict, x, stride: int = 1, padding="SAME"):
    """x (B, H, W, C), w (kh, kw, I, O) -> (B, H', W', O).

    NHWC/HWIO: channels ride the TPU lane dimension so XLA maps the conv
    onto the MXU directly (NCHW forces layout shuffles that collapse conv
    throughput ~100x on TPU -- measured in bench.py round 2)."""
    out = jax.lax.conv_general_dilated(
        x, params["w"].astype(x.dtype),
        window_strides=(stride, stride), padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32)
    if "b" in params:
        out = out + params["b"].astype(jnp.float32)
    return out.astype(x.dtype)
