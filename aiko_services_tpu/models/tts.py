# Text-to-speech: the framework's TTS seat, filling the reference's Coqui
# TTS element (reference: src/aiko_services/examples/speech/
# speech_elements.py:109-146 -- PE_TextToSpeech wrapping TTS
# "tts_models/en/vctk/vits" on CUDA, 594 MB VRAM).
#
# TPU-first design -- everything from characters to waveform is ONE jit:
#   chars (B, L) -> embedding -> static-duration upsample (frames_per_char,
#   jit-friendly static shapes; no autoregressive loop) -> 1D conv decoder
#   -> mel (B, n_mels, T) -> mel-to-linear (precomputed filterbank
#   pseudo-inverse, an MXU matmul) -> Griffin-Lim phase recovery
#   (lax.fori_loop of STFT/ISTFT round-trips on jnp.fft) -> waveform.
#
# Weights are random-initialized at the element level (same policy as the
# LM/ASR/detector families: real checkpoints load through
# models/weights.py load_pytree); the synthesis chain, shapes, and the
# vocoder are the production path.

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.audio import mel_filterbank
from .layers import dense, init_dense

__all__ = [
    "TTSConfig", "init_tts_params", "synthesize_mel", "griffin_lim",
    "synthesize", "encode_chars", "make_tts_train_step",
]


@dataclass(frozen=True)
class TTSConfig:
    vocab_size: int = 256          # byte-level characters
    d_model: int = 256
    n_conv_layers: int = 4
    kernel_size: int = 5
    n_mels: int = 80
    sample_rate: int = 16000
    n_fft: int = 400
    hop: int = 200                 # 12.5 ms
    frames_per_char: int = 6       # ~75 ms per character
    griffin_lim_iters: int = 30
    dtype: str = "float32"

    @property
    def jnp_dtype(self):
        return jnp.dtype(self.dtype)


def encode_chars(text: str, max_len: int | None = None) -> np.ndarray:
    """Byte-level character ids (1, L) int32; optionally padded/truncated
    to max_len with zeros (id 0 = padding/silence)."""
    ids = np.frombuffer(text.encode("utf-8", "replace"),
                        np.uint8).astype(np.int32)
    if max_len is not None:
        ids = ids[:max_len]
        ids = np.pad(ids, (0, max_len - len(ids)))
    return ids[None]


def init_tts_params(config: TTSConfig, key) -> dict:
    """Conv layers are STACKED on a leading axis (like every model
    family here) so save_pytree/load_pytree/shard_pytree apply
    unchanged; synthesize_mel runs them with lax.scan."""
    keys = jax.random.split(key, config.n_conv_layers + 3)
    dtype = config.jnp_dtype
    scale = 1.0 / np.sqrt(config.d_model * config.kernel_size)
    conv_w = jnp.stack([
        (jax.random.normal(
            keys[2 + index],
            (config.kernel_size, config.d_model, config.d_model),
            jnp.float32) * scale).astype(dtype)
        for index in range(config.n_conv_layers)])
    return {
        "embed": {"w": (jax.random.normal(
            keys[0], (config.vocab_size, config.d_model), jnp.float32)
            * 0.02).astype(dtype)},
        "convs": {"w": conv_w,
                  "b": jnp.zeros(
                      (config.n_conv_layers, config.d_model), dtype)},
        "mel_out": init_dense(keys[1], config.d_model, config.n_mels,
                              dtype),
    }


def synthesize_mel(params: dict, config: TTSConfig, chars) -> jnp.ndarray:
    """chars (B, L) int32 -> mel (B, n_mels, L * frames_per_char).

    Static-duration upsampling keeps every shape known at trace time (no
    data-dependent durations -> no recompiles, scan-free decode)."""
    h = jnp.take(params["embed"]["w"], chars, axis=0, mode="clip")   # (B, L, D)
    h = jnp.repeat(h, config.frames_per_char, axis=1)   # (B, T, D)
    # position-within-char phase feature lets the convs shape transients
    phase = jnp.tile(
        jnp.arange(config.frames_per_char, dtype=jnp.float32)
        / config.frames_per_char, chars.shape[1])
    h = h + jnp.sin(2 * jnp.pi * phase)[None, :, None].astype(h.dtype)

    def conv_block(h, conv):
        y = jax.lax.conv_general_dilated(
            h, conv["w"], window_strides=(1,), padding="SAME",
            dimension_numbers=("NWC", "WIO", "NWC"))
        return h + jnp.tanh(y + conv["b"]), None        # residual

    h, _ = jax.lax.scan(conv_block, h, params["convs"])
    mel = dense(params["mel_out"], h)                   # (B, T, n_mels)
    return mel.transpose(0, 2, 1)                       # (B, n_mels, T)


def _frame(signal, n_fft: int, hop: int):
    """(B, S) -> (B, frames, n_fft) strided windows.  When hop divides
    n_fft (the config default: 400/100) the frames assemble from STATIC
    slices of hop-sized blocks -- TPU gathers are serial and this
    framing sits inside the Griffin-Lim loop; the gather fallback
    covers exotic hop settings."""
    frames = 1 + (signal.shape[-1] - n_fft) // hop
    if n_fft % hop == 0:
        ratio = n_fft // hop
        usable = frames + ratio - 1          # hop-blocks covering frames
        blocks = signal[:, :usable * hop].reshape(
            signal.shape[0], usable, hop)
        return jnp.concatenate(
            [blocks[:, s:s + frames] for s in range(ratio)],
            axis=2)
    index = (jnp.arange(frames)[:, None] * hop
             + jnp.arange(n_fft)[None, :])
    return signal[:, index]


def _dft_matrices(n_fft: int):
    """rfft as a pair of real matmuls: the shared cos/-sin bases
    (ops/audio.py dft_basis -- same math as the ASR conv-STFT kernel).
    TPU-first: a 400x201 matmul rides the MXU while XLA's complex FFT
    at this size runs on the scalar/vector pipeline -- the Griffin-Lim
    loop is 2 transforms x 30 iterations deep, so the transform IS the
    workload (bench note: tts section, BENCH_NOTES.md)."""
    from ..ops.audio import dft_basis
    cos_m, sin_m = dft_basis(n_fft)
    return jnp.asarray(cos_m), jnp.asarray(sin_m)


def _irfft_weights(n_fft: int):
    """Hermitian bin weights for the real inverse: DC and Nyquist count
    once, interior bins twice (their conjugate halves are implicit)."""
    bins = n_fft // 2 + 1
    weights = np.full((bins,), 2.0, np.float32)
    weights[0] = 1.0
    if n_fft % 2 == 0:
        weights[-1] = 1.0
    return jnp.asarray(weights / n_fft, jnp.float32)


def _stft_ri(signal, n_fft: int, hop: int, window, cos_m, sin_m):
    """(B, S) -> (real, imag) each (B, frames, bins), via MXU matmuls.
    Precision.HIGHEST: the default TPU matmul precision loses ~3
    decimal digits on the DFT's cancellation-heavy sums (measured in
    ops/audio.py), and Griffin-Lim feeds each iteration's error into
    the next."""
    frames = _frame(signal, n_fft, hop) * window
    highest = jax.lax.Precision.HIGHEST
    return (jnp.matmul(frames, cos_m, precision=highest),
            jnp.matmul(frames, sin_m, precision=highest))


def _window_norm(window_np: np.ndarray, hop: int, n_frames: int,
                 length: int):
    """Overlap-add normalization for the GIVEN window: depends only on
    the window and the shapes, so it is a numpy-built constant, never
    device work."""
    n_fft = window_np.shape[0]
    window_sq = np.asarray(window_np, np.float32) ** 2
    total = np.zeros((length,), np.float32)
    for frame in range(n_frames):
        total[frame * hop:frame * hop + n_fft] += window_sq
    return jnp.asarray(np.maximum(total, 1e-8))


def _overlap_add(frames, n_fft: int, hop: int, length: int):
    """(B, F, n_fft) windowed frames -> (B, length) sum at hop offsets.
    When hop divides n_fft this is `ratio` STATIC-slice adds on a
    hop-blocked accumulator (the scatter fallback is the single
    slowest op a TPU can run, and it sat inside the Griffin-Lim
    loop: 30 x ~4 ms/iteration was the whole TTS budget)."""
    batch, n_frames, _ = frames.shape
    if n_fft % hop == 0:
        ratio = n_fft // hop
        blocks = frames.reshape(batch, n_frames, ratio, hop)
        acc = jnp.zeros((batch, n_frames + ratio - 1, hop),
                        frames.dtype)
        for s in range(ratio):
            acc = acc.at[:, s:s + n_frames].add(blocks[:, :, s])
        return acc.reshape(batch, -1)[:, :length]
    signal = jnp.zeros((batch, length), frames.dtype)
    positions = (jnp.arange(n_frames)[:, None] * hop
                 + jnp.arange(n_fft)[None, :])       # (frames, n_fft)
    return signal.at[:, positions.reshape(-1)].add(
        frames.reshape(batch, -1))


def _istft_ri(real, imag, n_fft: int, hop: int, window, length: int,
              cos_m, sin_m, weights, norm):
    """Inverse of _stft_ri + windowed overlap-add against the
    precomputed window normalization (`norm` from _window_norm -- it is
    loop-invariant, built once per griffin_lim call, and MUST match the
    `window` actually applied here).  x[n] = sum_k w_k (real_k cos -
    imag_k sin(angle)) -- two HIGHEST-precision matmuls against the
    transposed bases (see _stft_ri)."""
    highest = jax.lax.Precision.HIGHEST
    frames = (jnp.matmul(real * weights, cos_m.T, precision=highest)
              + jnp.matmul(imag * weights, sin_m.T,
                           precision=highest)) * window
    signal = _overlap_add(frames, n_fft, hop, length)
    return signal / norm[None, :]


def griffin_lim(magnitude, config: TTSConfig) -> jnp.ndarray:
    """Phase recovery: magnitude (B, n_fft//2+1, T) -> waveform (B, S).

    Classic Griffin-Lim as a lax.fori_loop of ISTFT/STFT round-trips --
    fully on-device, jit-compiled with the synthesis net.  The
    transforms run as real DFT matmuls (MXU) rather than complex FFTs,
    and the loop carries only the phase ANGLE (real), so no complex
    dtype exists anywhere (speedup vs the jnp.fft formulation measured
    in BENCH_NOTES.md, tts section)."""
    n_fft, hop = config.n_fft, config.hop
    magnitude = magnitude.transpose(0, 2, 1)            # (B, T, bins)
    frames = magnitude.shape[1]
    length = (frames - 1) * hop + n_fft
    window_np = np.hanning(n_fft).astype(np.float32)
    window = jnp.asarray(window_np)
    cos_m, sin_m = _dft_matrices(n_fft)
    weights = _irfft_weights(n_fft)
    norm = _window_norm(window_np, hop, frames, length)
    angles = jnp.zeros_like(magnitude)                  # deterministic

    def body(_, angles):
        signal = _istft_ri(magnitude * jnp.cos(angles),
                           magnitude * jnp.sin(angles),
                           n_fft, hop, window, length,
                           cos_m, sin_m, weights, norm)
        real, imag = _stft_ri(signal, n_fft, hop, window, cos_m, sin_m)
        return jnp.arctan2(imag, real)

    angles = jax.lax.fori_loop(0, config.griffin_lim_iters, body, angles)
    return _istft_ri(magnitude * jnp.cos(angles),
                     magnitude * jnp.sin(angles),
                     n_fft, hop, window, length, cos_m, sin_m, weights,
                     norm)


def make_tts_train_step(config: TTSConfig, optimizer):
    """Returns train_step(params, opt_state, chars, target_mel) ->
    (params, opt_state, loss): mel-regression MSE through the synthesis
    net (same convention as transformer.make_train_step).  The trainable
    path makes TTS a capability, not a shape: fit character->spectral
    targets (phoneme templates, or real aligned mel data) and
    synthesize() renders them through the same Griffin-Lim vocoder
    (reference parity: the Coqui element produces learned speech,
    speech_elements.py:109-146)."""

    def loss_fn(params, chars, target_mel):
        mel = synthesize_mel(params, config, chars)
        return jnp.mean(
            (mel.astype(jnp.float32) - target_mel.astype(jnp.float32))
            ** 2)

    @partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, opt_state, chars, target_mel):
        loss, grads = jax.value_and_grad(loss_fn)(params, chars,
                                                  target_mel)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(
            lambda p, u: p + u.astype(p.dtype), params, updates)
        return params, opt_state, loss

    return train_step


@partial(jax.jit, static_argnames=("config",))
def synthesize(params: dict, config: TTSConfig, chars) -> jnp.ndarray:
    """chars (B, L) int32 -> waveform (B, S) float32 in [-1, 1]: the full
    text->speech chain as ONE jit (filterbank pinv is a trace-time
    constant)."""
    mel = synthesize_mel(params, config, chars)
    filterbank = mel_filterbank(
        sample_rate=config.sample_rate, n_fft=config.n_fft,
        n_mels=config.n_mels)                            # (n_mels, bins)
    inverse = jnp.asarray(np.linalg.pinv(np.asarray(filterbank)),
                          jnp.float32)                   # (bins, n_mels)
    energy = jnp.exp(mel.astype(jnp.float32))            # log-mel -> mel
    linear = jnp.maximum(
        jnp.einsum("bmt,fm->bft", energy, inverse), 0.0)
    magnitude = jnp.sqrt(linear + 1e-8)
    waveform = griffin_lim(magnitude, config)
    peak = jnp.max(jnp.abs(waveform), axis=-1, keepdims=True)
    return (waveform / jnp.maximum(peak, 1e-6)).astype(jnp.float32)
